"""Project-level jit-reachability: which functions run under a JAX trace.

A function is a **jit root** when it is

  * decorated with a jit-family transform (`@jax.jit`, `@partial(jax.jit,
    static_argnames=...)`, `@jax.vmap`, ...), or
  * passed by name to a jit-family call anywhere in the project
    (`jax.jit(step)`, `lax.scan(body, ...)`, `jax.vmap(one)(...)`,
    `shard_map(fwd, mesh=...)`).

The **jit-reachable set** is the closure of the roots over the project
call graph: anything a root calls (within the package) also executes
under the trace.  Call edges are resolved conservatively-precise rather
than by bare-name matching across the whole package:

  * bare-name calls resolve to defs in the SAME module (including
    enclosing/nested scopes), or to names imported `from <module> import
    <fn>` (exact cross-module match via the alias map);
  * dotted calls (`scheduling.mwis_activate(...)`) resolve through the
    import-alias map to `<package>.<module>.<fn>` exact matches;
  * `self.method(...)` resolves within the enclosing class only.

Unresolvable calls (getattr dances, callables passed as values) simply
add no edge — JX001 is a tripwire for the common spelling of the bug,
not a soundness proof.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from multihop_offload_tpu.analysis.modinfo import ModuleCtx

# canonical names whose first callable argument (or decorated function)
# becomes traced code.  shard_map is matched by suffix: the repo routes it
# through parallel.compat, so its canonical name is package-internal.
JIT_FAMILY = {
    "jax.jit", "jax.pjit", "jax.pmap", "jax.vmap", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map", "jax.lax.associative_scan",
    "jax.experimental.pjit.pjit",
}


def is_jit_family(canon: Optional[str]) -> bool:
    if canon is None:
        return False
    return canon in JIT_FAMILY or canon == "shard_map" \
        or canon.endswith(".shard_map")


def _func_key(mod: ModuleCtx, qualname: str) -> Tuple[str, str]:
    return (mod.path, qualname)


class ProjectIndex:
    """Function index + call graph + jit-reachable set over many modules."""

    def __init__(self, modules: Iterable[ModuleCtx]):
        self.modules: List[ModuleCtx] = list(modules)
        # module path -> dotted module name (for cross-module resolution)
        self._modname: Dict[str, str] = {}
        for m in self.modules:
            parts = list(m.rel_parts)
            if parts and parts[-1].endswith(".py"):
                parts[-1] = parts[-1][:-3]
            if parts and parts[-1] == "__init__":
                parts = parts[:-1]
            self._modname[m.path] = ".".join(parts)
        # dotted "modname.funcname" -> set of (path, qualname); tail names
        # only (methods register under their bare name within the class)
        self._by_dotted: Dict[str, Set[Tuple[str, str]]] = {}
        for m in self.modules:
            modname = self._modname[m.path]
            for qn, fi in m.functions.items():
                tail = qn.rsplit(".", 1)[-1]
                self._by_dotted.setdefault(
                    f"{modname}.{tail}", set()).add(_func_key(m, qn))
        self.reachable: Set[Tuple[str, str]] = set()
        self._compute()

    # ---- resolution helpers ------------------------------------------------

    def _resolve_local(self, mod: ModuleCtx, name: str,
                       from_qualname: str) -> List[Tuple[str, str]]:
        """A bare name used inside `from_qualname`: nearest enclosing-scope
        def first (nested helpers), then any same-module def, then an
        exact `from x import name` target."""
        prefix = from_qualname
        while prefix:
            qn = f"{prefix}.{name}"
            if qn in mod.functions:
                return [_func_key(mod, qn)]
            prefix = prefix.rsplit(".", 1)[0] if "." in prefix else ""
        if name in mod.functions:
            return [_func_key(mod, name)]
        target = mod.aliases.get(name)
        if target and target in self._by_dotted:
            return sorted(self._by_dotted[target])
        return []

    def _resolve_call(self, mod: ModuleCtx, call: ast.Call,
                      from_qualname: str) -> List[Tuple[str, str]]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return self._resolve_local(mod, fn.id, from_qualname)
        if isinstance(fn, ast.Attribute):
            # self.method() -> same class
            if (isinstance(fn.value, ast.Name) and fn.value.id in
                    ("self", "cls") and "." in from_qualname):
                cls = from_qualname.rsplit(".", 2)[0] \
                    if from_qualname.count(".") >= 1 else ""
                qn = f"{cls}.{fn.attr}" if cls else fn.attr
                if qn in mod.functions:
                    return [_func_key(mod, qn)]
                return []
            canon = mod.canonical(fn)
            if canon and canon in self._by_dotted:
                return sorted(self._by_dotted[canon])
        return []

    def _resolve_callable_arg(self, mod: ModuleCtx, node: ast.AST,
                              from_qualname: str) -> List[Tuple[str, str]]:
        """The function object handed to a jit-family call."""
        if isinstance(node, ast.Name):
            return self._resolve_local(mod, node.id, from_qualname)
        if isinstance(node, ast.Attribute):
            canon = mod.canonical(node)
            if canon and canon in self._by_dotted:
                return sorted(self._by_dotted[canon])
        if isinstance(node, ast.Call):
            # partial(fn, ...) / jit(fn) / shard_map(fn, mesh=...): unwrap
            targets = []
            for a in node.args[:1]:
                targets += self._resolve_callable_arg(mod, a, from_qualname)
            return targets
        return []

    # ---- the closure -------------------------------------------------------

    def _owner_qualname(self, mod: ModuleCtx, node: ast.AST) -> str:
        qn_by_node = getattr(mod, "_qn_by_node", None)
        if qn_by_node is None:
            qn_by_node = {id(fi.node): qn for qn, fi in mod.functions.items()}
            mod._qn_by_node = qn_by_node
        fn = mod.enclosing_function(node)
        while fn is not None:
            qn = qn_by_node.get(id(fn))
            if qn is not None:
                return qn
            fn = mod.enclosing_function(fn)  # lambda owners: nearest def
        return ""

    def _compute(self) -> None:
        roots: Set[Tuple[str, str]] = set()
        edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for mod in self.modules:
            # decorators
            for qn, fi in mod.functions.items():
                for dec in getattr(fi.node, "decorator_list", []):
                    canon = mod.canonical(
                        dec.func if isinstance(dec, ast.Call) else dec)
                    if is_jit_family(canon):
                        roots.add(_func_key(mod, qn))
                    elif (isinstance(dec, ast.Call)
                          and mod.canonical(dec.func) in
                          ("functools.partial", "partial") and dec.args
                          and is_jit_family(mod.canonical(dec.args[0]))):
                        roots.add(_func_key(mod, qn))
            # call sites: jit-family args become roots; plain calls, edges
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                owner = self._owner_qualname(mod, node)
                canon = mod.canonical(node.func) \
                    if isinstance(node.func,
                                  (ast.Name, ast.Attribute)) else None
                if is_jit_family(canon):
                    for arg in node.args[:1]:
                        roots.update(
                            self._resolve_callable_arg(mod, arg, owner))
                if owner:
                    key = _func_key(mod, owner)
                    for tgt in self._resolve_call(mod, node, owner):
                        edges.setdefault(key, set()).add(tgt)
        # nested defs of a reachable function are reachable too (closures
        # built and called inside the traced body)
        nested: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for mod in self.modules:
            for qn in mod.functions:
                if "." in qn:
                    parent = qn.rsplit(".", 1)[0]
                    if parent in mod.functions:
                        nested.setdefault(
                            _func_key(mod, parent), set()).add(
                            _func_key(mod, qn))
        frontier = list(roots)
        seen = set(roots)
        while frontier:
            cur = frontier.pop()
            for nxt in edges.get(cur, set()) | nested.get(cur, set()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        self.reachable = seen

    def is_reachable(self, mod: ModuleCtx, qualname: str) -> bool:
        return _func_key(mod, qualname) in self.reachable

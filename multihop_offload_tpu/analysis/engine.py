"""The analysis engine: walk files, run rules, classify waivers, baseline.

One `run_analysis(...)` call produces a `Report`:

  * `findings`  — live violations (these fail the gate),
  * `waived`    — sites carrying the rule's waiver token (or `# noqa`)
                  on a line the flagged node spans: deliberate, reviewed
                  exceptions, counted per rule so waiver creep is visible
                  in benchmarks/analysis_report.json,
  * `suppressed`— findings matched by a `--baseline` file entry.

Scope resolution: a file's rule scope is decided by its path relative to
the PACKAGE ROOT — the path component named `multihop_offload_tpu` when
present, else the scanned root itself.  That second case lets fixture
trees (tests/fixtures/analysis_seeded/env/...) exercise dir-scoped rules
without nesting a fake package.

Baseline format (JSON): a list of {path, rule, snippet_sha1} entries
with an occurrence count.  Matching is by content hash of the stripped
flagged line, so findings survive unrelated line-number drift but
re-surface the moment the flagged code itself changes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from multihop_offload_tpu.analysis import checks_imports  # noqa: F401  (registers rules)
from multihop_offload_tpu.analysis import checks_jax      # noqa: F401
from multihop_offload_tpu.analysis import checks_repo     # noqa: F401
from multihop_offload_tpu.analysis.modinfo import ModuleCtx, parse_module
from multihop_offload_tpu.analysis.reachability import ProjectIndex
from multihop_offload_tpu.analysis.rules import Finding, Rule, resolve_select

PACKAGE_DIR = "multihop_offload_tpu"
_SKIP_DIRS = ("__pycache__", ".git", ".ruff_cache", ".pytest_cache")


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    waived: List[Finding]
    suppressed: List[Finding]
    files_scanned: int
    rules_run: List[str]

    def counts(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for rid in self.rules_run:
            out[rid] = {"findings": 0, "waived": 0, "suppressed": 0}
        for f in self.findings:
            out.setdefault(f.rule, {"findings": 0, "waived": 0,
                                    "suppressed": 0})["findings"] += 1
        for f in self.waived:
            out.setdefault(f.rule, {"findings": 0, "waived": 0,
                                    "suppressed": 0})["waived"] += 1
        for f in self.suppressed:
            out.setdefault(f.rule, {"findings": 0, "waived": 0,
                                    "suppressed": 0})["suppressed"] += 1
        return out

    def to_json(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "rules": self.counts(),
            "findings": [f.to_json() for f in self.findings],
            "waived": [f.to_json() for f in self.waived],
            "suppressed": [f.to_json() for f in self.suppressed],
        }


def iter_py_files(roots: Sequence[str]):
    for root in roots:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _rel_parts(path: str, root: str) -> Tuple[str, ...]:
    """Path components relative to the package root (see module doc)."""
    parts = os.path.normpath(path).split(os.sep)
    if PACKAGE_DIR in parts:
        i = len(parts) - 1 - parts[::-1].index(PACKAGE_DIR)
        return tuple(parts[i + 1:])
    rel = os.path.relpath(path, root if os.path.isdir(root)
                          else os.path.dirname(root) or ".")
    return tuple(os.path.normpath(rel).split(os.sep))


def _waiver_on_span(mod: ModuleCtx, finding: Finding, rule: Rule) -> Tuple[bool, str]:
    """Is the rule's waiver token (or # noqa) present on any line the
    flagged node spans?  Returns (waived, reason-text)."""
    # scan from the flagged line to where its bracket nesting closes (a
    # multi-line call may carry the waiver on any of its physical lines)
    depth = 0
    for ln in range(finding.line, min(finding.line + 12,
                                      len(mod.lines) + 1)):
        text = mod.line(ln)
        if rule.waiver and rule.waiver in text:
            reason = text.split(rule.waiver, 1)[1]
            return True, reason.split(")", 1)[0]
        if "# noqa" in text and ln == finding.line:
            return True, "noqa"
        code = text.split("#", 1)[0]
        depth += (code.count("(") + code.count("[")
                  - code.count(")") - code.count("]"))
        if depth <= 0:
            break
    return False, ""


def _snippet_hash(f: Finding) -> str:
    return hashlib.sha1(f.snippet.encode("utf-8")).hexdigest()[:16]


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("suppressions", []):
        key = (e["path"], e["rule"], e["snippet_sha1"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    agg: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        key = (f.path, f.rule, _snippet_hash(f))
        agg[key] = agg.get(key, 0) + 1
    entries = [
        {"path": p, "rule": r, "snippet_sha1": h, "count": c}
        for (p, r, h), c in sorted(agg.items())
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"format": "mho-lint-baseline-v1",
                   "suppressions": entries}, fh, indent=2)
        fh.write("\n")


def run_analysis(
    roots: Sequence[str],
    select: Optional[str] = None,
    baseline: Optional[str] = None,
) -> Report:
    rules = resolve_select(select)
    mods: List[ModuleCtx] = []
    parse_findings: List[Finding] = []
    n_files = 0
    for root in roots:
        for path in iter_py_files([root]):
            n_files += 1
            mod, err = parse_module(path, _rel_parts(path, root))
            if err is not None:
                parse_findings.append(err)
            if mod is not None:
                mods.append(mod)
    project = ProjectIndex(mods)
    for mod in mods:
        mod.project = project

    findings: List[Finding] = list(parse_findings)
    waived: List[Finding] = []
    for mod in mods:
        for r in rules:
            if not r.applies_to(mod.rel_parts):
                continue
            for f in r.check(mod):
                is_waived, reason = _waiver_on_span(mod, f, r)
                if is_waived:
                    waived.append(dataclasses.replace(
                        f, waived=True, waiver_reason=reason))
                else:
                    findings.append(f)

    suppressed: List[Finding] = []
    if baseline and os.path.exists(baseline):
        budget = load_baseline(baseline)
        live: List[Finding] = []
        for f in findings:
            key = (f.path, f.rule, _snippet_hash(f))
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                suppressed.append(f)
            else:
                live.append(f)
        findings = live

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    waived.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=findings, waived=waived, suppressed=suppressed,
                  files_scanned=n_files, rules_run=[r.id for r in rules])

"""mho-lint: the repo's JAX-aware static-analysis engine.

AST-based (alias- and multi-line-aware) replacements for the old regex
fallback rules plus the JAX-correctness tripwires every perf gate in this
repo leans on: trace-safety (JX001), retrace hazards (JX002), dtype
pinning (JX003), hot-loop host sync (JX004), and nondeterminism (JX005),
alongside the original MP001/SL001/OB001 and the ruff-approximation
E999/F401/F811 set.  Stdlib-only: the gate runs in containers without
ruff or jax installed.  See docs/OPERATIONS.md "Static analysis".
"""

from multihop_offload_tpu.analysis.engine import (
    Report,
    run_analysis,
    write_baseline,
)
from multihop_offload_tpu.analysis.reachability import ProjectIndex
from multihop_offload_tpu.analysis.rules import (
    Finding,
    Rule,
    all_rules,
    get_rule,
    resolve_select,
)

__all__ = [
    "Report", "run_analysis", "write_baseline", "ProjectIndex",
    "Finding", "Rule", "all_rules", "get_rule", "resolve_select",
]

"""Per-module symbol model for the analysis engine.

`ModuleCtx` wraps one parsed source file with everything the checks need:

  * the AST with parent links (`parent_of`) so checks can ask "is this
    call inside a loop / a function / module scope";
  * an import-alias map collected from EVERY `import`/`from ... import`
    in the file (module scope AND function scope — lazy in-function jax
    imports are this repo's idiom), so `canonical(node)` can resolve
    `jnp.zeros`, `jn.zeros` (any alias), `from jax.numpy import zeros`,
    and simple local aliases like `z = jnp.zeros` to one dotted name
    (`jax.numpy.zeros`).  This is what makes the AST rules alias-aware
    where the old line regexes only matched the literal spelling `jnp.`;
  * a function index (`functions`): every `def`, keyed by dotted
    qualname (`Class.method`, `outer.<locals>.inner` collapses to
    `outer.inner`), used by the jit-reachability pass.

Waiver handling note: checks report the node's `lineno`; the ENGINE
scans `lineno..end_lineno` for the rule's waiver token, so a waiver
comment on any physical line of a multi-line call is honored.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

# canonical prefixes we normalize toward; anything else resolves to the
# import target verbatim (e.g. multihop_offload_tpu.env.scheduling)
_NUMPY_ALIASES = {"numpy": "numpy", "jax.numpy": "jax.numpy"}


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition inside a module."""

    qualname: str
    node: ast.AST               # FunctionDef | AsyncFunctionDef | Lambda
    lineno: int


class ModuleCtx:
    """Parsed module + symbol info (see module docstring)."""

    def __init__(self, path: str, rel_parts: Tuple[str, ...], source: str,
                 tree: ast.Module):
        self.path = path
        self.rel_parts = rel_parts          # path parts under the pkg root
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: Dict[int, ast.AST] = {}
        self.aliases: Dict[str, str] = {}   # local name -> dotted target
        self.functions: Dict[str, FuncInfo] = {}
        self._index()

    # ---- construction ------------------------------------------------------

    def _index(self) -> None:
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bind = a.asname or a.name.split(".")[0]
                    # `import jax.numpy as jnp` binds jnp -> jax.numpy;
                    # bare `import jax.numpy` binds jax -> jax
                    self.aliases[bind] = a.name if a.asname else bind
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue  # relative imports stay package-internal
                for a in node.names:
                    bind = a.asname or a.name
                    if bind != "*":
                        self.aliases[bind] = f"{node.module}.{a.name}"
        # simple value aliases: `z = jnp.zeros` (module or function scope)
        # make the constructor rules alias-proof; one extra resolution hop
        # only — chains of aliases are not followed.
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, (ast.Attribute, ast.Name))):
                tgt = self._dotted(node.value)
                if tgt:
                    root = tgt.split(".", 1)[0]
                    base = self.aliases.get(root)
                    if base and root not in ("self", "cls"):
                        resolved = tgt.replace(root, base, 1)
                        if resolved.split(".", 1)[0] in ("numpy", "jax"):
                            self.aliases.setdefault(
                                node.targets[0].id, resolved)
        self._index_functions(self.tree, prefix="")

    def _index_functions(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                self.functions[qn] = FuncInfo(qn, child, child.lineno)
                self._index_functions(child, prefix=f"{qn}.")
            elif isinstance(child, ast.ClassDef):
                self._index_functions(child, prefix=f"{prefix}{child.name}.")
            else:
                self._index_functions(child, prefix)

    # ---- queries -----------------------------------------------------------

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent_of(node)
        while cur is not None:
            yield cur
            cur = self.parent_of(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return a
        return None

    def in_loop(self, node: ast.AST, stop_at_function: bool = True) -> bool:
        """Is `node` lexically inside a for/while body?  With
        `stop_at_function` the search stops at the nearest enclosing def:
        a function defined in a loop is the *function's* problem only if
        the call site is (JX002 handles the def-in-loop case itself)."""
        for a in self.ancestors(node):
            if isinstance(a, (ast.For, ast.While, ast.AsyncFor)):
                return True
            if stop_at_function and isinstance(
                    a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return False
        return False

    def _dotted(self, node: ast.AST) -> Optional[str]:
        """Raw dotted text of a Name/Attribute chain, no alias resolution."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain through the import-alias map:
        `jnp.zeros` -> `jax.numpy.zeros`, `scan` (from jax.lax import
        scan) -> `jax.lax.scan`.  Unresolvable chains (locals, self.x)
        return the raw dotted text — callers match on known prefixes, so
        an unresolved local name simply never matches."""
        dotted = self._dotted(node)
        if dotted is None:
            return None
        root, _, rest = dotted.partition(".")
        base = self.aliases.get(root)
        if base is None:
            return dotted
        return f"{base}.{rest}" if rest else base

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def span_lines(self, node: ast.AST) -> range:
        end = getattr(node, "end_lineno", None) or node.lineno
        return range(node.lineno, end + 1)


def parse_module(path: str, rel_parts: Tuple[str, ...],
                 source: Optional[str] = None) -> Tuple[Optional[ModuleCtx],
                                                        Optional["object"]]:
    """Parse one file; on syntax error return (None, the E999 finding)."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        from multihop_offload_tpu.analysis.rules import Finding
        return None, Finding(
            rule="E999", path=path, line=e.lineno or 0,
            message=f"syntax error: {e.msg}",
            snippet=(e.text or "").strip(),
        )
    return ModuleCtx(path, rel_parts, source, tree), None

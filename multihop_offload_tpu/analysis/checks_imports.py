"""The ruff-approximation rules the old fallback provided (E999/F401/F811).

Ported verbatim-in-spirit from `scripts/_lint_fallback.py` (which is now
a shim over this package): module-scope unused imports honoring `# noqa`,
`__init__.py` re-export hubs, `__all__`, underscore bindings, and
string-literal mentions (doctest-ish uses); F811 for an import rebinding
an earlier import.  E999 (syntax errors) is detected at parse time by the
engine — the rule is registered here so `--select pyflakes` and the docs
have an entry for it; its check body never runs on an unparseable file.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from multihop_offload_tpu.analysis.modinfo import ModuleCtx
from multihop_offload_tpu.analysis.rules import Finding, rule


@rule(
    id="E999", severity="error", scope="everywhere", waiver="",
    doc="file does not parse (syntax/indentation error)",
)
def check_e999(mod: ModuleCtx) -> Iterator[Finding]:
    return iter(())  # parse errors are emitted by the engine before checks


@rule(
    id="F401", severity="error", scope="everywhere", waiver="",
    doc="module-scope import never used (honors # noqa, __all__, _name)",
)
def check_f401(mod: ModuleCtx) -> Iterator[Finding]:
    if os.path.basename(mod.path) == "__init__.py":
        return
    imports = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                bind = a.asname or a.name.split(".")[0]
                if bind != "*":
                    imports[bind] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                bind = a.asname or a.name
                if bind != "*":
                    imports[bind] = (node.lineno,
                                     f"{node.module}.{a.name}")
    used = {n.id for n in ast.walk(mod.tree)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
    exported = set()
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            exported = {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)}
    literal_words = set(" ".join(
        n.value for n in ast.walk(mod.tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ).split())
    for name, (lineno, display) in imports.items():
        if name in used or name in exported or name in literal_words:
            continue
        if name.startswith("_"):
            continue
        if "# noqa" in mod.line(lineno):
            continue
        yield Finding(
            rule="F401", path=mod.path, line=lineno,
            message=f"unused import '{display}' as '{name}'",
            snippet=mod.line(lineno).strip(),
        )


@rule(
    id="F811", severity="error", scope="everywhere", waiver="",
    doc="a later module-scope import rebinds an earlier imported name",
)
def check_f811(mod: ModuleCtx) -> Iterator[Finding]:
    seen = {}
    for node in mod.tree.body:
        names = []
        if isinstance(node, ast.Import):
            names = [(a.asname or a.name.split(".")[0], node.lineno)
                     for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module != "__future__":
            names = [(a.asname or a.name, node.lineno) for a in node.names]
        for bind, lineno in names:
            if bind == "*":
                continue
            if bind in seen and "# noqa" not in mod.line(lineno):
                yield Finding(
                    rule="F811", path=mod.path, line=lineno,
                    message=f"import redefines '{bind}'",
                    snippet=mod.line(lineno).strip(),
                )
            seen[bind] = lineno

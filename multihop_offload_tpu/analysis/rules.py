"""Rule registry for the `mho-lint` static-analysis engine.

A `Rule` is an id plus everything the engine and the docs need to know
about it: severity, the package scope it applies to, the per-line waiver
token that marks a deliberate, reviewed exception, and a one-line doc
rendered by `mho-lint --list-rules` and docs/OPERATIONS.md.

Rules register themselves with the `@rule(...)` decorator; the check
callable receives a `ModuleCtx` (parsed module + import-alias info, see
`modinfo`) and yields `Finding`s.  The ENGINE, not the check, decides
whether a finding is waived (waiver token or `# noqa` on any source line
the flagged node spans) — checks only say *where* and *what*.

Stdlib-only, like the rest of the package: the lint gate must run in
containers without ruff or jax installed.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule hit: a location, the rule id, and the human message."""

    rule: str
    path: str
    line: int
    message: str
    # the stripped source line, used for baseline matching (stable under
    # line-number drift, invalidated when the flagged code itself changes)
    snippet: str = ""
    waived: bool = False
    waiver_reason: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "waived": self.waived,
            **({"waiver_reason": self.waiver_reason}
               if self.waiver_reason else {}),
        }


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered check (see module docstring)."""

    id: str
    severity: str                     # "error" | "warning"
    scope: str                        # human-readable scope description
    waiver: str                       # waiver token, e.g. "# dtype-ok(" ("" = none)
    doc: str                          # one-line summary for --list-rules / docs
    check: Callable[..., Iterable[Finding]]
    # first-level package dirs the rule applies to; None = whole package
    dirs: Optional[Tuple[str, ...]] = None
    # first-level package dirs exempt from the rule (e.g. cli/ for prints)
    exempt_dirs: Tuple[str, ...] = ()
    # exempt file basenames (e.g. precision.py defines the dtype policy)
    exempt_files: Tuple[str, ...] = ()

    def applies_to(self, rel_parts: Tuple[str, ...]) -> bool:
        """Does this rule run on a file at `rel_parts` (path components
        relative to the package root, e.g. ("env", "queueing.py"))?"""
        if not rel_parts:
            return False
        if rel_parts[-1] in self.exempt_files:
            return False
        top = rel_parts[0] if len(rel_parts) > 1 else ""
        if top in self.exempt_dirs:
            return False
        if self.dirs is not None and top not in self.dirs:
            return False
        return True


_REGISTRY: Dict[str, Rule] = {}

# selection groups understood by the CLI's --select
GROUPS = {
    # the repo-specific rules lint.sh runs on both branches
    "repo": ("JX001", "JX002", "JX003", "JX004", "JX005", "JX006", "JX007",
             "JX008", "JX009", "JX010", "JX011", "JX012", "MP001", "SL001",
             "OB001", "OB002", "OB003"),
    # the ruff-approximation rules (E9/F401/F811) the fallback branch runs
    # over tests/ scripts/ bench.py as well as the package
    "pyflakes": ("E999", "F401", "F811"),
}


def rule(**kwargs) -> Callable:
    """Register the decorated callable as a rule's check."""

    def deco(fn):
        r = Rule(check=fn, **kwargs)
        if r.id in _REGISTRY:
            raise ValueError(f"duplicate rule id {r.id}")
        _REGISTRY[r.id] = r
        return fn

    return deco


def all_rules() -> List[Rule]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    return _REGISTRY[rule_id]


def resolve_select(select: Optional[str]) -> List[Rule]:
    """Expand a --select value ("repo", "pyflakes", "all", or a
    comma-separated id list) into rules.  Unknown ids raise ValueError."""
    if select is None or select == "repo":
        ids: Iterable[str] = GROUPS["repo"]
    elif select == "all":
        ids = sorted(_REGISTRY)
    elif select in GROUPS:
        ids = GROUPS[select]
    else:
        ids = [s.strip() for s in select.split(",") if s.strip()]
    out = []
    for i in ids:
        if i not in _REGISTRY:
            raise ValueError(
                f"unknown rule id '{i}' (known: {', '.join(sorted(_REGISTRY))})"
            )
        out.append(_REGISTRY[i])
    return out

"""The JAX-correctness rules (JX001–JX005) — see docs/OPERATIONS.md.

JX001 trace-safety      Python control flow / concretization on traced
                        values inside jit-reachable code
JX002 retrace hazard    jit construction inside a loop, or jit over a
                        fresh lambda built per call
JX003 dtype pinning     jnp/np arange|zeros|ones without an explicit
                        dtype in hot-path dirs (the sim/ i32-pin bug)
JX004 host sync         device read-backs inside the serve tick / train
                        step / sim step host loops
JX005 nondeterminism    wall-clock / global-RNG calls in library code —
                        clocks are injected (the health layer's
                        convention), RNG is seeded
JX008 saturation div    unguarded `x / (1 - ...)` in the queueing-math
                        dirs — the M/M/1 utilization denominator blows
                        up to inf/NaN exactly at the saturated inputs
                        the admission guards exist to keep out
JX009 rollout purity    host sync / callback (`.item()`, `np.*`,
                        `jax.debug.callback` / `io_callback`) inside an
                        rl/ rollout-scan body — the Anakin closed loop
                        must stay one compiled program
JX010 mesh bring-up     `jax.distributed.initialize` / process-index
                        branching outside multihost/ — process-group
                        formation has one owner (multihost.runtime), so
                        retry/backoff/idempotence live in one place
JX011 topology drawing  raw `networkx` graph constructors outside
                        graphs/ — ad-hoc draws skip the connectivity
                        retry, the seeded-determinism contract and the
                        (adj, pos) dtype normalization that
                        graphs.generators owns (the scenario matrix's
                        realizations must be reproducible per seed)
JX012 use-after-donate  reading a buffer after passing it at a donated
                        position of a `jax.jit(..., donate_argnums=...)`
                        program — the donated pages may already back the
                        program's outputs, so the read observes garbage
                        on TPU (and nothing on CPU, where donation is a
                        no-op and the bug ships silently)

JX001 runs a small intraprocedural taint pass over each jit-reachable
function (see `reachability`): values produced by `jax.*` calls are
*traced*; taint follows assignments, arithmetic, subscripts and method
calls, and is DROPPED through static accessors (`.shape`, `.ndim`,
`.dtype`, `.size`, `len()`) and by rebinding to an untraced value — so
`if x.ndim == 2:` and a traced name shadowed by a Python int are not
findings.  Function parameters are deliberately NOT taint seeds: static
shape/config arguments branch all the time in this codebase; the bug
class is branching on *array values*, which must flow through a jax op
first.  This is a tripwire for the common spelling of each bug, not a
soundness proof — `getattr` dances and data passed through containers
escape it.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from multihop_offload_tpu.analysis.modinfo import ModuleCtx
from multihop_offload_tpu.analysis.rules import Finding, rule

_ARRAY_NS = ("numpy", "jax.numpy")

# attribute reads that yield STATIC (trace-time) values on traced arrays
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
# calls whose results are static regardless of argument taint
_STATIC_CALLS = {"len", "isinstance", "range", "type", "getattr", "hasattr",
                 "jax.numpy.shape", "jax.numpy.ndim", "jax.numpy.result_type"}

JX003_DIRS = ("env", "models", "agent", "serve", "sim", "layouts",
              "train", "loop")
JX004_DIRS = ("serve", "sim", "train", "loop")

_HOT_LOOP_NAMES = ("tick", "step", "drain")


def _snippet(mod: ModuleCtx, node: ast.AST) -> str:
    return mod.line(node.lineno).strip()


# ---------------------------------------------------------------------------
# JX001 — trace-safety
# ---------------------------------------------------------------------------


class _TaintPass:
    """One function's worth of taint propagation + flag points."""

    def __init__(self, mod: ModuleCtx, fn: ast.AST):
        self.mod = mod
        self.fn = fn
        self.tainted: Set[str] = set()
        self.findings: List[Finding] = []

    # ---- expression taint --------------------------------------------------

    def _call_canon(self, node: ast.Call):
        if isinstance(node.func, (ast.Name, ast.Attribute)):
            return self.mod.canonical(node.func)
        return None

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            canon = self._call_canon(node)
            if canon in _STATIC_CALLS or (canon or "").split(".")[0] in (
                    "len", "isinstance", "range"):
                return False
            # bool()/float()/int() concretize: flagged at the flag points,
            # and their RESULT is a Python scalar again
            if isinstance(node.func, ast.Name) and node.func.id in (
                    "bool", "float", "int"):
                return False
            if canon and canon.startswith("jax."):
                return True
            if isinstance(node.func, ast.Attribute) \
                    and self.is_tainted(node.func):
                return True  # tainted.sum() and friends
            return any(self.is_tainted(a) for a in node.args) or any(
                self.is_tainted(k.value) for k in node.keywords)
        if isinstance(node, (ast.BinOp,)):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return (self.is_tainted(node.body) or self.is_tainted(node.test)
                    or self.is_tainted(node.orelse))
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        return False

    # ---- flag points -------------------------------------------------------

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            rule="JX001", path=self.mod.path, line=node.lineno,
            message=(f"{what} on a traced value in jit-reachable code — "
                     "use lax.cond/jnp.where (or hoist to the host), or "
                     "waive with '# trace-ok(<why>)'"),
            snippet=_snippet(self.mod, node),
        ))

    def _scan_expr(self, node: ast.AST) -> None:
        """Find concretization calls anywhere inside an expression."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Name) and sub.func.id in (
                    "bool", "float", "int") and sub.args:
                if self.is_tainted(sub.args[0]):
                    self._flag(sub, f"{sub.func.id}()")
            elif isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "item" and not sub.args:
                self._flag(sub, ".item()")

    # ---- statement walk ----------------------------------------------------

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if tainted
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)

    def run(self) -> List[Finding]:
        body = getattr(self.fn, "body", [])
        if isinstance(body, ast.AST):     # lambda
            self._scan_expr(body)
            return self.findings
        self._stmts(body)
        return self.findings

    def _stmts(self, stmts) -> None:
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st: ast.AST) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs are analyzed as their own reachable entries
        if isinstance(st, ast.Assign):
            self._scan_expr(st.value)
            t = self.is_tainted(st.value)
            for tgt in st.targets:
                self._bind(tgt, t)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            self._scan_expr(st.value)
            self._bind(st.target, self.is_tainted(st.value))
        elif isinstance(st, ast.AugAssign):
            self._scan_expr(st.value)
            if self.is_tainted(st.value):
                self._bind(st.target, True)
        elif isinstance(st, (ast.If, ast.While)):
            self._scan_expr(st.test)
            if self.is_tainted(st.test):
                kind = "if" if isinstance(st, ast.If) else "while"
                self._flag(st.test, f"Python `{kind}`")
            # two passes over loop bodies to catch loop-carried taint
            rounds = 2 if isinstance(st, ast.While) else 1
            for _ in range(rounds):
                self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(st.iter)
            self._bind(st.target, self.is_tainted(st.iter))
            for _ in range(2):
                self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._scan_expr(item.context_expr)
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
        elif isinstance(st, (ast.Return, ast.Expr)) and st.value is not None:
            self._scan_expr(st.value)
        elif isinstance(st, ast.Raise) and st.exc is not None:
            self._scan_expr(st.exc)


@rule(
    id="JX001", severity="error",
    scope="jit-reachable functions, whole package",
    waiver="# trace-ok(",
    doc=("Python if/while/bool()/float()/int()/.item() on a traced value "
         "inside jit-reachable code"),
)
def check_jx001(mod: ModuleCtx) -> Iterator[Finding]:
    project = getattr(mod, "project", None)
    if project is None:
        return
    for qn, fi in mod.functions.items():
        if not project.is_reachable(mod, qn):
            continue
        yield from _TaintPass(mod, fi.node).run()


# ---------------------------------------------------------------------------
# JX002 — retrace hazards
# ---------------------------------------------------------------------------

_JIT_CTORS = {"jax.jit", "jax.pjit", "jax.pmap", "jax.experimental.pjit.pjit"}


@rule(
    id="JX002", severity="error",
    scope="whole package",
    waiver="# retrace-ok(",
    doc=("jax.jit/pjit/pmap constructed inside a loop, or over a fresh "
         "lambda built per call — each construction is a new cache entry"),
)
def check_jx002(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon not in _JIT_CTORS:
            continue
        if mod.in_loop(node):
            yield Finding(
                rule="JX002", path=mod.path, line=node.lineno,
                message=("jit construction inside a loop — every iteration "
                         "makes a fresh compilation-cache entry; hoist the "
                         "jit out (or waive a build-once-per-bucket site "
                         "with '# retrace-ok(<why>)')"),
                snippet=_snippet(mod, node),
            )
        elif (node.args and isinstance(node.args[0], ast.Lambda)
                and mod.enclosing_function(node) is not None):
            yield Finding(
                rule="JX002", path=mod.path, line=node.lineno,
                message=("jit over a lambda built inside a function — a "
                         "fresh lambda per call never hits the jit cache; "
                         "name the function at module/build scope, or "
                         "waive with '# retrace-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )


# ---------------------------------------------------------------------------
# JX003 — unpinned dtypes in hot paths
# ---------------------------------------------------------------------------


@rule(
    id="JX003", severity="error",
    scope="env/ models/ agent/ serve/ sim/ layouts/ train/ loop/",
    waiver="# dtype-ok(",
    doc=("jnp/np arange|zeros|ones without an explicit dtype in a hot-path "
         "dir — platform-default dtypes caused the sim/ i32-pin bug"),
    dirs=JX003_DIRS,
)
def check_jx003(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon is None:
            continue
        ns, _, fn = canon.rpartition(".")
        if ns not in _ARRAY_NS or fn not in ("arange", "zeros", "ones"):
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        # positional dtype: zeros/ones(shape, dtype); arange(a, b, step, dtype)
        if fn in ("zeros", "ones") and len(node.args) >= 2:
            continue
        if fn == "arange" and len(node.args) >= 4:
            continue
        yield Finding(
            rule="JX003", path=mod.path, line=node.lineno,
            message=(f"{fn}() without an explicit dtype in a hot-path dir — "
                     "pin it (i32 for indices, policy dtype for data), or "
                     "waive with '# dtype-ok(<why>)'"),
            snippet=_snippet(mod, node),
        )


# ---------------------------------------------------------------------------
# JX004 — host sync inside the serving/training/sim hot loops
# ---------------------------------------------------------------------------

_HOST_SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}


def _is_hot_loop_fn(name: str) -> bool:
    return name in _HOT_LOOP_NAMES or name.endswith("_tick") \
        or name.endswith("_step")


@rule(
    id="JX004", severity="error",
    scope="serve/ sim/ train/ loop/ — functions named tick/step/drain "
          "(and *_tick/*_step)",
    waiver="# host-sync-ok(",
    doc=("np.asarray/.block_until_ready()/device_get/float(x[...]) inside "
         "a hot loop body — each one is a device sync per tick"),
    dirs=JX004_DIRS,
)
def check_jx004(mod: ModuleCtx) -> Iterator[Finding]:
    project = getattr(mod, "project", None)
    for qn, fi in mod.functions.items():
        tail = qn.rsplit(".", 1)[-1]
        if not _is_hot_loop_fn(tail):
            continue
        # a jitted train/sim step cannot host-sync (it would fail at trace
        # time); JX004 is about the HOST side of the loop
        if project is not None and project.is_reachable(mod, qn):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.canonical(node.func) if isinstance(
                node.func, (ast.Name, ast.Attribute)) else None
            hit = None
            if canon in _HOST_SYNC_CALLS:
                hit = canon
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                hit = ".block_until_ready()"
            elif (isinstance(node.func, ast.Name)
                  and node.func.id == "float" and node.args
                  and isinstance(node.args[0], ast.Subscript)):
                hit = "float(x[...]) read-back"
            if hit:
                yield Finding(
                    rule="JX004", path=mod.path, line=node.lineno,
                    message=(f"{hit} inside hot-loop function '{tail}' — "
                             "one device sync per tick; batch the fetch or "
                             "move it off the loop, or waive with "
                             "'# host-sync-ok(<why>)'"),
                    snippet=_snippet(mod, node),
                )


# ---------------------------------------------------------------------------
# JX005 — nondeterminism outside injected clocks / seeded RNG
# ---------------------------------------------------------------------------

_WALL_CLOCKS = {"time.time", "time.monotonic", "time.perf_counter",
                "time.process_time"}


@rule(
    id="JX005", severity="error",
    scope="library code (cli/ exempt — the console owns wall time)",
    waiver="# nondet-ok(",
    doc=("wall-clock / global-RNG call in library code — inject clocks "
         "(clock=time.monotonic param) and seed RNG; unseeded time/random "
         "breaks replay and resume"),
    exempt_dirs=("cli",),
)
def check_jx005(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon is None:
            continue
        root = canon.split(".")[0]
        msg = None
        if canon in _WALL_CLOCKS and "time" in mod.aliases:
            msg = (f"{canon}() call — inject the clock instead "
                   "(`clock: Callable[[], float]` parameter, the health "
                   "layer's convention)")
        elif root == "random" and "random" in mod.aliases:
            msg = (f"{canon}() — stdlib global RNG is unseeded "
                   "nondeterminism; use np.random.default_rng(seed) or "
                   "jax.random keys")
        elif canon.startswith("numpy.random."):
            fn = canon.rsplit(".", 1)[-1]
            if fn == "default_rng":
                if node.args or node.keywords:
                    continue  # seeded — the sanctioned pattern
                msg = ("np.random.default_rng() without a seed — "
                       "nondeterministic; thread a seed in")
            elif fn[:1].isupper() or fn == "Generator":
                continue  # type reference, not a draw
            else:
                msg = (f"np.random.{fn}() — legacy global-state RNG; use "
                       "np.random.default_rng(seed)")
        if msg:
            yield Finding(
                rule="JX005", path=mod.path, line=node.lineno,
                message=msg + ", or waive with '# nondet-ok(<why>)'",
                snippet=_snippet(mod, node),
            )


# ---------------------------------------------------------------------------
# JX008 — unguarded saturation denominators in the queueing-math dirs
# ---------------------------------------------------------------------------

JX008_DIRS = ("env", "sim", "loop")


def _has_one_minus(node: ast.AST) -> bool:
    """Does the expression contain a top-level `1 - x` / `1.0 - x`?  Does
    NOT descend into calls: a denominator wrapped in a guard
    (`jnp.maximum(1 - rho, eps)`, `jnp.where(...)`) is the sanctioned fix
    and must not fire."""
    if isinstance(node, ast.Call):
        return False
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
            and isinstance(node.left, ast.Constant)
            and node.left.value in (1, 1.0)):
        return True
    return any(_has_one_minus(c) for c in ast.iter_child_nodes(node))


@rule(
    id="JX008", severity="error",
    scope="env/ sim/ loop/",
    waiver="# div-ok(",
    doc=("unguarded `x / (1 - ...)` division in a queueing-math dir — the "
         "M/M/1 utilization denominator is 0 at rho=1 and negative past "
         "it; clamp (jnp.maximum(1 - rho, eps)), select (jnp.where), or "
         "prove the bound and waive"),
    dirs=JX008_DIRS,
)
def check_jx008(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div)):
            continue
        if not _has_one_minus(node.right):
            continue
        yield Finding(
            rule="JX008", path=mod.path, line=node.lineno,
            message=("division by an unguarded `1 - ...` saturation "
                     "denominator — inf/NaN at utilization 1; clamp it "
                     "(jnp.maximum(1 - rho, eps)) or select around it "
                     "(jnp.where), or waive a proven-bounded site with "
                     "'# div-ok(<why>)'"),
            snippet=_snippet(mod, node),
        )


# ---------------------------------------------------------------------------
# JX006 — swallowed exceptions in the recovery-critical dirs
# ---------------------------------------------------------------------------

JX006_DIRS = ("serve", "loop", "train", "obs")


def _pass_only(body) -> bool:
    return all(isinstance(st, ast.Pass) for st in body)


@rule(
    id="JX006", severity="error",
    scope="serve/ loop/ train/ obs/",
    waiver="# swallow-ok(",
    doc=("bare `except:` or `except Exception: pass` in a recovery-critical "
         "dir — a swallowed error here hides the exact corruption the chaos "
         "drills exist to surface; handle it, narrow it, or justify it"),
    dirs=JX006_DIRS,
)
def check_jx006(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            yield Finding(
                rule="JX006", path=mod.path, line=node.lineno,
                message=("bare `except:` swallows SystemExit/KeyboardInterrupt "
                         "and every error signal — catch a concrete type, or "
                         "waive with '# swallow-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )
            continue
        if not _pass_only(node.body):
            continue
        names = []
        types = (node.type.elts if isinstance(node.type, ast.Tuple)
                 else [node.type])
        for t in types:
            if isinstance(t, ast.Name):
                names.append(t.id)
        if any(n in ("Exception", "BaseException") for n in names):
            yield Finding(
                rule="JX006", path=mod.path, line=node.lineno,
                message=("`except Exception: pass` silently swallows errors "
                         "in a recovery-critical dir — handle or log the "
                         "failure, or waive with '# swallow-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )


# ---------------------------------------------------------------------------
# JX007 — unplaced device_put in the serving path
# ---------------------------------------------------------------------------


@rule(
    id="JX007", severity="error",
    scope="serve/",
    waiver="# placement-ok(",
    doc=("`jax.device_put` without an explicit device/sharding in serve/ — "
         "under the sharded executor the placement planner owns which chip "
         "holds what; an unplaced put lands on jax's default device and "
         "silently fights the plan"),
    dirs=("serve",),
)
def check_jx007(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon != "jax.device_put":
            continue
        explicit = len(node.args) >= 2 or any(
            kw.arg in ("device", "sharding") for kw in node.keywords
        )
        if explicit:
            continue
        yield Finding(
            rule="JX007", path=mod.path, line=node.lineno,
            message=("jax.device_put() without a device/sharding argument — "
                     "pass the planner's NamedSharding / target device so "
                     "placement stays the planner's decision, or waive with "
                     "'# placement-ok(<why>)'"),
            snippet=_snippet(mod, node),
        )


# ---------------------------------------------------------------------------
# JX009 — host sync / callback inside an rl/ rollout-scan body
# ---------------------------------------------------------------------------

_JX009_CALLBACKS = {
    "jax.debug.print", "jax.debug.callback",
    "jax.experimental.io_callback", "jax.io_callback",
}


def _jx009_scan_bodies(mod: ModuleCtx):
    """AST subtrees passed as the body callable of a `jax.lax.scan` call:
    lambdas inline, plus every module-level/nested `def` whose name is the
    first scan argument (one def may back several scans — yielded once)."""
    fns: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, []).append(node)
    seen = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon != "jax.lax.scan":
            continue
        body = node.args[0]
        if isinstance(body, ast.Lambda):
            yield body
        elif isinstance(body, ast.Name):
            for fn in fns.get(body.id, []):
                if id(fn) not in seen:
                    seen.add(id(fn))
                    yield fn


@rule(
    id="JX009", severity="error",
    scope="rl/",
    waiver="# rollout-ok(",
    doc=("host sync or callback (`.item()`, `np.*`, `jax.debug.callback` / "
         "`io_callback`) inside an rl/ rollout-scan body — the Anakin "
         "contract is ONE compiled program between episodes; any host hop "
         "in the scan serializes the device at every round"),
    dirs=("rl",),
)
def check_jx009(mod: ModuleCtx) -> Iterator[Finding]:
    emitted = set()
    for body in _jx009_scan_bodies(mod):
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                msg = (".item() inside a rollout scan body forces a "
                       "device->host sync every iteration")
            else:
                canon = mod.canonical(node.func) if isinstance(
                    node.func, (ast.Name, ast.Attribute)) else None
                if canon in _JX009_CALLBACKS:
                    msg = (f"{canon} inside a rollout scan body round-trips "
                           "the host from inside the compiled loop")
                elif canon == "numpy" or (canon or "").startswith("numpy."):
                    msg = (f"{canon} inside a rollout scan body is host "
                           "numpy — the result is computed outside the "
                           "program and re-transferred every iteration")
            if msg is None or (node.lineno, msg) in emitted:
                continue
            emitted.add((node.lineno, msg))
            yield Finding(
                rule="JX009", path=mod.path, line=node.lineno,
                message=(msg + " — keep the body device-native (jnp/lax), "
                         "or waive with '# rollout-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )


# ---------------------------------------------------------------------------
# JX010 — process-group bring-up outside multihost/
# ---------------------------------------------------------------------------

_JX010_BRINGUP = {"jax.distributed.initialize", "jax.distributed.shutdown"}
_JX010_TOPOLOGY = {"jax.process_index", "jax.process_count"}


@rule(
    id="JX010", severity="error",
    scope="package (multihost/ exempt)",
    waiver="# mesh-ok(",
    doc=("`jax.distributed.initialize` or process-index/count branching "
         "outside multihost/ — mesh bring-up has ONE owner "
         "(`multihost.runtime`: retry, backoff, idempotence, env fallback); "
         "a second initialize call crashes the runtime, and ad-hoc "
         "process-index forks drift from the federation's host naming"),
    exempt_dirs=("multihost",),
)
def check_jx010(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon in _JX010_BRINGUP:
            msg = (f"{canon}() outside multihost/ — call "
                   "multihost.runtime.bootstrap()/init_distributed() "
                   "instead (initialize is once-per-process; the runtime "
                   "module owns the guard, retries and env fallback)")
        elif canon in _JX010_TOPOLOGY:
            msg = (f"{canon}() outside multihost/ — route topology "
                   "decisions through multihost.runtime (MeshRuntime / "
                   "host_name) so host naming matches the federation's "
                   "labels")
        else:
            continue
        yield Finding(
            rule="JX010", path=mod.path, line=node.lineno,
            message=(msg + ", or waive with '# mesh-ok(<why>)'"),
            snippet=_snippet(mod, node),
        )


# ---------------------------------------------------------------------------
# JX011 — raw networkx topology draws outside graphs/
# ---------------------------------------------------------------------------

# the classic constructor surface: nx.<family>_graph(...) plus the bare
# container classes people reach for when hand-building a topology
_JX011_CLASSES = {"networkx.Graph", "networkx.DiGraph", "networkx.MultiGraph"}


@rule(
    id="JX011", severity="error",
    scope="package (graphs/ exempt — it owns topology drawing)",
    waiver="# topo-ok(",
    doc=("raw networkx graph constructor outside graphs/ — topology draws "
         "go through graphs.generators.generate so every caller gets the "
         "bounded connectivity retry, per-seed determinism and the "
         "(adj, pos) contract; an ad-hoc nx draw silently reintroduces the "
         "disconnected-graph hazard the generators close"),
    exempt_dirs=("graphs",),
)
def check_jx011(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon is None or not canon.startswith("networkx."):
            continue
        if not (canon.endswith("_graph") or canon in _JX011_CLASSES):
            continue
        yield Finding(
            rule="JX011", path=mod.path, line=node.lineno,
            message=(f"{canon}() outside graphs/ — draw topologies through "
                     "graphs.generators.generate (connectivity retry, "
                     "seeded determinism, (adj, pos) contract), or waive "
                     "with '# topo-ok(<why>)'"),
            snippet=_snippet(mod, node),
        )


# ---------------------------------------------------------------------------
# JX012 — use-after-donate
# ---------------------------------------------------------------------------


def _jx012_donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """Donated argument positions from a LITERAL `donate_argnums=` keyword;
    None when absent or dynamic — non-literal donation vectors are skipped
    (this is a tripwire for the common spelling, not alias analysis)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {int(v.value)}
        if isinstance(v, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for elt in v.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, int)):
                    return None
                out.add(int(elt.value))
            return out or None
        return None
    return None


def _jx012_units(body):
    """Statements in source order, each paired with the expression nodes
    that execute AT that statement (compound statements contribute their
    header only; their blocks are descended into as later units)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # nested defs get their own linear scan
        if isinstance(stmt, (ast.If, ast.While)):
            yield [stmt.test]
            yield from _jx012_units(stmt.body)
            yield from _jx012_units(stmt.orelse)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            yield [stmt.iter, stmt.target]
            yield from _jx012_units(stmt.body)
            yield from _jx012_units(stmt.orelse)
        elif isinstance(stmt, ast.Try):
            yield from _jx012_units(stmt.body)
            for h in stmt.handlers:
                yield from _jx012_units(h.body)
            yield from _jx012_units(stmt.orelse)
            yield from _jx012_units(stmt.finalbody)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield [i.context_expr for i in stmt.items]
            yield from _jx012_units(stmt.body)
        else:
            yield [stmt]


@rule(
    id="JX012", severity="error",
    scope="package",
    waiver="# donate-ok(",
    doc=("use-after-donate: a buffer read after being passed at a donated "
         "position of a `jax.jit(..., donate_argnums=...)` program — the "
         "donated buffer's pages may already back the program's outputs, so "
         "the read observes garbage on TPU and works by luck on CPU (where "
         "donation is a no-op and the bug ships silently)"),
)
def check_jx012(mod: ModuleCtx) -> Iterator[Finding]:
    # pass 1: names bound directly to a donating jax.jit(...) call —
    # module-level or local, one shared namespace (tripwire granularity)
    donating: dict = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        canon = (mod.canonical(node.value.func)
                 if isinstance(node.value.func, (ast.Name, ast.Attribute))
                 else None)
        if canon != "jax.jit":
            continue
        pos = _jx012_donated_positions(node.value)
        if not pos:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                donating[tgt.id] = pos
    if not donating:
        return
    # pass 2: per function, a linear statement scan — after a call to a
    # donating program, a later load of a name it consumed is a finding;
    # rebinding (or deleting) the name clears it.  Loop back-edges are not
    # modeled: a donation at the bottom of a loop body does not poison the
    # next iteration's reads (tripwire, not dataflow).
    for qn, fi in mod.functions.items():
        if isinstance(fi.node, ast.Lambda):
            continue
        consumed: dict = {}  # name -> (callee, donation line)
        for exprs in _jx012_units(fi.node.body):
            nodes = [n for e in exprs for n in ast.walk(e)]
            for n in nodes:  # reads of already-donated buffers
                if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                        and n.id in consumed):
                    callee, cline = consumed.pop(n.id)
                    yield Finding(
                        rule="JX012", path=mod.path, line=n.lineno,
                        message=(
                            f"'{n.id}' is read after being donated to "
                            f"{callee}() on line {cline} — a donated "
                            "buffer is invalid once the call is issued "
                            "(its pages may back the outputs); copy "
                            "before donating, reorder the read, or waive "
                            "with '# donate-ok(<why>)'"),
                        snippet=_snippet(mod, n),
                    )
            for n in nodes:  # new donations issued by this statement
                if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                        and n.func.id in donating):
                    for i in donating[n.func.id]:
                        if i < len(n.args) and isinstance(n.args[i], ast.Name):
                            consumed[n.args[i].id] = (n.func.id, n.lineno)
            for n in nodes:  # rebinds clear the donation
                if (isinstance(n, ast.Name)
                        and isinstance(n.ctx, (ast.Store, ast.Del))
                        and n.id in consumed):
                    del consumed[n.id]

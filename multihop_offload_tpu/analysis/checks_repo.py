"""AST re-implementations of the repo's original three lint rules.

These replace the line regexes in the old `scripts/_lint_fallback.py`
(MP001 / SL001 / OB001) with alias- and multi-line-aware AST checks:

  * `jnp.zeros(\n    (n, n))` split across lines no longer escapes SL001
    (the regex bug this engine was built to close);
  * `import jax.numpy as jn; jn.float32` is still MP001 — any import
    alias resolves through `ModuleCtx.canonical`;
  * `z = jnp.zeros; z((n, n))` is still SL001 — simple value aliases are
    one resolution hop in the alias map.

Same waiver comments as before (`# fp32-island(`, `# dense-ok(`,
`# print-ok(`), honored on ANY physical line the flagged call spans.
"""

from __future__ import annotations

import ast
from typing import Iterator

from multihop_offload_tpu.analysis.modinfo import ModuleCtx
from multihop_offload_tpu.analysis.rules import Finding, rule

_ARRAY_NS = ("numpy", "jax.numpy")

# hot-path dirs match the original fallback rules exactly
MP001_DIRS = ("env", "models", "agent", "serve", "sim")
SL001_DIRS = ("env", "models", "serve", "sim")


def _snippet(mod: ModuleCtx, node: ast.AST) -> str:
    return mod.line(node.lineno).strip()


@rule(
    id="MP001", severity="error",
    scope="env/ models/ agent/ serve/ sim/ (precision.py exempt)",
    waiver="# fp32-island(",
    doc=("hardcoded float32 in a hot-path module — dtypes flow from "
         "precision.PrecisionPolicy"),
    dirs=MP001_DIRS, exempt_files=("precision.py",),
)
def check_mp001(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Attribute):
            continue
        canon = mod.canonical(node)
        if canon in ("numpy.float32", "jax.numpy.float32"):
            yield Finding(
                rule="MP001", path=mod.path, line=node.lineno,
                message=("hardcoded float32 in hot path — take the dtype "
                         "from precision.PrecisionPolicy, or waive with "
                         "'# fp32-island(<why>)'"),
                snippet=_snippet(mod, node),
            )


def _same_symbol_dims(elts) -> bool:
    """First two tuple elements are the same Name/Attribute chain — the
    (n, n) square-buffer signature the old regex looked for."""
    if len(elts) < 2:
        return False
    a, b = elts[0], elts[1]
    if not isinstance(a, (ast.Name, ast.Attribute)):
        return False
    return ast.dump(a) == ast.dump(b)


@rule(
    id="SL001", severity="error",
    scope="env/ models/ serve/ sim/",
    waiver="# dense-ok(",
    doc=("dense square (N, N)-style materialization in a hot-path module — "
         "instance structure flows through layouts/ edge lists"),
    dirs=SL001_DIRS,
)
def check_sl001(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        canon = mod.canonical(node.func) if isinstance(
            node.func, (ast.Name, ast.Attribute)) else None
        if canon is None:
            continue
        ns, _, fn = canon.rpartition(".")
        if ns not in _ARRAY_NS or fn not in ("zeros", "ones", "full", "empty"):
            continue
        shape = node.args[0]
        if isinstance(shape, (ast.Tuple, ast.List)) \
                and _same_symbol_dims(shape.elts):
            yield Finding(
                rule="SL001", path=mod.path, line=node.lineno,
                message=("dense square materialization in hot path — route "
                         "through the padded edge lists in layouts/, or "
                         "waive with '# dense-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )


@rule(
    id="OB001", severity="error",
    scope="library code (cli/ and */cli.py exempt — printing is the "
          "console's job)",
    waiver="# print-ok(",
    doc=("bare print() in library code — telemetry goes through the run "
         "log / metric registry (obs/)"),
    exempt_dirs=("cli",), exempt_files=("cli.py",),
)
def check_ob001(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield Finding(
                rule="OB001", path=mod.path, line=node.lineno,
                message=("bare print() in library code — emit through the "
                         "run log or metric registry (obs/), or waive with "
                         "'# print-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )


# XLA introspection surface the prof layer owns; a direct call anywhere
# else forks the cost/memory view away from the registered program facts
_OB002_ATTRS = ("cost_analysis", "memory_analysis", "memory_stats")


@rule(
    id="OB002", severity="error",
    scope="library code (obs/ and bench.py exempt — the prof layer owns "
          "cost/memory introspection)",
    waiver="# prof-ok(",
    doc=("direct cost_analysis()/memory_analysis()/memory_stats() call "
         "outside the prof layer — go through obs.prof (extract_cost / "
         "ProgramRegistry) or obs.memwatch"),
    exempt_dirs=("obs",), exempt_files=("bench.py",),
)
def check_ob002(mod: ModuleCtx) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _OB002_ATTRS):
            yield Finding(
                rule="OB002", path=mod.path, line=node.lineno,
                message=(f"direct {node.func.attr}() outside the prof "
                         "layer — cost/memory introspection is centralized "
                         "in obs.prof / obs.memwatch so program facts and "
                         "gauges share one view; waive with "
                         "'# prof-ok(<why>)'"),
                snippet=_snippet(mod, node),
            )


# host-callback escape hatches: each call inside a compiled program stalls
# the device on a host round trip — in-program telemetry goes through the
# obs/devmetrics accumulator pytree instead
_OB003_CALLS = {
    "jax.debug.print",
    "jax.debug.callback",
    "jax.experimental.io_callback",
    "jax.io_callback",
}


@rule(
    id="OB003", severity="error",
    scope="jit-reachable functions outside obs/ (the obs layer owns the "
          "deliberate host bridges)",
    waiver="# devcb-ok(",
    doc=("jax.debug.print / jax.debug.callback / io_callback in "
         "jit-reachable code — each host callback stalls the device; "
         "accumulate through obs.devmetrics instead"),
    exempt_dirs=("obs",),
)
def check_ob003(mod: ModuleCtx) -> Iterator[Finding]:
    project = getattr(mod, "project", None)
    if project is None:
        return
    for qn, fi in mod.functions.items():
        if not project.is_reachable(mod, qn):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.canonical(node.func) if isinstance(
                node.func, (ast.Name, ast.Attribute)) else None
            if canon in _OB003_CALLS:
                yield Finding(
                    rule="OB003", path=mod.path, line=node.lineno,
                    message=(f"host callback {canon}() in jit-reachable "
                             "code — the device stalls on every invocation; "
                             "thread an obs.devmetrics accumulator through "
                             "the program instead, or waive with "
                             "'# devcb-ok(<why>)'"),
                    snippet=_snippet(mod, node),
                )

"""Node mobility: position jitter + topology rebuild.

The reference's (driver-unused but public) mobility support:
`AdhocCloud.random_walk` (`offloading_v3.py:80-97`) jitters a random subset
of node positions until unit-disk connectivity holds, and `topology_update`
(`:99-129`) rebuilds the conflict structure returning an old->new link map so
per-link state can migrate.  Host-side NumPy, producing fresh Topology arrays
for the device pipeline; the old->new map is expressed on canonical link ids.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from multihop_offload_tpu.graphs.generators import unit_disk_adjacency
from multihop_offload_tpu.graphs.topology import Topology, build_topology


def random_walk(
    pos: np.ndarray,
    n_moving: int = 10,
    step_std: float = 0.1,
    radius: float = 1.0,
    bounds: Optional[Tuple[float, float]] = None,
    rng: Optional[np.random.Generator] = None,
    max_tries: int = 1000,
) -> Tuple[np.ndarray, np.ndarray]:
    """Jitter `n_moving` random nodes by N(0, step_std) until the unit-disk
    graph stays connected; returns (new_pos, new_adj).

    Degenerate inputs degrade to a no-move step rather than erroring: an
    empty fleet, zero movers, or zero step size return the input positions
    unchanged, and an exhausted retry budget (radius too tight for any
    connected perturbation) falls back to the unperturbed graph when that
    one is itself connected — a mobility trace should stall, not crash,
    on a hard slot.  Only an input that is ALREADY disconnected raises.
    """
    rng = rng or np.random.default_rng()  # nondet-ok(explicit caller opt-in: no rng passed)
    n = pos.shape[0]
    if n == 0:
        return pos.copy(), np.zeros((0, 0), dtype=np.uint8)
    if n_moving <= 0 or step_std <= 0.0:
        return pos.copy(), unit_disk_adjacency(pos, radius)
    lo, hi = bounds if bounds is not None else (pos.min(), pos.max())
    for _ in range(max_tries):
        moving = rng.choice(n, size=min(n_moving, n), replace=False)
        cand = pos.copy()
        cand[moving] += rng.normal(0.0, step_std, (moving.size, 2))
        cand = cand.clip(lo, hi)
        adj = unit_disk_adjacency(cand, radius)
        if build_topology(adj).connected:
            return cand, adj
    adj = unit_disk_adjacency(pos, radius)
    if build_topology(adj).connected:
        return pos.copy(), adj
    raise RuntimeError("random_walk: no connected perturbation found")


def topology_update(
    old: Topology, new_adj: np.ndarray, pos: Optional[np.ndarray] = None,
    cf_radius: float = 0.0,
) -> Tuple[Topology, np.ndarray]:
    """Rebuild topology arrays after mobility; returns (new_topo, link_map)
    with link_map[i] = old canonical id of new link i, or -1 if the link is
    new (`offloading_v3.py:104-116` semantics on canonical ids)."""
    new_topo = build_topology(new_adj, pos=pos, cf_radius=cf_radius)
    link_map = np.full((new_topo.num_links,), -1, dtype=np.int64)
    for i, (u, v) in enumerate(new_topo.link_ends):
        if u < old.n and v < old.n:
            j = old.link_index[u, v]
            if j >= 0:
                link_map[i] = j
    return new_topo, link_map


def migrate_link_state(
    link_map: np.ndarray, old_state: np.ndarray, fill=0.0
) -> np.ndarray:
    """Carry per-link arrays (rates, queues) across a topology update."""
    new_state = np.full((link_map.shape[0],) + old_state.shape[1:], fill,
                        dtype=old_state.dtype)
    keep = link_map >= 0
    new_state[keep] = old_state[link_map[keep]]
    return new_state

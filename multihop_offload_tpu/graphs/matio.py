"""Reader/writer for the reference's `.mat` case schema.

Schema (verified against `/root/reference/data/aco_data_ba_100/*.mat`, written
by `data_generation_offloading.py:136-144`):
  network    (1,1) struct {num_nodes, seed, m, gtype}
  adj        sparse float (N, N)
  link_rate  (1, L) float
  nodes_info (N, 2) int   [role, proc_bw]
  pos_c      (N, 2) float

The `link_rate` vector is ordered by the NetworkX line-graph node order of
`nx.from_numpy_array(adj)` (that is the `link_list` the reference's
`links_init` assigns against, `offloading_v3.py:252-260` + `AdHoc_train.py:102`).
We store links in canonical sorted order, so the loader reproduces the
reference's ordering with one throwaway `nx.line_graph` call and permutes the
rates onto canonical link ids — the same physical link gets the same rate.
"""

from __future__ import annotations

import dataclasses
import os

import networkx as nx
import numpy as np
import scipy.io as sio
import scipy.sparse as sp

from multihop_offload_tpu.graphs.topology import Topology, build_topology


@dataclasses.dataclass
class CaseRecord:
    """One dataset case: topology + roles/resources, before padding."""

    topo: Topology
    roles: np.ndarray        # (n,) int
    proc_bws: np.ndarray     # (n,) float
    link_rates: np.ndarray   # (L,) float, canonical link order
    seed: int
    m: int
    gtype: str
    filename: str = ""

    @property
    def num_servers(self) -> int:
        return int((self.roles == 1).sum())

    @property
    def num_relays(self) -> int:
        return int((self.roles == 2).sum())

    @property
    def mobile_nodes(self) -> np.ndarray:
        return np.flatnonzero(self.roles == 0)

    @property
    def sizes(self):
        """(n, l, s, j_max) for PadSpec computation; j_max = mobile count."""
        return (
            self.topo.n,
            self.topo.num_links,
            self.num_servers,
            self.mobile_nodes.size,
        )


def reference_link_order(adj: np.ndarray) -> np.ndarray:
    """Map reference link positions -> canonical link ids.

    Returns `perm` with `perm[k]` = canonical id of the k-th link in the
    reference's `link_list` (NetworkX line-graph node order).
    """
    g = nx.from_numpy_array(np.asarray(adj))
    link_list = list(nx.line_graph(g).nodes)
    iu, ju = np.nonzero(np.triu(adj, k=1))
    order = np.lexsort((ju, iu))
    canon = {
        (int(iu[o]), int(ju[o])): k for k, o in enumerate(order)
    }
    perm = np.empty((len(link_list),), dtype=np.int64)
    for k, (u, v) in enumerate(link_list):
        a, b = (u, v) if u < v else (v, u)
        perm[k] = canon[(a, b)]
    return perm


def load_case_mat(path: str, cf_radius: float = 0.0) -> CaseRecord:
    """Load one `.mat` case (reference load path: `AdHoc_train.py:84-110`)."""
    m = sio.loadmat(path)
    adj = np.asarray(m["adj"].todense() if sp.issparse(m["adj"]) else m["adj"])
    adj = (adj != 0).astype(np.uint8)
    pos = np.asarray(m["pos_c"], dtype=np.float64)
    nodes_info = np.asarray(m["nodes_info"])
    link_rate = np.asarray(m["link_rate"]).flatten().astype(np.float64)
    net = m["network"][0, 0]
    seed = int(np.asarray(net["seed"]).flatten()[0])
    m_attach = int(np.asarray(net["m"]).flatten()[0])
    gtype = str(np.asarray(net["gtype"]).flatten()[0]) if "gtype" in net.dtype.names else "ba"

    topo = build_topology(adj, pos=pos, cf_radius=cf_radius)
    if link_rate.shape[0] != topo.num_links:
        raise ValueError(
            f"{path}: link_rate has {link_rate.shape[0]} entries, "
            f"graph has {topo.num_links} links"
        )
    rates_canon = np.empty_like(link_rate)
    rates_canon[reference_link_order(adj)] = link_rate

    return CaseRecord(
        topo=topo,
        roles=nodes_info[:, 0].astype(np.int32),
        proc_bws=nodes_info[:, 1].astype(np.float64),
        link_rates=rates_canon,
        seed=seed,
        m=m_attach,
        gtype=gtype,
        filename=os.path.basename(path),
    )


def save_case_mat(
    path: str,
    adj: np.ndarray,
    link_rates_canon: np.ndarray,
    nodes_info: np.ndarray,
    pos: np.ndarray,
    seed: int,
    m: int,
    gtype: str,
) -> None:
    """Write a case in the reference schema (readable by both frameworks).

    `link_rates_canon` is in canonical order; it is permuted back to the
    reference's line-graph order on disk so the reference code would assign
    identical rates to identical physical links.
    """
    perm = reference_link_order(adj)
    link_rate_ref = np.asarray(link_rates_canon, dtype=np.float64)[perm]
    num_nodes = int(adj.shape[0])
    sio.savemat(
        path,
        {
            "network": {
                "num_nodes": num_nodes, "seed": int(seed),
                "m": int(m), "gtype": gtype,
            },
            "adj": sp.csc_matrix(np.asarray(adj, dtype=np.float64)),
            "link_rate": link_rate_ref.reshape(1, -1),
            "nodes_info": np.asarray(nodes_info, dtype=np.int64),
            "pos_c": np.asarray(pos, dtype=np.float64),
        },
    )


def list_dataset(datapath: str):
    """Sorted case filenames, as the drivers do (`AdHoc_train.py:39`)."""
    return sorted(f for f in os.listdir(datapath) if f.endswith(".mat"))

from multihop_offload_tpu.graphs.generators import (  # noqa: F401
    barabasi_albert,
    erdos_renyi,
    gaussian_random_partition,
    poisson_disk,
    watts_strogatz,
    unit_disk_adjacency,
)
from multihop_offload_tpu.graphs.topology import Topology  # noqa: F401
from multihop_offload_tpu.graphs.instance import (  # noqa: F401
    Instance,
    JobSet,
    PadSpec,
    build_instance,
    stack_instances,
)
from multihop_offload_tpu.graphs.matio import (  # noqa: F401
    load_case_mat,
    save_case_mat,
    CaseRecord,
)

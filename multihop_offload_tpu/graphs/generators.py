"""Host-side random-topology generators.

Covers every graph family the reference environment can construct
(`/root/reference/src/offloading_v3.py:39-57`): Barabási–Albert, Gaussian
random partition, connected Watts–Strogatz, Erdős–Rényi, plus the Poisson
unit-disk process of the dataset generator
(`data_generation_offloading.py:34-50`).  Generation is cheap, irregular,
host-only work — NumPy/NetworkX is the right tool; everything downstream of
the returned dense adjacency is fixed-shape JAX.

All generators return ``(adj, pos)`` with ``adj`` a dense ``(n, n)`` uint8
symmetric 0/1 matrix with zero diagonal and ``pos`` an ``(n, 2)`` float array
of node coordinates (or ``None`` when the family has no natural geometry).

Beyond the reference families, the scenario matrix (`scenarios/`) adds
planned deployments the paper never evaluated: `grid` / `corridor`
lattices (warehouse / road-segment layouts) and `two_tier` clustered
edge/cloud topologies (dense local clusters bridged through a small cloud
core).  Everything downstream is family-agnostic — a family is just a name
in `GENERATORS` returning the same ``(adj, pos)`` contract.

Connectivity: the sim strands packets (and admission refuses with
``disconnected``) on a disconnected graph, so the random families whose
draws can disconnect (`erdos_renyi`, `gaussian_random_partition`) retry a
bounded number of times at increasing density, mirroring
`connected_poisson_disk`; the typed `DisconnectedGraphWarning` marks every
draw where the fallback engaged.
"""

from __future__ import annotations

import warnings
from typing import Optional, Tuple

import networkx as nx
import numpy as np
from scipy.spatial import distance_matrix


class DisconnectedGraphWarning(UserWarning):
    """A generator's nominal draw was disconnected and the bounded
    densify-and-retry fallback engaged (the returned graph IS connected,
    but denser than the family's nominal parameterization)."""


# bounded retry-to-connected: densify by _RETRY_GROWTH per attempt, give up
# (raise) after _MAX_CONNECT_TRIES total draws
_MAX_CONNECT_TRIES = 8
_RETRY_GROWTH = 1.5


def _is_connected(adj: np.ndarray) -> bool:
    return bool(nx.is_connected(nx.from_numpy_array(adj)))


def _retry_connected(draw, family: str, n: int):
    """Run `draw(attempt)` until the graph connects (bounded).

    `draw` maps an attempt index (0 = nominal parameters) to ``(adj, pos)``;
    the densification schedule lives in the caller's closure.  Mirrors
    `connected_poisson_disk`'s densify-until-connected loop, but bounded and
    with the typed warning contract."""
    for attempt in range(_MAX_CONNECT_TRIES):
        adj, pos = draw(attempt)
        if _is_connected(adj):
            return adj, pos
        if attempt == 0:
            warnings.warn(
                f"{family}(n={n}) drew a disconnected graph; densifying "
                f"and retrying (bounded, x{_RETRY_GROWTH} per attempt)",
                DisconnectedGraphWarning,
                stacklevel=3,
            )
    raise ValueError(
        f"{family}(n={n}) stayed disconnected after "
        f"{_MAX_CONNECT_TRIES} densifying retries"
    )


def _to_adj(g: nx.Graph, n: int) -> np.ndarray:
    adj = np.zeros((n, n), dtype=np.uint8)
    for u, v in g.edges:
        adj[u, v] = 1
        adj[v, u] = 1
    return adj


def barabasi_albert(n: int, m: int = 2, seed: int = 0) -> Tuple[np.ndarray, None]:
    """BA preferential attachment (reference `offloading_v3.py:39-40`)."""
    return _to_adj(nx.barabasi_albert_graph(n, m, seed=seed), n), None


def gaussian_random_partition(
    n: int, p_in: float = 0.4, p_out: float = 0.2, seed: int = 0
) -> Tuple[np.ndarray, None]:
    """GRP(n, 15, 3, p_in, p_out) (reference `offloading_v3.py:41-42`),
    densified-and-retried to connectivity (bounded)."""

    def draw(attempt):
        grow = _RETRY_GROWTH ** attempt
        g = nx.gaussian_random_partition_graph(
            n, 15, 3, min(p_in * grow, 1.0), min(p_out * grow, 1.0),
            seed=seed + 7919 * attempt,
        )
        return _to_adj(g, n), None

    return _retry_connected(draw, "gaussian_random_partition", n)


def watts_strogatz(n: int, k: int = 6, p: float = 0.2, seed: int = 0) -> Tuple[np.ndarray, None]:
    """Connected WS(k=6, p=0.2) (reference `offloading_v3.py:43-44`)."""
    g = nx.connected_watts_strogatz_graph(n, k=k, p=p, seed=seed)
    return _to_adj(g, n), None


def erdos_renyi(
    n: int, degree: float = 15.0, seed: int = 0
) -> Tuple[np.ndarray, None]:
    """ER with expected degree `degree` (reference `offloading_v3.py:45-46`),
    densified-and-retried to connectivity (bounded)."""

    def draw(attempt):
        p = min(degree * (_RETRY_GROWTH ** attempt) / float(n), 1.0)
        g = nx.fast_gnp_random_graph(n, p, seed=seed + 7919 * attempt)
        return _to_adj(g, n), None

    return _retry_connected(draw, "erdos_renyi", n)


def unit_disk_adjacency(pos: np.ndarray, radius: float = 1.0) -> np.ndarray:
    """Adjacency of a unit-disk graph over 2-D points.

    Same rule as the reference's mobility model (`offloading_v3.py:90-93`)
    and Poisson generator (`data_generation_offloading.py:45-48`).
    """
    n = pos.shape[0]
    d = distance_matrix(pos, pos)
    adj = (d <= radius).astype(np.uint8)
    np.fill_diagonal(adj, 0)
    return adj


def poisson_disk(
    n: int, nb: float = 4.0, radius: float = 1.0, seed: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """2-D Poisson point process with expected `nb` neighbors in unit radius.

    Mirrors `data_generation_offloading.py:34-50`: points uniform on a square
    sized so the point density is nb/pi per unit area.
    """
    rng = np.random.default_rng(seed)
    density = float(nb) / np.pi
    side = np.sqrt(float(n) / density)
    pos = rng.uniform(0, side, (int(n), 2))
    return unit_disk_adjacency(pos, radius), pos


def connected_poisson_disk(
    n: int, seed: Optional[int] = None, nb_start: float = 4.0
) -> Tuple[np.ndarray, np.ndarray, float]:
    """Increase density until the Poisson graph is connected
    (`data_generation_offloading.py:61-67`)."""
    nb = nb_start - 1
    while True:
        nb += 1
        adj, pos = poisson_disk(n, nb=nb, seed=seed)
        if nx.is_connected(nx.from_numpy_array(adj)):
            return adj, pos, nb


def _lattice(n: int, rows: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Row-major induced lattice over the first `n` cells of a rows x cols
    grid with unit spacing — connected by construction (row-major prefixes
    of a grid are connected).  Positions carry a small seeded jitter so the
    geometry is non-degenerate for mobility/plotting; adjacency is the
    exact lattice, independent of the jitter."""
    rows = max(int(rows), 1)
    cols = -(-n // rows)
    adj = np.zeros((n, n), dtype=np.uint8)
    for i in range(n):
        r, c = divmod(i, cols)
        if c + 1 < cols and i + 1 < n:          # east neighbor
            adj[i, i + 1] = adj[i + 1, i] = 1
        if i + cols < n:                        # south neighbor
            adj[i, i + cols] = adj[i + cols, i] = 1
    rng = np.random.default_rng(seed)
    grid_pos = np.stack(
        [np.arange(n) % cols, np.arange(n) // cols], axis=1
    ).astype(np.float64)
    pos = grid_pos + rng.uniform(-0.1, 0.1, (n, 2))
    return adj, pos


def grid_lattice(
    n: int, aspect: float = 1.0, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Near-square planned lattice (warehouse / campus floor-plan layout);
    `aspect` = rows/cols ratio of the bounding grid."""
    if aspect <= 0:
        raise ValueError("aspect must be positive")
    rows = max(int(round(np.sqrt(n * aspect))), 1)
    return _lattice(n, rows, seed=seed)


def corridor(n: int, width: int = 2, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Long thin lattice (road segment / tunnel / assembly line): `width`
    parallel lanes, length n/width — the maximum-diameter planned layout."""
    if width < 1:
        raise ValueError("width must be >= 1")
    return _lattice(n, min(int(width), n), seed=seed)


def two_tier(
    n: int, clusters: int = 3, core: int = 2, p_in: float = 0.5,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Clustered two-tier edge/cloud topology.

    `core` cloud nodes form a clique; the remaining nodes split round-robin
    into `clusters` edge clusters, each starred onto a cluster-head node
    (connected by construction) plus random intra-cluster chords with
    probability `p_in`; every cluster head uplinks to two cloud nodes
    (or one, when `core == 1`).  Nodes 0..core-1 are the cloud tier;
    nodes core..core+clusters-1 are the cluster heads — the heads
    aggregate their cluster's star plus the cloud uplinks, so they end up
    the highest-degree nodes and degree-ranked server placement puts the
    compute at the edge gateways (traffic multihops through a head either
    way, which is the regime the paper's policy is for).
    """
    if not 1 <= core < n:
        raise ValueError("need 1 <= core < n")
    clusters = max(1, min(int(clusters), n - core))
    rng = np.random.default_rng(seed)
    adj = np.zeros((n, n), dtype=np.uint8)
    for a in range(core):           # cloud clique
        for b in range(a + 1, core):
            adj[a, b] = adj[b, a] = 1
    members = [[] for _ in range(clusters)]
    for i in range(core, n):        # round-robin edge membership
        members[(i - core) % clusters].append(i)
    for c, nodes in enumerate(members):
        if not nodes:
            continue
        head = nodes[0]
        for v in nodes[1:]:         # star onto the head: connectivity
            adj[head, v] = adj[v, head] = 1
        for ai in range(1, len(nodes)):     # random intra-cluster chords
            for bi in range(ai + 1, len(nodes)):
                if rng.random() < p_in:
                    a, b = nodes[ai], nodes[bi]
                    adj[a, b] = adj[b, a] = 1
        up = (c % core, (c + 1) % core)     # head -> cloud gateways
        for g in set(up):
            adj[head, g] = adj[g, head] = 1
    # geometry: cloud at the origin, clusters on a surrounding circle
    pos = np.zeros((n, 2), dtype=np.float64)
    pos[:core] = rng.uniform(-0.5, 0.5, (core, 2))
    for c, nodes in enumerate(members):
        theta = 2.0 * np.pi * c / clusters
        center = 3.0 * np.array([np.cos(theta), np.sin(theta)])
        pos[nodes] = center + rng.uniform(-0.8, 0.8, (len(nodes), 2))
    return adj, pos


# family registry: callable + the family-specific kwargs it accepts.
# `generate` threads kwargs honestly — an unknown kwarg raises instead of
# being silently dropped (the old dispatch swallowed `m` for grp/ws/er).
_FAMILIES = {
    "ba": (barabasi_albert, ("m",)),
    "grp": (gaussian_random_partition, ("p_in", "p_out")),
    "ws": (watts_strogatz, ("k", "p")),
    "er": (erdos_renyi, ("degree",)),
    "poisson": (poisson_disk, ("nb", "radius")),
    "grid": (grid_lattice, ("aspect",)),
    "corridor": (corridor, ("width",)),
    "two_tier": (two_tier, ("clusters", "core", "p_in")),
}

# name -> callable(n, seed, **family_kwargs); kept as the public registry
GENERATORS = {
    name: (lambda n, seed, _f=fn, **kw: _f(n, seed=seed, **kw))
    for name, (fn, _) in _FAMILIES.items()
}


def generate(gtype: str, n: int, seed: int, m: Optional[int] = None, **kwargs):
    """Dispatch on graph-family name (reference `offloading_v3.py:39-59`).

    `m` is the legacy density shorthand: BA attachment degree / Poisson
    expected-neighbor count.  Passing it (or any kwarg) to a family that
    does not take it raises — parameters are threaded honestly, never
    silently dropped.
    """
    gtype = gtype.lower()
    if gtype not in _FAMILIES:
        raise ValueError(
            f"unsupported graph model '{gtype}' "
            f"(known: {', '.join(sorted(_FAMILIES))})"
        )
    fn, allowed = _FAMILIES[gtype]
    if m is not None:
        legacy = {"ba": "m", "poisson": "nb"}.get(gtype)
        if legacy is None:
            raise ValueError(
                f"graph family '{gtype}' does not take the density "
                f"parameter m; its parameters are {allowed or '()'}"
            )
        kwargs.setdefault(legacy, m)
    unknown = sorted(set(kwargs) - set(allowed))
    if unknown:
        raise ValueError(
            f"unknown parameter(s) {unknown} for graph family '{gtype}'; "
            f"it takes {allowed or '()'}"
        )
    return fn(n, seed=seed, **kwargs)


def spring_positions(
    adj: np.ndarray,
    seed: Optional[int] = None,
    cache_dir: Optional[str] = None,
    name: Optional[str] = None,
    fresh: bool = False,
) -> np.ndarray:
    """Spring layout for plotting (reference `offloading_v3.py:156,163`).

    With `cache_dir` + `name`, layouts are cached on disk (the reference
    pickles them under `../pos/`, `offloading_v3.py:152-163`; ours are .npy);
    `fresh=True` recomputes and overwrites (the reference's `pos='new'`).
    """
    import os

    path = None
    if cache_dir and name:
        path = os.path.join(cache_dir, f"{name}.npy")
        if not fresh and os.path.isfile(path):
            cached = np.load(path)
            if cached.shape == (adj.shape[0], 2):
                return cached
    g = nx.from_numpy_array(adj)
    pos = nx.spring_layout(g, seed=seed)
    out = np.stack([pos[i] for i in range(adj.shape[0])])
    if path is not None:
        os.makedirs(cache_dir, exist_ok=True)
        np.save(path, out)
    return out

"""Host-side random-topology generators.

Covers every graph family the reference environment can construct
(`/root/reference/src/offloading_v3.py:39-57`): Barabási–Albert, Gaussian
random partition, connected Watts–Strogatz, Erdős–Rényi, plus the Poisson
unit-disk process of the dataset generator
(`data_generation_offloading.py:34-50`).  Generation is cheap, irregular,
host-only work — NumPy/NetworkX is the right tool; everything downstream of
the returned dense adjacency is fixed-shape JAX.

All generators return ``(adj, pos)`` with ``adj`` a dense ``(n, n)`` uint8
symmetric 0/1 matrix with zero diagonal and ``pos`` an ``(n, 2)`` float array
of node coordinates (or ``None`` when the family has no natural geometry).
"""

from __future__ import annotations

from typing import Optional, Tuple

import networkx as nx
import numpy as np
from scipy.spatial import distance_matrix


def _to_adj(g: nx.Graph, n: int) -> np.ndarray:
    adj = np.zeros((n, n), dtype=np.uint8)
    for u, v in g.edges:
        adj[u, v] = 1
        adj[v, u] = 1
    return adj


def barabasi_albert(n: int, m: int = 2, seed: int = 0) -> Tuple[np.ndarray, None]:
    """BA preferential attachment (reference `offloading_v3.py:39-40`)."""
    return _to_adj(nx.barabasi_albert_graph(n, m, seed=seed), n), None


def gaussian_random_partition(n: int, seed: int = 0) -> Tuple[np.ndarray, None]:
    """GRP(n, 15, 3, 0.4, 0.2) (reference `offloading_v3.py:41-42`)."""
    g = nx.gaussian_random_partition_graph(n, 15, 3, 0.4, 0.2, seed=seed)
    return _to_adj(g, n), None


def watts_strogatz(n: int, k: int = 6, p: float = 0.2, seed: int = 0) -> Tuple[np.ndarray, None]:
    """Connected WS(k=6, p=0.2) (reference `offloading_v3.py:43-44`)."""
    g = nx.connected_watts_strogatz_graph(n, k=k, p=p, seed=seed)
    return _to_adj(g, n), None


def erdos_renyi(n: int, seed: int = 0) -> Tuple[np.ndarray, None]:
    """ER with expected degree 15 (reference `offloading_v3.py:45-46`)."""
    g = nx.fast_gnp_random_graph(n, 15.0 / float(n), seed=seed)
    return _to_adj(g, n), None


def unit_disk_adjacency(pos: np.ndarray, radius: float = 1.0) -> np.ndarray:
    """Adjacency of a unit-disk graph over 2-D points.

    Same rule as the reference's mobility model (`offloading_v3.py:90-93`)
    and Poisson generator (`data_generation_offloading.py:45-48`).
    """
    n = pos.shape[0]
    d = distance_matrix(pos, pos)
    adj = (d <= radius).astype(np.uint8)
    np.fill_diagonal(adj, 0)
    return adj


def poisson_disk(
    n: int, nb: float = 4.0, radius: float = 1.0, seed: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """2-D Poisson point process with expected `nb` neighbors in unit radius.

    Mirrors `data_generation_offloading.py:34-50`: points uniform on a square
    sized so the point density is nb/pi per unit area.
    """
    rng = np.random.default_rng(seed)
    density = float(nb) / np.pi
    side = np.sqrt(float(n) / density)
    pos = rng.uniform(0, side, (int(n), 2))
    return unit_disk_adjacency(pos, radius), pos


def connected_poisson_disk(
    n: int, seed: Optional[int] = None, nb_start: float = 4.0
) -> Tuple[np.ndarray, np.ndarray, float]:
    """Increase density until the Poisson graph is connected
    (`data_generation_offloading.py:61-67`)."""
    nb = nb_start - 1
    while True:
        nb += 1
        adj, pos = poisson_disk(n, nb=nb, seed=seed)
        if nx.is_connected(nx.from_numpy_array(adj)):
            return adj, pos, nb


GENERATORS = {
    "ba": lambda n, seed, m=2: barabasi_albert(n, m=m, seed=seed),
    "grp": lambda n, seed, m=2: gaussian_random_partition(n, seed=seed),
    "ws": lambda n, seed, m=2: watts_strogatz(n, seed=seed),
    "er": lambda n, seed, m=2: erdos_renyi(n, seed=seed),
    "poisson": lambda n, seed, m=2: poisson_disk(n, nb=m, seed=seed),
}


def generate(gtype: str, n: int, seed: int, m: int = 2):
    """Dispatch on graph-family name (reference `offloading_v3.py:39-59`)."""
    gtype = gtype.lower()
    if gtype not in GENERATORS:
        raise ValueError(f"unsupported graph model '{gtype}'")
    return GENERATORS[gtype](n, seed, m=m)


def spring_positions(
    adj: np.ndarray,
    seed: Optional[int] = None,
    cache_dir: Optional[str] = None,
    name: Optional[str] = None,
    fresh: bool = False,
) -> np.ndarray:
    """Spring layout for plotting (reference `offloading_v3.py:156,163`).

    With `cache_dir` + `name`, layouts are cached on disk (the reference
    pickles them under `../pos/`, `offloading_v3.py:152-163`; ours are .npy);
    `fresh=True` recomputes and overwrites (the reference's `pos='new'`).
    """
    import os

    path = None
    if cache_dir and name:
        path = os.path.join(cache_dir, f"{name}.npy")
        if not fresh and os.path.isfile(path):
            cached = np.load(path)
            if cached.shape == (adj.shape[0], 2):
                return cached
    g = nx.from_numpy_array(adj)
    pos = nx.spring_layout(g, seed=seed)
    out = np.stack([pos[i] for i in range(adj.shape[0])])
    if path is not None:
        os.makedirs(cache_dir, exist_ok=True)
        np.save(path, out)
    return out

"""Padded, fixed-shape device representation of a network instance.

The reference passes NetworkX objects and Python lists between every stage
(`offloading_v3.py`, `gnn_offloading_agent.py`); under XLA everything must be
a static-shape array.  `Instance` freezes one network (topology + roles +
capacities) into padded arrays; `JobSet` holds a padded workload.  Both are
pytrees, so a batch of instances is just the same structure with a leading
axis (`stack_instances`) and every environment kernel is written per-instance
and `vmap`'d.

Extended-line-graph layout (replaces `graph_expand`, `offloading_v3.py:262-339`):
slot ``e in [0, L)`` is real link ``e``; slot ``L + i`` is node ``i``'s
pseudo-link ("compute here", the reference's `(i, n+i)` edge).  This makes the
reference's `maps_ol_el` the identity and `maps_on_el[i] = L + i`, removing
every dynamic `list.index` lookup from the hot path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np
from flax import struct

from multihop_offload_tpu.graphs.topology import Topology


@dataclasses.dataclass(frozen=True)
class PadSpec:
    """Static pad sizes. E (extended slots) is always L + N by construction.

    `enn` / `cnn` bound the sparse layout's edge-list pads (nonzeros of the
    extended / conflict adjacency).  0 means "use the heuristic default" —
    generous for the BA workload graphs; builders RAISE (never truncate)
    when a graph exceeds the bound, and `dataclasses.replace(pad, enn=...)`
    sets an exact bound computed from data (`layouts.ext_nnz_count`).
    Dense-layout programs never read them.
    """

    n: int          # nodes
    l: int          # links
    s: int          # servers
    j: int          # jobs
    enn: int = 0    # extended-adjacency nnz pad (0 = heuristic default)
    cnn: int = 0    # conflict-adjacency nnz pad (0 = heuristic default)

    @property
    def e(self) -> int:
        return self.l + self.n

    @property
    def ext_nnz(self) -> int:
        # line-graph entries scale with sum(deg^2); 16 * E covers the BA
        # workload with slack (measured ~3.4k real vs 5.4k pad at N=110)
        return self.enn if self.enn > 0 else self.round_up(16 * self.e, 128)

    @property
    def cf_nnz(self) -> int:
        return self.cnn if self.cnn > 0 else self.round_up(16 * self.l, 128)

    @staticmethod
    def round_up(x: int, to: int) -> int:
        return int(-(-x // to) * to)

    @classmethod
    def for_cases(cls, sizes: Sequence[tuple], round_to: int = 8) -> "PadSpec":
        """sizes: iterable of (n, l, s, j) actual sizes."""
        arr = np.asarray(list(sizes), dtype=np.int64)
        n, l, s, j = (int(arr[:, k].max()) for k in range(4))
        r = lambda v: cls.round_up(max(v, 1), round_to)
        return cls(n=r(n), l=r(l), s=r(s), j=r(j))


@struct.dataclass
class Instance:
    """One padded network. All arrays fixed-shape; float dtype configurable."""

    # nodes
    adj: np.ndarray          # (N, N) float 0/1 connectivity
    node_mask: np.ndarray    # (N,) bool — real node
    roles: np.ndarray        # (N,) int32: 0 mobile / 1 server / 2 relay (pad=2)
    proc_bws: np.ndarray     # (N,) float processing bandwidth (relay/pad = 0)
    comp_mask: np.ndarray    # (N,) bool — node can compute (roles < 2, real)
    # links (canonical order; pad links have rate 1, zero conflict rows)
    link_ends: np.ndarray    # (L, 2) int32
    link_rates: np.ndarray   # (L,) float
    link_mask: np.ndarray    # (L,) bool
    link_index: np.ndarray   # (N, N) int32 edge -> link id (0 where no edge)
    adj_conflict: np.ndarray  # (L, L) float conflict-graph adjacency
    cf_degs: np.ndarray      # (L,) float conflict degrees
    # extended line graph (E = L + N slots)
    adj_ext: np.ndarray      # (E, E) float extended-line-graph adjacency
    ext_rate: np.ndarray     # (E,) float: link rate / node proc_bw
    ext_self_loop: np.ndarray  # (E,) float 1.0 on active pseudo-link slots
    ext_as_server: np.ndarray  # (E,) float 1.0 on server pseudo-links
    ext_mask: np.ndarray     # (E,) bool
    # servers, ascending node index (reference add-order, AdHoc_train.py:104-110)
    servers: np.ndarray      # (S,) int32 (pad = 0)
    server_mask: np.ndarray  # (S,) bool
    # precomputed unweighted APSP (reference `sp_hop`, AdHoc_train.py:135).
    # Hop counts depend only on the topology, so they are computed ONCE on
    # host at build time instead of re-running a min-plus APSP inside every
    # train/eval step (the reference recomputes Dijkstra hops per call,
    # `gnn_offloading_agent.py:304-305` — we beat that, not copy it).
    hop: np.ndarray          # (N, N) float hop counts (inf unreachable, 0 diag)
    # scalars
    T: np.ndarray            # () float congestion-penalty scale
    # sparse layout twin (layouts.SparseInstance): edge lists padded to the
    # PadSpec nnz bounds.  None under the dense layout — an EMPTY pytree
    # subtree, so stacking/vmap/jit are unaffected; sparse-layout programs
    # read these and leave the dense structural leaves to jit's unused-
    # argument pruning (that pruning IS the argument-bytes win).
    sparse: Optional[object] = None

    @property
    def num_pad_nodes(self) -> int:
        return self.adj.shape[-1]

    @property
    def num_pad_links(self) -> int:
        return self.link_rates.shape[-1]


@struct.dataclass
class JobSet:
    """Padded workload: one compute task stream per slot
    (reference `Job`, `offloading_v3.py:131-138`)."""

    src: np.ndarray    # (J,) int32 source node (pad = 0)
    rate: np.ndarray   # (J,) float arrival rate (pad = 0)
    ul: np.ndarray     # (J,) float uplink data size
    dl: np.ndarray     # (J,) float downlink data size
    mask: np.ndarray   # (J,) bool

    @property
    def num_jobs(self):
        return self.mask.sum()


def build_instance(
    topo: Topology,
    roles: np.ndarray,
    proc_bws: np.ndarray,
    link_rates: np.ndarray,
    t_max: float,
    pad: PadSpec,
    dtype=np.float32,
    hop: Optional[np.ndarray] = None,
    device: bool = True,
    layout=None,
) -> Instance:
    """Freeze a topology + resource assignment into a padded Instance.

    `hop` optionally supplies the padded (pad.n, pad.n) hop-count matrix —
    it depends only on the topology, so repeat builds of the same case
    (per-visit link-rate re-realization) can cache it (`compute_hop_matrix`).
    `device=False` keeps numpy leaves so callers that stack many instances
    can ship one batched transfer (`stack_instances`).
    `layout` (str | LayoutPolicy | None): under the sparse layout the
    Instance additionally carries edge-list twins of the structural matrices
    (`inst.sparse`, padded to `pad.ext_nnz`/`pad.cf_nnz`) and packs integer
    index maps at int16 (compact storage; guarded against overflow).
    """
    from multihop_offload_tpu.layouts import resolve_layout

    lay = resolve_layout(layout)
    n, l = topo.n, topo.num_links
    N, L, S = pad.n, pad.l, pad.s
    if n > N or l > L:
        raise ValueError(f"case ({n} nodes, {l} links) exceeds pad ({N}, {L})")

    roles = np.asarray(roles, dtype=np.int32)
    proc_bws = np.asarray(proc_bws, dtype=dtype)
    link_rates = np.asarray(link_rates, dtype=dtype)

    adj = np.zeros((N, N), dtype=dtype)
    adj[:n, :n] = topo.adj
    node_mask = np.zeros((N,), dtype=bool)
    node_mask[:n] = True
    roles_p = np.full((N,), 2, dtype=np.int32)
    roles_p[:n] = roles
    bws_p = np.zeros((N,), dtype=dtype)
    bws_p[:n] = proc_bws
    comp_mask = (roles_p < 2) & node_mask

    ends_p = np.zeros((L, 2), dtype=np.int32)
    ends_p[:l] = topo.link_ends
    rates_p = np.ones((L,), dtype=dtype)  # pad rate 1 avoids 0/0 in the FP
    rates_p[:l] = link_rates
    link_mask = np.zeros((L,), dtype=bool)
    link_mask[:l] = True
    # compact-int satellite: under the sparse layout the (N, N) link-id map
    # (the one dense int leaf sparse programs still read, for route tracing)
    # ships at int16 — link ids < L fit 15 bits, guarded at build time
    link_index = np.zeros((N, N), dtype=lay.index_dtype)
    if lay.index_dtype != np.int32:
        assert L - 1 <= np.iinfo(lay.index_dtype).max, (
            f"link pad {L} overflows {np.dtype(lay.index_dtype).name}"
        )
    link_index[:n, :n] = np.maximum(topo.link_index, 0)
    adj_cf = np.zeros((L, L), dtype=dtype)
    adj_cf[:l, :l] = topo.adj_conflict
    cf_degs = np.zeros((L,), dtype=dtype)
    cf_degs[:l] = topo.cf_degs

    # extended line graph: [0, L) real links, [L, L + N) pseudo-links
    E = pad.e
    ext_mask = np.concatenate([link_mask, comp_mask])
    ext_rate = np.concatenate([rates_p, bws_p]).astype(dtype)
    ext_self_loop = np.concatenate(
        [np.zeros((L,)), comp_mask.astype(np.float64)]
    ).astype(dtype)
    ext_as_server = np.zeros((E,), dtype=dtype)
    ext_as_server[L:][roles_p == 1] = 1.0  # reference `edge_as_server`, :317-326
    adj_ext = np.zeros((E, E), dtype=dtype)
    adj_ext[:L, :L][:l, :l] = topo.adj_lg  # pure line graph (not conflict-aug.)
    inc = np.zeros((L, N), dtype=dtype)    # link-node incidence, masked
    inc[np.arange(l), topo.link_ends[:, 0]] = 1.0
    inc[np.arange(l), topo.link_ends[:, 1]] = 1.0
    inc *= comp_mask[None, :].astype(dtype)
    adj_ext[:L, L:] = inc
    adj_ext[L:, :L] = inc.T

    if hop is None:
        hop = compute_hop_matrix(topo, N)
    hop = np.asarray(hop, dtype=dtype)

    server_ids = np.flatnonzero(roles_p == 1)
    if server_ids.size > S:
        raise ValueError(f"{server_ids.size} servers exceed pad {S}")
    servers = np.zeros((S,), dtype=np.int32)
    servers[: server_ids.size] = np.sort(server_ids)
    server_mask = np.zeros((S,), dtype=bool)
    server_mask[: server_ids.size] = True

    sparse = None
    if lay.sparse:
        from multihop_offload_tpu.layouts import build_sparse_instance

        sparse = build_sparse_instance(
            adj_ext, adj_cf, pad.ext_nnz, pad.cf_nnz, dtype=dtype
        )

    inst = Instance(
        adj=adj, node_mask=node_mask, roles=roles_p, proc_bws=bws_p,
        comp_mask=comp_mask, link_ends=ends_p, link_rates=rates_p,
        link_mask=link_mask, link_index=link_index, adj_conflict=adj_cf,
        cf_degs=cf_degs, adj_ext=adj_ext, ext_rate=ext_rate,
        ext_self_loop=ext_self_loop, ext_as_server=ext_as_server,
        ext_mask=ext_mask, servers=servers, server_mask=server_mask,
        hop=hop, T=np.asarray(t_max, dtype=dtype), sparse=sparse,
    )
    return to_device(inst) if device else inst


def compute_hop_matrix(topo: Topology, pad_n: int) -> np.ndarray:
    """Unweighted hop counts on host (scipy BFS), padded to (pad_n, pad_n):
    pad nodes are unreachable (inf) with a zero diagonal — identical to
    `env.apsp.hop_matrix(adj)` on the padded adjacency."""
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import shortest_path

    hop = np.full((pad_n, pad_n), np.inf)
    np.fill_diagonal(hop, 0.0)
    hop[: topo.n, : topo.n] = shortest_path(
        csr_matrix(topo.adj > 0), unweighted=True
    )
    return hop


def build_jobset(
    src: np.ndarray,
    rate: np.ndarray,
    pad_jobs: int,
    ul: float = 100.0,
    dl: float = 1.0,
    dtype=np.float32,
    device: bool = True,
    index_dtype=np.int32,
) -> JobSet:
    """Pad a concrete workload (job defaults from `offloading_v3.py:132`).

    `index_dtype`: storage dtype of the source-node index vector — the
    sparse layout packs at int16 (`LayoutPolicy.index_dtype`); node ids are
    guarded against the dtype range at build time."""
    src = np.asarray(src, dtype=np.int64)
    rate = np.asarray(rate, dtype=dtype)
    j = src.shape[0]
    J = pad_jobs
    if j > J:
        raise ValueError(f"{j} jobs exceed pad {J}")
    if j and index_dtype != np.int32:
        assert int(src.max()) <= np.iinfo(index_dtype).max, (
            f"job source ids overflow {np.dtype(index_dtype).name}"
        )
    src_p = np.zeros((J,), dtype=index_dtype)
    src_p[:j] = src
    rate_p = np.zeros((J,), dtype=dtype)
    rate_p[:j] = rate
    mask = np.zeros((J,), dtype=bool)
    mask[:j] = True
    js = JobSet(
        src=src_p, rate=rate_p,
        ul=np.full((J,), ul, dtype=dtype), dl=np.full((J,), dl, dtype=dtype),
        mask=mask,
    )
    return to_device(js) if device else js


def to_device(tree):
    """Convert every leaf to a jnp array (indexable under tracing)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, tree)


def stack_instances(items: Sequence):
    """Stack same-shape pytrees into a batched pytree (the vmap axis).

    numpy leaves (from `build_instance(..., device=False)`) are stacked on
    host and shipped in ONE transfer per leaf — batching N instances costs
    ~20 `device_put`s total instead of ~20N (the drivers' host pipeline is
    what end-to-end throughput amortizes; see benchmarks/README.md)."""
    import jax
    import jax.numpy as jnp

    if all(isinstance(leaf, np.ndarray) or np.isscalar(leaf)
           for leaf in jax.tree_util.tree_leaves(items[0])):
        return jax.tree_util.tree_map(
            lambda *xs: jnp.asarray(np.stack(xs)), *items
        )
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *items)

"""Topology: the host-side structural precompute.

Everything the reference derives with NetworkX object graphs —
line graph / conflict graph (`offloading_v3.py:65-77`), link index maps
(`link_mapping`, `:226-241`), physical-distance conflict augmentation
(`add_conflict_relations`, `:193-224`) — is computed here once per network,
vectorized in NumPy, and frozen into plain arrays.  Downstream JAX code never
touches a graph object.

Canonical orderings (a deliberate departure from the reference, which orders
links by NetworkX line-graph node insertion order): links are the edges
``(u, v), u < v`` sorted lexicographically.  Link ordering is unobservable in
the model — loads, delays, and decisions attach to physical links — so the
canonical order only permutes i.i.d. random link rates, which is
distribution-preserving.  See PARITY.md.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
from scipy.spatial import distance_matrix


@dataclasses.dataclass
class Topology:
    """Structural arrays for one connectivity graph (unpadded, host-side)."""

    n: int                      # number of nodes
    adj: np.ndarray             # (n, n) uint8 symmetric adjacency, zero diag
    link_ends: np.ndarray       # (L, 2) int32, u < v, lexicographic order
    link_index: np.ndarray      # (n, n) int32: edge -> link id, -1 elsewhere
    adj_lg: np.ndarray          # (L, L) uint8 line-graph adjacency
    adj_conflict: np.ndarray    # (L, L) uint8 conflict adjacency (>= adj_lg)
    cf_degs: np.ndarray         # (L,) int32 conflict degree per link
    pos: Optional[np.ndarray]   # (n, 2) float positions or None
    cf_radius: float = 0.0

    @property
    def num_links(self) -> int:
        return int(self.link_ends.shape[0])

    @property
    def mean_conflict_degree(self) -> float:
        # reference `offloading_v3.py:77`
        return float(self.cf_degs.mean()) if self.num_links else 0.0

    @property
    def connected(self) -> bool:
        """BFS connectivity check (reference uses `nx.is_connected`, `:60`)."""
        if self.n == 0:
            return False
        seen = np.zeros(self.n, dtype=bool)
        frontier = np.zeros(self.n, dtype=bool)
        frontier[0] = True
        while frontier.any():
            seen |= frontier
            frontier = (self.adj[frontier].any(axis=0)) & ~seen
        return bool(seen.all())


def _line_graph_adjacency(link_ends: np.ndarray, n: int) -> np.ndarray:
    """Links are adjacent iff they share an endpoint (nx.line_graph semantics,
    reference `offloading_v3.py:65`).  Vectorized via the node-link incidence
    matrix: A_lg = B @ B.T with shared-endpoint count, minus self-loops."""
    num_links = link_ends.shape[0]
    # float32 so the product runs through BLAS; entries are 0/1/2, exact
    inc = np.zeros((num_links, n), dtype=np.float32)
    rows = np.arange(num_links)
    inc[rows, link_ends[:, 0]] = 1
    inc[rows, link_ends[:, 1]] = 1
    shared = inc @ inc.T
    np.fill_diagonal(shared, 0)
    return (shared > 0).astype(np.uint8)


def _conflict_extra(
    link_ends: np.ndarray,
    adj_lg: np.ndarray,
    pos: np.ndarray,
    cf_radius: float,
) -> np.ndarray:
    """Physical-interference conflicts: two links conflict when any endpoint of
    one is within `cf_radius x median link distance` of an endpoint of the
    other.  Behavioral equivalent of `add_conflict_relations`
    (`offloading_v3.py:193-224`), vectorized."""
    d = distance_matrix(pos, pos)
    link_dist = d[link_ends[:, 0], link_ends[:, 1]]
    finite = link_dist[np.isfinite(link_dist)]
    if finite.size == 0:
        # linkless (or NaN-positioned) graph after a mobility step: no
        # distance scale exists, so no physical conflicts beyond adj_lg —
        # np.nanmedian would warn and poison `thresh` with NaN here
        return adj_lg.copy()
    thresh = cf_radius * np.median(finite)
    # near[l, v]: link l has an endpoint within thresh of node v
    near = (d[link_ends[:, 0], :] < thresh) | (d[link_ends[:, 1], :] < thresh)
    # links k whose some endpoint is a node near link l
    touches = near[:, link_ends[:, 0]] | near[:, link_ends[:, 1]]  # (L, L)
    conflict = (touches | touches.T).astype(np.uint8)
    np.fill_diagonal(conflict, 0)
    return np.maximum(conflict, adj_lg)


def build_topology(
    adj: np.ndarray,
    pos: Optional[np.ndarray] = None,
    cf_radius: float = 0.0,
) -> Topology:
    """Derive all structural arrays from a dense adjacency matrix."""
    adj = np.asarray(adj)
    n = adj.shape[0]
    iu, ju = np.nonzero(np.triu(adj, k=1))
    order = np.lexsort((ju, iu))
    link_ends = np.stack([iu[order], ju[order]], axis=1).astype(np.int32)
    num_links = link_ends.shape[0]

    link_index = -np.ones((n, n), dtype=np.int32)
    link_index[link_ends[:, 0], link_ends[:, 1]] = np.arange(num_links)
    link_index[link_ends[:, 1], link_ends[:, 0]] = np.arange(num_links)

    adj_lg = _line_graph_adjacency(link_ends, n)
    if cf_radius > 0.5:
        # reference gate `offloading_v3.py:72-75`
        if pos is None:
            raise ValueError("cf_radius interference needs node positions")
        adj_conflict = _conflict_extra(link_ends, adj_lg, np.asarray(pos), cf_radius)
    else:
        adj_conflict = adj_lg
    cf_degs = adj_conflict.sum(axis=0).astype(np.int32)

    return Topology(
        n=n,
        adj=adj.astype(np.uint8),
        link_ends=link_ends,
        link_index=link_index,
        adj_lg=adj_lg,
        adj_conflict=adj_conflict,
        cf_degs=cf_degs,
        pos=None if pos is None else np.asarray(pos, dtype=np.float64),
        cf_radius=float(cf_radius),
    )


def sample_link_rates(
    topo: Topology,
    rates,
    std: float = 2.0,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Per-link capacities: round(clip(N(rate, std), 0, rate + 3*std)).

    Mirrors `links_init` (`offloading_v3.py:252-260`).  `rates` is a scalar or
    an (L,)-vector in canonical link order.
    """
    rng = rng or np.random.default_rng()  # nondet-ok(explicit caller opt-in: no rng passed)
    rates = np.asarray(rates, dtype=np.float64)
    if rates.ndim == 1:
        assert rates.shape[0] == topo.num_links
    noisy = rng.normal(rates, std, size=(topo.num_links,))
    return np.round(np.clip(noisy, 0.0, rates + 3.0 * std))

"""Closed-loop fleet simulation: one jitted scan-of-scans, vmapped.

Program structure (compiles exactly once per `FleetSim`):

    vmap over fleet instances
      scan over policy rounds                # R iterations
        policy_fn(inst, jobs_est, ...)       # re-decide on measured rates
        scan over slots                      # K iterations of sim_slot_step

The policy runs *inside* the compiled program once per round — an
unconditional outer-scan step rather than a `lax.cond` on the slot index,
because under `vmap` a cond executes both branches anyway and the
round/slot split keeps the hot inner loop free of the policy's APSP.
`jobs_est` replaces the ground-truth arrival rates with the windowed
empirical estimate ``packets_generated / (K * dt * ul)`` from the
*previous* round, so every policy (GNN / baseline / local) is evaluated
on what it could actually observe; round 0 uses the caller's
`init_rates` (true rates for fidelity studies, zeros for cold start).

Host-level dynamics (mobility re-wiring rebuilds the topology with NumPy)
cannot live inside the scan; instead a run is *segmented*: call
`FleetSim.run` repeatedly, migrating `SimState` queues between topologies
with `graphs.mobility.migrate_link_state` — every segment reuses the same
compiled program as long as padded shapes hold (verified by the
zero-unexpected-retrace gate in `sim.fidelity`).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.obs import jaxhooks
from multihop_offload_tpu.obs import prof as obs_prof
from multihop_offload_tpu.obs import trace as obs_trace
from multihop_offload_tpu.obs.registry import registry
from multihop_offload_tpu.obs.spans import span
from multihop_offload_tpu.sim.state import (
    SimParams,
    SimRoutes,
    SimSpec,
    SimState,
    init_state,
    liveness_masks,
)
from multihop_offload_tpu.sim.step import sim_devmetrics, sim_slot_step


@struct.dataclass
class SimRun:
    """Result of one simulated segment (leading fleet axis when batched)."""

    state: SimState          # final state, all counters cumulative
    routes: SimRoutes        # last policy decision in force
    est_rates: jnp.ndarray   # (R, J) per-round empirical rate estimates
    sched: jnp.ndarray | None  # (R, K, L) bool schedule trace, if collected
    dev: Any = ()            # devmetrics accumulators for THIS segment


def simulate(
    inst: Instance,
    jobs: JobSet,
    spec: SimSpec,
    params: SimParams,
    policy_fn: Callable,
    state: SimState,
    init_rates: jnp.ndarray,
    key: jax.Array,
    rounds: int,
    slots_per_round: int,
    collect_schedule: bool = False,
    dm=None,
) -> SimRun:
    """Run `rounds * slots_per_round` slots on one instance (pure, jittable).

    With a `sim_devmetrics` declaration `dm`, the per-slot accumulators
    ride the scan carries and come back as `SimRun.dev` — one window per
    segment, starting from zeros."""
    j = spec.num_jobs
    n = spec.num_nodes
    fdt = state.delay_sum.dtype

    def round_body(carry, xs):
        st, dev, prev_gen, _ = carry
        kr, is_first = xs
        k_dec, k_slots = jax.random.split(kr)
        node_up, link_up = liveness_masks(inst, params, st.t)
        window = (st.generated - prev_gen)[:j].astype(fdt)
        denom = (
            slots_per_round * params.dt.astype(fdt)
            * jnp.maximum(jobs.ul.astype(fdt), 1e-9)
        )
        est = jnp.where(is_first, init_rates.astype(fdt), window / denom)
        jobs_est = jobs.replace(rate=est.astype(jobs.rate.dtype))
        routes = policy_fn(inst, jobs_est, node_up, link_up, k_dec)

        def slot_body(c, kk):
            s, d = c
            if dm is None:
                s2, sched = sim_slot_step(
                    inst, spec, params, routes, jobs, s, kk
                )
            else:
                s2, sched, d = sim_slot_step(
                    inst, spec, params, routes, jobs, s, kk, dm=dm, dev=d
                )
            return (s2, d), (sched if collect_schedule else None)

        (st2, dev2), scheds = jax.lax.scan(
            slot_body, (st, dev), jax.random.split(k_slots, slots_per_round)
        )
        return (st2, dev2, st.generated, routes), (est, scheds)

    from multihop_offload_tpu.layouts import NEXT_HOP_DTYPE

    routes0 = SimRoutes(
        dst=jnp.zeros((j,), jnp.int32),
        # compact int16 table — dtype must match what policy_fn emits
        # (layouts.pack_next_hop) or the round-scan carry mismatches
        next_hop=jnp.zeros((n, n), NEXT_HOP_DTYPE),  # dense-ok(scan-carry seed for the policy's forwarding table)
        reach=jnp.zeros((n, n), bool),               # dense-ok(scan-carry seed, same constraint)
    )
    xs = (
        jax.random.split(key, rounds),
        jnp.arange(rounds, dtype=jnp.int32) == 0,
    )
    dev0 = dm.init() if dm is not None else ()
    (st_f, dev_f, _, routes_f), (ests, scheds) = jax.lax.scan(
        round_body, (state, dev0, state.generated, routes0), xs
    )
    return SimRun(state=st_f, routes=routes_f, est_rates=ests, sched=scheds,
                  dev=dev_f)


class FleetSim:
    """Compile-once driver for a fleet of same-shaped instances.

    All static choices (spec, policy, horizon, schedule collection) are
    fixed at construction; `run` only ever feeds arrays, so repeated
    segments hit the same executable.  Instrumented through `obs`:
    `sim/build` wraps construction, `sim/scan` wraps each (blocking)
    segment, and the `mho_sim_*` metrics accumulate across segments.
    """

    def __init__(
        self,
        spec: SimSpec,
        policy_fn: Callable,
        rounds: int,
        slots_per_round: int,
        collect_schedule: bool = False,
        dtype=jnp.float32,  # fp32-island(sim accumulators; precision only narrows the policy APSP)
        devmetrics: bool = True,
    ):
        self.spec = spec
        self.rounds = rounds
        self.slots_per_round = slots_per_round
        self.collect_schedule = collect_schedule
        self.dtype = dtype
        # declared before the first trace — a compile-time constant
        self.devmetrics = sim_devmetrics(spec) if devmetrics else None
        self.last_devmetrics: dict | None = None
        with span("sim/build", rounds=rounds, slots=slots_per_round):
            def one(inst, jobs, params, state, init_rates, key):
                return simulate(
                    inst, jobs, spec, params, policy_fn, state,
                    init_rates, key, rounds, slots_per_round,
                    collect_schedule, dm=self.devmetrics,
                )

            # registers with the prof layer on the first segment (AOT
            # compile + cost analysis under the name every segment reuses)
            self._fn = obs_prof.wrap("sim/scan", jax.jit(jax.vmap(one)))

    def init_states(self, fleet: int) -> SimState:
        s = init_state(self.spec, self.dtype)
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (fleet,) + x.shape), s
        )

    def run(
        self,
        insts: Instance,
        jobss: JobSet,
        paramss: SimParams,
        keys: jax.Array,
        states: SimState | None = None,
        init_rates: jnp.ndarray | None = None,
        request_ids=None,
        tag: str = "",
    ) -> SimRun:
        """Simulate one segment for the whole (stacked) fleet.

        `request_ids` (one per lane, e.g. the held-out requests an A/B
        validation replays) stamps a per-lane ``sim_outcome`` trace hop so
        a traced request's journey includes its simulated fate."""
        fleet = int(keys.shape[0])
        if states is None:
            states = self.init_states(fleet)
        if init_rates is None:
            init_rates = jnp.zeros((fleet, self.spec.num_jobs), self.dtype)
        prev_gen = int(jnp.sum(states.generated))
        prev_del = int(jnp.sum(states.delivered))
        prev_drop = int(jnp.sum(states.dropped))
        with span("sim/scan", block=True, fleet=fleet):
            t0 = time.perf_counter()  # nondet-ok(device-time accounting is a measurement)
            out = self._fn(insts, jobss, paramss, states, init_rates, keys)
            jax.block_until_ready(out.state.t)
            self._fn.account(time.perf_counter() - t0)  # nondet-ok(same measurement)
        reg = registry()
        reg.counter(
            "mho_sim_slots_total", "simulated slots across the fleet"
        ).inc(fleet * self.rounds * self.slots_per_round)
        reg.counter(
            "mho_sim_policy_rounds_total", "policy re-decisions executed"
        ).inc(fleet * self.rounds)
        reg.counter(
            "mho_sim_packets_generated_total", "packets born"
        ).inc(int(jnp.sum(out.state.generated)) - prev_gen)
        reg.counter(
            "mho_sim_packets_delivered_total", "packets delivered end to end"
        ).inc(int(jnp.sum(out.state.delivered)) - prev_del)
        reg.counter(
            "mho_sim_packets_dropped_total", "packets lost"
        ).inc(int(jnp.sum(out.state.dropped)) - prev_drop)
        reg.gauge(
            "mho_sim_in_flight", "packets queued at segment end"
        ).set(int(jnp.sum(out.state.count[..., :-1])))
        if self.devmetrics is not None:
            # rides the sync boundary the span above already paid for;
            # flush merges the fleet's vmap lanes into one window (and
            # fetches the accumulators in one packed transfer)
            self.last_devmetrics = self.devmetrics.flush(out.dev, reg=reg)
        if request_ids:
            st = jax.tree_util.tree_map(np.asarray, out.state)
            obs_trace.hop(
                "sim_outcome", request_ids, tag=tag,
                delivered=st.delivered.sum(axis=1).astype(int).tolist(),
                dropped=st.dropped.sum(axis=1).astype(int).tolist(),
                generated=st.generated.sum(axis=1).astype(int).tolist(),
            )
        return out

    def mark_steady(self) -> None:
        """Call after the first completed segment: later retraces count as
        unexpected (`jax_unexpected_retraces_total`)."""
        jaxhooks.mark_steady()

"""Simulator state: fixed-capacity ring-buffer queues + counters as a pytree.

The discrete-time simulator models every packet explicitly, but under XLA
all queue storage must be static-shape.  One network instance carries
``Q = 2L + N`` FIFO queues laid out after the extended-line-graph idiom
(`graphs.instance`): queue ``l in [0, L)`` is link ``l`` in its canonical
u->v direction, ``L + l`` is the reverse v->u direction (the channel is
shared — scheduling and service are per *undirected* link — but forwarding
needs to know which endpoint a packet exits at), and ``2L + i`` is node
``i``'s server queue.  Each queue is a ring buffer of `cap` packet records
(stream id, stream-birth slot, queue-entry slot); one extra scratch row
absorbs masked-out scatter writes, the repo's standard dummy-row trick.

Streams: job ``j`` contributes an uplink packet stream (id ``j``, rate
``rate_j * ul_j`` packets per time unit, src -> dst -> server) and an
independent downlink stream (id ``J + j``, rate ``rate_j * dl_j``,
dst -> src) — the same open-network flow decomposition the analytic
M/M/1 model applies (`env.queueing.run_empirical` charges links
``(ul + dl) * rate`` and servers ``ul * rate``), so the two models are
comparable stream by stream.

Time: one slot is ``dt`` time units, sized so every per-slot probability
is a valid Bernoulli parameter (`build_sim_params` derives
``dt = 1 / (margin * max link rate)`` by default); servers may complete
several packets per slot (deterministic floor + Bernoulli remainder),
links at most one — a link transmission is a multi-slot geometric hold of
the shared channel.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
from flax import struct

from multihop_offload_tpu.graphs.instance import Instance, JobSet


@dataclasses.dataclass(frozen=True)
class SimSpec:
    """Static (Python-level) sizes: changing any of these recompiles."""

    num_links: int      # L (padded)
    num_nodes: int      # N (padded)
    num_jobs: int       # J (padded)
    cap: int = 64       # ring-buffer capacity per queue

    @property
    def num_queues(self) -> int:
        return 2 * self.num_links + self.num_nodes

    @property
    def num_streams(self) -> int:
        return 2 * self.num_jobs


@struct.dataclass
class SimParams:
    """Per-instance dynamics parameters (arrays — value changes never
    retrace).  Failure schedules use slot -1 for "never fails"."""

    dt: jnp.ndarray             # () slot duration in model time units
    link_srv_p: jnp.ndarray     # (L,) per-slot completion prob of a held link
    srv_rate: jnp.ndarray       # (N,) expected server completions per slot
    arr_p: jnp.ndarray          # (2J,) per-slot packet-arrival prob per stream
    fail_link_slot: jnp.ndarray  # (L,) int32 slot the link dies (-1 = never)
    fail_node_slot: jnp.ndarray  # (N,) int32 slot the node dies (-1 = never)


@struct.dataclass
class SimRoutes:
    """The policy's routing decision, fixed between policy rounds."""

    dst: jnp.ndarray        # (J,) int32 compute destination per job
    next_hop: jnp.ndarray   # (N, N) int16 greedy forwarding table
    #                         (layouts.pack_next_hop — node ids are < N)
    reach: jnp.ndarray      # (N, N) bool: destination reachable from node


@struct.dataclass
class SimState:
    """All mutable simulator state for one instance."""

    # ring buffers, (Q + 1, cap): row Q is the masked-write scratch row
    buf_stream: jnp.ndarray   # int16 stream id of each stored packet (ids are
    #                           < 2J; used as scatter indices -> int16 floor,
    #                           layouts.compact_index_dtype)
    buf_birth: jnp.ndarray    # int32 slot the packet entered the network
    buf_enq: jnp.ndarray      # int32 slot the packet entered THIS queue
    head: jnp.ndarray         # (Q + 1,) int32 ring head index
    count: jnp.ndarray        # (Q + 1,) int32 packets stored
    # conservation counters, per stream (2J,)
    generated: jnp.ndarray    # int32 packets born (incl. dropped at entry)
    delivered: jnp.ndarray    # int32 packets that completed their journey
    dropped: jnp.ndarray      # int32 packets lost (full queue / no route)
    delay_sum: jnp.ndarray    # float end-to-end slots summed over delivered
    # per-queue service statistics (Q + 1,)
    q_sojourn: jnp.ndarray    # float sum of (dequeue - enqueue) slots
    q_served: jnp.ndarray     # int32 packets dequeued
    q_busy: jnp.ndarray       # int32 slots with a nonempty queue
    q_arrived: jnp.ndarray    # int32 packets enqueued
    sched_slots: jnp.ndarray  # (L,) int32 slots each link won the schedule
    t: jnp.ndarray            # () int32 current slot


def init_state(spec: SimSpec, dtype=jnp.float32) -> SimState:  # fp32-island(delay accumulators: bf16 drops +1 past 256)
    from multihop_offload_tpu.layouts import compact_index_dtype

    q1 = spec.num_queues + 1
    c = spec.cap
    s = spec.num_streams
    i32 = jnp.int32
    # stream ids fit the narrowest index dtype for [0, 2J) — int16 in
    # practice; the bound is static so the choice can never overflow
    sdt = compact_index_dtype(max(spec.num_streams - 1, 0))
    return SimState(
        buf_stream=jnp.zeros((q1, c), sdt),
        buf_birth=jnp.zeros((q1, c), i32),
        buf_enq=jnp.zeros((q1, c), i32),
        head=jnp.zeros((q1,), i32),
        count=jnp.zeros((q1,), i32),
        generated=jnp.zeros((s,), i32),
        delivered=jnp.zeros((s,), i32),
        dropped=jnp.zeros((s,), i32),
        delay_sum=jnp.zeros((s,), dtype),
        q_sojourn=jnp.zeros((q1,), dtype),
        q_served=jnp.zeros((q1,), i32),
        q_busy=jnp.zeros((q1,), i32),
        q_arrived=jnp.zeros((q1,), i32),
        sched_slots=jnp.zeros((spec.num_links,), i32),
        t=jnp.zeros((), i32),
    )


def spec_for(inst: Instance, jobs: JobSet, cap: int = 64) -> SimSpec:
    return SimSpec(
        num_links=inst.num_pad_links,
        num_nodes=inst.num_pad_nodes,
        num_jobs=int(jobs.src.shape[-1]),
        cap=cap,
    )


def build_sim_params(
    inst: Instance,
    jobs: JobSet,
    dt: float | None = None,
    margin: float = 1.25,
    fail_link_slot: np.ndarray | None = None,
    fail_node_slot: np.ndarray | None = None,
) -> SimParams:
    """Derive slot-level probabilities from the instance's model-time rates.

    `dt` defaults to ``1 / (margin * max real link rate)`` so the busiest
    link's per-slot completion probability is ``1/margin < 1`` — the
    geometric service approximation of an exponential server is only valid
    with per-slot probabilities below 1 (servers are exempt: they drain
    multiple packets per slot via the floor+Bernoulli split).
    """
    rates = np.asarray(inst.link_rates, dtype=np.float64)
    mask = np.asarray(inst.link_mask)
    real_max = float(rates[mask].max()) if mask.any() else 1.0
    if dt is None:
        dt = 1.0 / (margin * max(real_max, 1e-9))
    dt = float(dt)
    link_srv_p = np.where(mask, np.clip(rates * dt, 0.0, 1.0), 0.0)
    srv_rate = np.asarray(inst.proc_bws, dtype=np.float64) * dt

    rate = np.asarray(jobs.rate, dtype=np.float64)
    ul = np.asarray(jobs.ul, dtype=np.float64)
    dl = np.asarray(jobs.dl, dtype=np.float64)
    jmask = np.asarray(jobs.mask)
    arr_ul = np.where(jmask, rate * ul * dt, 0.0)
    arr_dl = np.where(jmask, rate * dl * dt, 0.0)
    arr_p = np.clip(np.concatenate([arr_ul, arr_dl]), 0.0, 1.0)

    num_links = rates.shape[0]
    n = srv_rate.shape[0]
    fls = (np.full((num_links,), -1, np.int32) if fail_link_slot is None
           else np.asarray(fail_link_slot, np.int32))
    fns = (np.full((n,), -1, np.int32) if fail_node_slot is None
           else np.asarray(fail_node_slot, np.int32))

    f = inst.link_rates.dtype
    return SimParams(
        dt=jnp.asarray(dt, f),
        link_srv_p=jnp.asarray(link_srv_p, f),
        srv_rate=jnp.asarray(srv_rate, f),
        arr_p=jnp.asarray(arr_p, f),
        fail_link_slot=jnp.asarray(fls),
        fail_node_slot=jnp.asarray(fns),
    )


def liveness_masks(inst: Instance, params: SimParams, t: jnp.ndarray):
    """(node_up (N,), link_up (L,)) at slot `t`: a link is up while its own
    schedule and both endpoints are alive; padding is always down."""
    node_up = (params.fail_node_slot < 0) | (t < params.fail_node_slot)
    node_up = node_up & inst.node_mask
    u, v = inst.link_ends[:, 0], inst.link_ends[:, 1]
    link_up = (params.fail_link_slot < 0) | (t < params.fail_link_slot)
    link_up = link_up & node_up[u] & node_up[v] & inst.link_mask
    return node_up, link_up


def migrate_sim_state(
    state: SimState, link_map: np.ndarray, spec: SimSpec
) -> SimState:
    """Carry one lane's queue state across a mobility topology update.

    Host-side companion of `graphs.mobility.migrate_link_state` for the
    segmented-run pattern (see `sim.runner`): `link_map[i]` is the old
    canonical id of new link `i` (-1 = new link).  Both direction queues of
    a surviving link follow it to its new id with their packets and service
    statistics; server queues and the global counters carry over unchanged.
    Packets stranded in queues of vanished links are lost at the re-wiring
    boundary and counted into `dropped` per stream, so `conservation_gap`
    stays zero across segments.  Padded shapes must match `spec` (the whole
    point of the pattern is to reuse the compiled program).
    """
    num_links, n, c = spec.num_links, spec.num_nodes, spec.cap
    q1 = spec.num_queues + 1
    link_map = np.asarray(link_map, np.int64)

    # perm[new_row] = old_row, or -1 for rows that start out empty
    perm = np.full((q1,), -1, np.int64)
    nl = min(link_map.shape[0], num_links)
    for i in range(nl):
        j = int(link_map[i])
        if j >= 0:
            perm[i] = j
            perm[num_links + i] = num_links + j
    perm[2 * num_links:2 * num_links + n] = np.arange(
        2 * num_links, 2 * num_links + n, dtype=np.int64
    )
    keep = perm >= 0
    src = np.where(keep, perm, 0)

    def rows(a):
        a = np.asarray(a)
        sel = keep.reshape((-1,) + (1,) * (a.ndim - 1))
        return np.where(sel, a[src], 0).astype(a.dtype)

    # packets stranded in unclaimed rows are dropped at the boundary
    claimed = np.zeros((q1,), bool)
    claimed[perm[keep]] = True
    claimed[q1 - 1] = True  # scratch row holds garbage, never real packets
    old_head = np.asarray(state.head)
    old_count = np.asarray(state.count)
    old_stream = np.asarray(state.buf_stream)
    dropped = np.asarray(state.dropped).astype(np.int64).copy()
    for q in np.flatnonzero(~claimed[: q1 - 1] & (old_count[: q1 - 1] > 0)):
        idx = (old_head[q] + np.arange(old_count[q], dtype=np.int64)) % c
        np.add.at(dropped, old_stream[q, idx], 1)

    sched = np.asarray(state.sched_slots)
    new_sched = np.where(keep[:num_links], sched[src[:num_links]], 0)

    return SimState(
        buf_stream=jnp.asarray(rows(state.buf_stream)),
        buf_birth=jnp.asarray(rows(state.buf_birth)),
        buf_enq=jnp.asarray(rows(state.buf_enq)),
        head=jnp.asarray(rows(state.head)),
        count=jnp.asarray(rows(state.count)),
        generated=jnp.asarray(np.asarray(state.generated)),
        delivered=jnp.asarray(np.asarray(state.delivered)),
        dropped=jnp.asarray(
            dropped.astype(np.asarray(state.dropped).dtype)
        ),
        delay_sum=jnp.asarray(np.asarray(state.delay_sum)),
        q_sojourn=jnp.asarray(rows(state.q_sojourn)),
        q_served=jnp.asarray(rows(state.q_served)),
        q_busy=jnp.asarray(rows(state.q_busy)),
        q_arrived=jnp.asarray(rows(state.q_arrived)),
        sched_slots=jnp.asarray(new_sched.astype(sched.dtype)),
        t=jnp.asarray(np.asarray(state.t)),
    )


def in_flight(state: SimState) -> jnp.ndarray:
    """Total packets currently stored across all real queues."""
    return jnp.sum(state.count[:-1])


def conservation_gap(state: SimState) -> jnp.ndarray:
    """generated - delivered - dropped - in_flight; zero when no packet was
    created or destroyed outside the accounted transitions."""
    return (
        jnp.sum(state.generated)
        - jnp.sum(state.delivered)
        - jnp.sum(state.dropped)
        - in_flight(state)
    )

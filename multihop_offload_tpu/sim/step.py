"""One slot of the packet simulator, as fixed-shape masked array math.

Per slot, in order (all on the slot-start state, so nothing is served the
slot it arrives):

1. **Link scheduling** — contenders are up links with backlog; greedy MWIS
   on the conflict graph (`env.scheduling.local_greedy_mwis`) with
   backlog-plus-uniform-jitter weights picks a conflict-free active set
   (jitter breaks equal-backlog ties randomly instead of by index, which
   would starve high-index links).  A scheduled link completes its
   head-of-line packet with probability ``rate * dt`` — the geometric
   multi-slot channel hold whose mean matches the exponential service time
   the analytic M/M/1 model assumes.  Of the two direction queues sharing
   the channel, the older head-of-line packet is served first.
2. **Server drain** — node ``i`` completes ``floor(bw*dt) +
   Bernoulli(frac(bw*dt))`` packets (capped by its queue); uplink packets
   completing service are *delivered*.
3. **Forwarding** — every completed link packet exits at the link's far
   endpoint and either (a) reaches its destination: downlink packets are
   delivered, uplink packets join the destination's server queue, or
   (b) descends the policy's next-hop table one more hop.  A packet whose
   next hop is invalid (failed link, unreachable destination after a
   failure) is dropped and counted.
4. **Arrivals** — per stream, one Bernoulli packet per slot (prob
   ``rate * size * dt``); uplink packets of local jobs enter the server
   queue directly, everything else enters its first link queue.
5. **Enqueue** — forwarded packets and arrivals are appended FIFO; packets
   racing into the same queue are ordered (links by id, then streams by
   id) via a one-hot rank cumsum; appends beyond `cap` are dropped and
   counted.  Masked scatter writes land in the scratch row, the repo's
   standard dummy-slot trick.

In-flight packets always chase the *current* routing decision: `dest` and
`next_hop` are read from the live `SimRoutes`, so a policy round that
re-offloads a job redirects its queued packets too (the decision takes
effect network-wide, matching how the analytic evaluator re-scores whole
flows).  Conservation (`generated = delivered + dropped + in-flight`)
holds exactly by construction; `tests/test_sim.py` asserts it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multihop_offload_tpu.env.scheduling import local_greedy_mwis
from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.obs.devmetrics import DevMetrics, pow2_buckets
from multihop_offload_tpu.sim.state import (
    SimParams,
    SimRoutes,
    SimSpec,
    SimState,
    liveness_masks,
)

# Devmetric keys (declaration labels are part of the key, see
# `obs.devmetrics._default_key`).  The three drop reasons partition
# `SimState.dropped` exactly: per packet, `drop_l` / `drop_a` /
# `put & ~space_ok` are mutually exclusive, so the summed per-reason
# counters reproduce the state's OR-accumulated total bit for bit.
DM_GENERATED = "mho_dev_sim_packets_generated_total"
DM_DELIVERED = "mho_dev_sim_packets_delivered_total"
DM_DROP_FWD = "mho_dev_sim_dropped_total{reason=no_route_forward}"
DM_DROP_ARR = "mho_dev_sim_dropped_total{reason=no_route_arrival}"
DM_DROP_CAP = "mho_dev_sim_dropped_total{reason=capacity}"
DM_FWD_LINK = "mho_dev_sim_forwarded_total{target=link}"
DM_FWD_SERVER = "mho_dev_sim_forwarded_total{target=server}"
DM_QUEUE_DEPTH = "mho_dev_sim_queue_depth"
DM_NONFINITE = "mho_dev_sim_nonfinite_total"


def sim_devmetrics(spec: SimSpec) -> DevMetrics:
    """Declare the sim hot loop's device metrics (frozen, trace-safe)."""
    dm = DevMetrics()
    dm.counter(DM_GENERATED, "packets born, counted in-program per slot")
    dm.counter(DM_DELIVERED, "packets delivered (server drain + downlink at destination)")
    for reason in ("no_route_forward", "no_route_arrival", "capacity"):
        dm.counter("mho_dev_sim_dropped_total",
                   "packets dropped, by reason", reason=reason)
    for target in ("link", "server"):
        dm.counter("mho_dev_sim_forwarded_total",
                   "completed link packets re-enqueued, by next-hop target",
                   target=target)
    dm.histogram(DM_QUEUE_DEPTH, pow2_buckets(spec.cap),
                 "per-slot occupancy of every live queue (links + servers)")
    dm.counter(DM_NONFINITE,
               "per-stream non-finite sim accumulators/probabilities, "
               "counted in-program per slot")
    return dm.freeze()


def sim_slot_step(
    inst: Instance,
    spec: SimSpec,
    params: SimParams,
    routes: SimRoutes,
    jobs: JobSet,
    state: SimState,
    key: jax.Array,
    dm: DevMetrics | None = None,
    dev: dict | None = None,
):
    """Advance one slot; returns (state', scheduled (L,) bool).

    With `dm`/`dev` (a `sim_devmetrics` declaration and its accumulator
    pytree) the return value grows a third element, the updated
    accumulators — pure scatter-adds on fixed shapes, no host traffic.
    """
    num_links, n, j = spec.num_links, spec.num_nodes, spec.num_jobs
    c, q = spec.cap, spec.num_queues
    i32 = jnp.int32
    fdt = state.delay_sum.dtype
    t = state.t
    k_tie, k_link, k_srv, k_arr = jax.random.split(key, 4)

    node_up, link_up = liveness_masks(inst, params, t)
    u_end, v_end = inst.link_ends[:, 0], inst.link_ends[:, 1]
    lidx = jnp.arange(num_links, dtype=i32)

    q_busy = state.q_busy + (state.count > 0).astype(i32)

    # ---- 1. undirected link schedule + geometric completion ----------------
    cnt_f, cnt_b = state.count[:num_links], state.count[num_links:2 * num_links]
    backlog = cnt_f + cnt_b
    contend = (backlog > 0) & link_up
    wts = jnp.where(
        contend,
        backlog.astype(fdt) + jax.random.uniform(k_tie, (num_links,), fdt),
        0.0,
    )
    sched, _ = local_greedy_mwis(inst.adj_conflict, wts, mask=contend)
    complete = sched & (
        jax.random.uniform(k_link, (num_links,), fdt) < params.link_srv_p
    )
    head_f, head_b = state.head[:num_links], state.head[num_links:2 * num_links]
    enq_f = state.buf_enq[lidx, head_f]
    enq_b = state.buf_enq[lidx + num_links, head_b]
    both = (cnt_f > 0) & (cnt_b > 0)
    use_f = jnp.where(both, enq_f <= enq_b, cnt_f > 0)
    src_q = jnp.where(use_f, lidx, lidx + num_links)          # (L,)
    exit_node = jnp.where(use_f, v_end, u_end)

    hq = state.head[src_q]
    s_l = state.buf_stream[src_q, hq]
    birth_l = state.buf_birth[src_q, hq]
    enq_l = state.buf_enq[src_q, hq]

    sq_w = jnp.where(complete, src_q, q)                      # scratch-masked
    head = (state.head.at[sq_w].add(1)) % c
    count = state.count.at[sq_w].add(-1)
    q_sojourn = state.q_sojourn.at[sq_w].add((t - enq_l).astype(fdt))
    q_served = state.q_served.at[sq_w].add(1)
    sched_slots = state.sched_slots + sched.astype(i32)

    # ---- 2. server drain ---------------------------------------------------
    srows = 2 * num_links + jnp.arange(n, dtype=i32)
    scnt = state.count[srows]
    base = jnp.floor(params.srv_rate).astype(i32)
    frac = params.srv_rate - base.astype(params.srv_rate.dtype)
    ndraw = base + (jax.random.uniform(k_srv, (n,), fdt) < frac).astype(i32)
    nserve = jnp.where(node_up, jnp.minimum(scnt, ndraw), 0)
    posm = (state.head[srows][:, None] + jnp.arange(c, dtype=i32)[None, :]) % c   # (N, C)
    smask = jnp.arange(c, dtype=i32)[None, :] < nserve[:, None]
    s_srv = state.buf_stream[srows[:, None], posm]
    birth_srv = state.buf_birth[srows[:, None], posm]
    enq_srv = state.buf_enq[srows[:, None], posm]
    # masked scatter-adds: garbage indices are in-range, their added value 0
    sf = s_srv.reshape(-1)
    mf = smask.reshape(-1)
    delivered = state.delivered.at[sf].add(mf.astype(i32))
    delay_sum = state.delay_sum.at[sf].add(
        (t - birth_srv).astype(fdt).reshape(-1) * mf.astype(fdt)
    )
    q_sojourn = q_sojourn.at[srows].add(
        jnp.sum((t - enq_srv).astype(fdt) * smask.astype(fdt), axis=1)
    )
    q_served = q_served.at[srows].add(nserve)
    head = (head.at[srows].add(nserve)) % c
    count = count.at[srows].add(-nserve)

    # ---- 3. forward completed link packets ---------------------------------
    dests = jnp.concatenate([routes.dst, jobs.src])           # (2J,)
    d_l = dests[s_l]
    at_dest = exit_node == d_l
    is_ul = s_l < j
    deliver_now = complete & at_dest & ~is_ul
    delivered = delivered.at[s_l].add(deliver_now.astype(i32))
    delay_sum = delay_sum.at[s_l].add(
        (t - birth_l).astype(fdt) * deliver_now.astype(fdt)
    )
    fw = complete & ~deliver_now
    nxt = routes.next_hop[exit_node, d_l]
    tgt_link = inst.link_index[exit_node, nxt]
    edge_ok = inst.adj[exit_node, nxt] > 0
    dirq = tgt_link + num_links * (exit_node != u_end[tgt_link]).astype(i32)
    to_server = at_dest & is_ul
    tgt_q = jnp.where(to_server, 2 * num_links + exit_node, dirq)
    ok_l = jnp.where(
        to_server,
        node_up[exit_node],
        edge_ok & link_up[tgt_link] & routes.reach[exit_node, d_l],
    )
    put_l = fw & ok_l
    drop_l = fw & ~ok_l

    # ---- 4. arrivals -------------------------------------------------------
    origin = jnp.concatenate([jobs.src, routes.dst])          # (2J,)
    offloaded = routes.dst != jobs.src
    gen_p = (
        params.arr_p
        * node_up[origin].astype(fdt)
        * node_up[dests].astype(fdt)
        * jnp.concatenate(
            [jnp.ones((j,), fdt), offloaded.astype(fdt)]
        )  # downlink streams exist only for offloaded jobs
    )
    gen = jax.random.uniform(k_arr, (2 * j,), fdt) < gen_p
    generated = state.generated + gen.astype(i32)
    local_entry = origin == dests                             # ul of local jobs
    nxt_a = routes.next_hop[origin, dests]
    tl_a = inst.link_index[origin, nxt_a]
    edge_ok_a = inst.adj[origin, nxt_a] > 0
    dirq_a = tl_a + num_links * (origin != u_end[tl_a]).astype(i32)
    tgt_a = jnp.where(local_entry, 2 * num_links + origin, dirq_a)
    ok_a = jnp.where(
        local_entry,
        node_up[origin],
        edge_ok_a & link_up[tl_a] & routes.reach[origin, dests],
    )
    put_a = gen & ok_a
    drop_a = gen & ~ok_a

    # ---- 5. ordered batched enqueue with capacity drops --------------------
    m = num_links + 2 * j
    tgt = jnp.concatenate([tgt_q, tgt_a])                     # (M,)
    put = jnp.concatenate([put_l, put_a])
    # stream ids keep the ring buffer's compact dtype (int16) end to end —
    # a wider arange here would promote the concat and fail the .set below
    strm = jnp.concatenate(
        [s_l, jnp.arange(2 * j, dtype=state.buf_stream.dtype)]
    )
    births = jnp.concatenate([birth_l, jnp.full((2 * j,), t, i32)])
    onehot = (put[:, None] & (tgt[:, None] == jnp.arange(q, dtype=i32)[None, :]))
    rank = jnp.cumsum(onehot.astype(i32), axis=0)[jnp.arange(m, dtype=i32), tgt] - 1
    space_ok = count[tgt] + rank < c
    final_put = put & space_ok
    dropped = state.dropped.at[strm].add(
        (jnp.concatenate([drop_l, drop_a]) | (put & ~space_ok)).astype(i32)
    )
    pos = (head[tgt] + count[tgt] + rank) % c
    row = jnp.where(final_put, tgt, q)                        # scratch-masked
    buf_stream = state.buf_stream.at[row, pos].set(strm)
    buf_birth = state.buf_birth.at[row, pos].set(births)
    buf_enq = state.buf_enq.at[row, pos].set(jnp.full((m,), t, i32))
    count = count.at[row].add(1)
    q_arrived = state.q_arrived.at[row].add(1)

    new_state = SimState(
        buf_stream=buf_stream, buf_birth=buf_birth, buf_enq=buf_enq,
        head=head, count=count,
        generated=generated, delivered=delivered, dropped=dropped,
        delay_sum=delay_sum,
        q_sojourn=q_sojourn, q_served=q_served, q_busy=q_busy,
        q_arrived=q_arrived, sched_slots=sched_slots,
        t=t + 1,
    )
    if dm is None:
        return new_state, sched
    # slot-start depths: every live queue (scratch row excluded) before
    # any service/arrival this slot touches it
    dev = dm.observe(dev, DM_QUEUE_DEPTH, state.count[:q])
    dev = dm.inc(dev, DM_GENERATED, gen)
    dev = dm.inc(dev, DM_DELIVERED, nserve)
    dev = dm.inc(dev, DM_DELIVERED, deliver_now)
    dev = dm.inc(dev, DM_DROP_FWD, drop_l)
    dev = dm.inc(dev, DM_DROP_ARR, drop_a)
    dev = dm.inc(dev, DM_DROP_CAP, put & ~space_ok)
    dev = dm.inc(dev, DM_FWD_LINK, put_l & ~to_server)
    dev = dm.inc(dev, DM_FWD_SERVER, put_l & to_server)
    # numeric sentinel: a poisoned rate/bandwidth that slipped past the
    # admission guards shows up here as a non-finite arrival probability
    # or delay accumulator — counted per stream per slot, zero in health
    dev = dm.inc(dev, DM_NONFINITE,
                 ~jnp.isfinite(gen_p) | ~jnp.isfinite(delay_sum))
    return new_state, sched, dev

"""Discrete-time packet-level network simulator (closed-loop evaluation).

The analytic evaluator (`env.queueing`) scores a routing decision with
steady-state M/M/1 formulas; this package replays the same system packet
by packet — slotted time, per-link/per-server FIFO ring buffers, MWIS
link activation, multi-hop forwarding, Bernoulli arrivals — as one jitted
`lax.scan`, `vmap`-able over a fleet, with the offloading policy re-run
in the loop on empirically measured arrival rates.  `sim.fidelity`
quantifies where the two models agree (low utilization) and where queueing
dynamics diverge from the analytic idealization.
"""

from multihop_offload_tpu.sim.policies import POLICY_KINDS, make_policy
from multihop_offload_tpu.sim.runner import FleetSim, SimRun, simulate
from multihop_offload_tpu.sim.state import (
    SimParams,
    SimRoutes,
    SimSpec,
    SimState,
    build_sim_params,
    conservation_gap,
    in_flight,
    init_state,
    liveness_masks,
    migrate_sim_state,
    spec_for,
)
from multihop_offload_tpu.sim.step import sim_slot_step

__all__ = [
    "POLICY_KINDS",
    "FleetSim",
    "SimParams",
    "SimRoutes",
    "SimRun",
    "SimSpec",
    "SimState",
    "build_sim_params",
    "conservation_gap",
    "in_flight",
    "init_state",
    "liveness_masks",
    "make_policy",
    "migrate_sim_state",
    "sim_slot_step",
    "simulate",
    "spec_for",
]

"""Sim-vs-analytic fidelity sweep (`mho-sim --fidelity`).

The analytic evaluator prices every link as an interference-coupled M/M/1
queue; the simulator realizes the same system packet by packet.  This
harness drives both on the *same* instances, jobs and (baseline) routing
decisions across an arrival-rate sweep and reports where they agree:

- **per-link**: empirical mean channel sojourn (`q_sojourn / q_served * dt``,
  both direction queues of a link pooled) against the analytic per-packet
  delay ``1/(mu - lambda)`` — traffic-weighted relative error over links
  with enough served packets;
- **per-server**: server-queue sojourn against ``1/(bw - load)``;
- **end-to-end**: per-stream mean packet delay against the analytic
  route sum of unit delays.

Low utilization is the regime where the M/M/1 idealization should hold
(geometric service -> exponential in the ``dt -> 0`` limit; MWIS sharing
-> the busyness fixed point when queues rarely collide), so the committed
record (`benchmarks/sim_fidelity.json`) gates on utilization <= 0.5; the
high-utilization rows are kept to *document* where queueing dynamics leave
the analytic model, which is the point of having a simulator at all.

The whole sweep runs through ONE compiled fleet program: every utilization
reuses the same `FleetSim` (only array values change), `mark_steady` fires
after the first segment, and the JSON records the unexpected-retrace count
(must be 0).  Discretization note: the geometric approximation biases
sojourn by O(arrival prob per slot); `margin` sets ``dt`` so the busiest
link's per-slot probabilities stay small (default 5 -> <= 0.2).
"""

from __future__ import annotations

import json
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from multihop_offload_tpu.env.policies import baseline_policy
from multihop_offload_tpu.graphs import generators
from multihop_offload_tpu.graphs.instance import (
    PadSpec,
    build_instance,
    build_jobset,
    stack_instances,
)
from multihop_offload_tpu.graphs.topology import build_topology, sample_link_rates
from multihop_offload_tpu.obs import jaxhooks
from multihop_offload_tpu.sim.policies import make_policy
from multihop_offload_tpu.sim.runner import FleetSim
from multihop_offload_tpu.sim.state import build_sim_params, spec_for
from multihop_offload_tpu.sim.step import (
    DM_DROP_ARR,
    DM_DROP_CAP,
    DM_DROP_FWD,
    DM_QUEUE_DEPTH,
)

DEFAULT_UTILS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.7, 0.85)


def make_case(seed: int, topo, pad: PadSpec, num_jobs: int,
              num_servers: int = 2, dtype=np.float32,  # fp32-island(storage default; callers pass the policy dtype)
              layout=None):
    """One random connected BA case with a mid-load workload (rates are
    rescaled per utilization target afterwards)."""
    from multihop_offload_tpu.layouts import resolve_layout

    lay = resolve_layout(layout)
    rng = np.random.default_rng(seed)
    n_nodes = topo.n
    deg = np.asarray(topo.adj).sum(axis=1)
    servers = np.argsort(-deg, kind="stable")[:num_servers]
    roles = np.zeros(n_nodes, np.int32)
    roles[servers] = 1
    bws = np.where(roles == 1, 100.0, 8.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=dtype,
                          layout=lay)
    mobile = np.setdiff1d(np.arange(n_nodes, dtype=np.int64), servers)
    srcs = rng.choice(mobile, size=min(num_jobs, mobile.size), replace=False)
    jrates = rng.uniform(0.5, 1.0, srcs.size)
    jobs = build_jobset(srcs, jrates, pad_jobs=pad.j, dtype=dtype,
                        index_dtype=lay.index_dtype)
    return inst, jobs


def max_busyness(inst, jobs, outcome) -> float:
    """Bottleneck rho over real links and loaded servers for a decision."""
    lam = np.asarray(outcome.delays.link_lambda, np.float64)
    mu = np.asarray(outcome.delays.link_mu, np.float64)
    lmask = np.asarray(inst.link_mask) & (lam > 0)
    rho_l = (lam[lmask] / mu[lmask]).max() if lmask.any() else 0.0
    load = np.asarray(outcome.delays.server_load, np.float64)
    bw = np.asarray(inst.proc_bws, np.float64)
    smask = (load > 0) & (bw > 0)
    rho_s = (load[smask] / bw[smask]).max() if smask.any() else 0.0
    return float(max(rho_l, rho_s, 1e-9))


def scale_to_util(inst, jobs, key, target: float, iters: int = 3,
                  policy_fn=baseline_policy):
    """Rescale job rates until the analytic bottleneck rho hits `target`.

    The interference fixed point makes mu depend on lambda, so rho is not
    linear in the rates; a few multiplicative corrections converge.  Pass a
    jitted `policy_fn` when calling repeatedly — the eager path builds fresh
    scan/while closures per call, which recompiles every time."""
    for _ in range(iters):
        out = policy_fn(inst, jobs, key)
        jobs = jobs.replace(
            rate=jobs.rate * (target / max_busyness(inst, jobs, out))
        )
    return jobs, policy_fn(inst, jobs, key)


def analytic_link_delay(inst, outcome) -> np.ndarray:
    """(L,) per-packet channel delay 1/(mu - lambda); NaN where untraversed
    or analytically congested."""
    lam = np.asarray(outcome.delays.link_lambda, np.float64)
    mu = np.asarray(outcome.delays.link_mu, np.float64)
    ok = np.asarray(inst.link_mask) & (lam > 0) & (mu > lam)
    with np.errstate(divide="ignore", invalid="ignore"):
        d = np.where(ok, 1.0 / (mu - lam), np.nan)
    return d


def analytic_server_delay(inst, outcome) -> np.ndarray:
    """(N,) per-packet server delay 1/(bw - load); NaN where unloaded."""
    load = np.asarray(outcome.delays.server_load, np.float64)
    bw = np.asarray(inst.proc_bws, np.float64)
    ok = (load > 0) & (bw > load)
    with np.errstate(divide="ignore", invalid="ignore"):
        d = np.where(ok, 1.0 / (bw - load), np.nan)
    return d


def empirical_queue_delays(state, spec, dt: float, min_served: int = 50):
    """Pooled per-channel and per-server (sojourn, served) in model time."""
    num_links, n = spec.num_links, spec.num_nodes
    soj = np.asarray(state.q_sojourn, np.float64)
    srv = np.asarray(state.q_served, np.float64)
    ch_soj = soj[:num_links] + soj[num_links:2 * num_links]
    ch_srv = srv[:num_links] + srv[num_links:2 * num_links]
    with np.errstate(divide="ignore", invalid="ignore"):
        link_d = np.where(ch_srv >= min_served, ch_soj / ch_srv * dt, np.nan)
        srv_d = np.where(
            srv[2 * num_links:2 * num_links + n] >= min_served,
            soj[2 * num_links:2 * num_links + n]
            / srv[2 * num_links:2 * num_links + n] * dt,
            np.nan,
        )
    return link_d, srv_d


def _weighted_err(emp: np.ndarray, ana: np.ndarray, weight: np.ndarray):
    ok = np.isfinite(emp) & np.isfinite(ana) & (weight > 0)
    if not ok.any():
        return {"weighted_rel_err": None, "max_rel_err": None, "compared": 0}
    rel = np.abs(emp[ok] - ana[ok]) / ana[ok]
    w = weight[ok] / weight[ok].sum()
    return {
        "weighted_rel_err": float((rel * w).sum()),
        "max_rel_err": float(rel.max()),
        "compared": int(ok.sum()),
    }


def composed_job_tau(inst, jobs, routes, emp_link, emp_srv) -> np.ndarray:
    """(J,) the analytic job-total formula with empirical unit delays
    substituted for 1/(mu - lambda) — the sim-grounded counterpart of
    `EmpiricalDelays.job_total`, used by the mobility rollout re-base."""
    num_links = inst.num_pad_links
    inc = np.asarray(routes.inc_ext, np.float64)[:num_links]          # (L, J)
    nhop = np.asarray(routes.nhop, np.float64)
    ul = np.asarray(jobs.ul, np.float64)
    dl = np.asarray(jobs.dl, np.float64)
    d_ul = np.maximum(ul[None, :] * emp_link[:, None], nhop[None, :])
    d_dl = np.maximum(dl[None, :] * emp_link[:, None], nhop[None, :])
    job_link = np.where(inc > 0, d_ul + d_dl, 0.0).sum(axis=0)
    job_server = np.maximum(ul * emp_srv[np.asarray(routes.dst)], 1.0)
    return np.where(np.asarray(jobs.mask), job_link + job_server, 0.0)


def analytic_mean_in_flight(inst, outcome) -> float:
    """Expected total packets in system, Sum rho/(1-rho) over loaded M/M/1
    queues (links + servers) — the Little's-law counterpart of the
    devmetrics per-slot queue-depth histogram's mean."""
    lam = np.asarray(outcome.delays.link_lambda, np.float64)
    mu = np.asarray(outcome.delays.link_mu, np.float64)
    ok_l = np.asarray(inst.link_mask) & (lam > 0) & (mu > lam)
    l_links = float((lam[ok_l] / (mu[ok_l] - lam[ok_l])).sum()) \
        if ok_l.any() else 0.0
    load = np.asarray(outcome.delays.server_load, np.float64)
    bw = np.asarray(inst.proc_bws, np.float64)
    ok_s = (load > 0) & (bw > load)
    l_srv = float((load[ok_s] / (bw[ok_s] - load[ok_s])).sum()) \
        if ok_s.any() else 0.0
    return l_links + l_srv


def _devmetrics_row(flushed, outcomes, cases, fleet: int, slots: int):
    """Per-utilization device-metrics block: the per-slot queue-depth
    histogram vs the analytic expected in-flight, plus drop reasons the
    terminal `SimState.dropped` cannot attribute."""
    if not flushed:
        return None
    h = flushed.get(DM_QUEUE_DEPTH)
    row = {
        "drops": {
            "no_route_forward": int(flushed.get(DM_DROP_FWD, 0)),
            "no_route_arrival": int(flushed.get(DM_DROP_ARR, 0)),
            "capacity": int(flushed.get(DM_DROP_CAP, 0)),
        },
    }
    if h and h["count"]:
        # the histogram observes every live queue every slot, so its sum
        # over one segment is (total in-flight) integrated over slot-lanes
        emp = h["sum"] / (fleet * slots)
        ana = float(np.mean([
            analytic_mean_in_flight(inst, out)
            for (inst, _), out in zip(cases, outcomes)
        ]))
        row["queue_depth"] = {
            "mean_in_flight_emp": float(emp),
            "mean_in_flight_analytic": ana,
            "rel_err": float(abs(emp - ana) / ana) if ana > 0 else None,
            "max_depth": h["max"],
            "counts": h["counts"],
        }
    return row


def _end_to_end(inst, jobs, outcome, state, spec, dt):
    """Delivered-weighted rel. error of per-stream mean packet delay."""
    num_links = inst.num_pad_links
    j = int(jobs.src.shape[-1])
    ana_l = analytic_link_delay(inst, outcome)
    ana_s = analytic_server_delay(inst, outcome)
    inc = np.asarray(outcome.routes.inc_ext, np.float64)[:num_links]  # (L, J)
    # NaN analytic entries on a traversed link poison the whole path sum, so
    # that stream drops out of the comparison instead of skewing it
    path_sum = np.where(inc > 0, ana_l[:, None], 0.0).sum(axis=0)
    dst = np.asarray(outcome.routes.dst)
    srv_term = ana_s[dst]
    # a served destination with no analytic load entry stays NaN -> excluded
    ana_ul = path_sum + srv_term                                       # (J,)
    ana_dl = path_sum                                                  # (J,)
    delivered = np.asarray(state.delivered, np.float64)
    dsum = np.asarray(state.delay_sum, np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        emp = np.where(delivered >= 50, dsum / delivered * dt, np.nan)
    emp_ul, emp_dl = emp[:j], emp[j:]
    ana = np.concatenate([ana_ul, ana_dl])
    return _weighted_err(
        np.concatenate([emp_ul, emp_dl]), ana, delivered
    )


def fidelity_sweep(
    utils: Sequence[float] = DEFAULT_UTILS,
    fleet: int = 8,
    n_nodes: int = 10,
    num_jobs: int = 4,
    rounds: int = 5,
    slots_per_round: int = 1000,
    margin: float = 5.0,
    cap: int = 128,
    seed: int = 0,
    min_served: int = 50,
) -> dict:
    """Run the sweep; returns the JSON-ready record."""
    topos = [
        build_topology(
            generators.barabasi_albert(n_nodes, seed=seed + 100 * i)[0]
        )
        for i in range(fleet)
    ]
    max_links = max(t.num_links for t in topos)
    pad = PadSpec(
        n=-(-n_nodes // 8) * 8,
        l=-(-max_links // 8) * 8,
        s=8,
        j=max(num_jobs, 8),
    )
    cases = [
        make_case(seed + 100 * i, topos[i], pad, num_jobs)
        for i in range(fleet)
    ]
    inst0, jobs0 = cases[0]
    spec = spec_for(inst0, jobs0, cap=cap)
    sim = FleetSim(
        spec, make_policy("baseline"),
        rounds=rounds, slots_per_round=slots_per_round,
    )
    keys = jax.random.split(jax.random.PRNGKey(seed), fleet)
    bp = jax.jit(baseline_policy)

    sweep = []
    first = True
    for u in utils:
        scaled, outcomes = [], []
        for i, (inst, jobs) in enumerate(cases):
            jobs_u, out = scale_to_util(inst, jobs, keys[i], u, policy_fn=bp)
            scaled.append((inst, jobs_u))
            outcomes.append(out)
        insts = stack_instances([c[0] for c in scaled])
        jobss = stack_instances([c[1] for c in scaled])
        params_list = [
            build_sim_params(inst, jobs, margin=margin)
            for inst, jobs in scaled
        ]
        paramss = stack_instances(params_list)
        init_rates = jnp.stack([jobs.rate for _, jobs in scaled])
        run = sim.run(insts, jobss, paramss, keys, init_rates=init_rates)
        # pull the whole fleet state to host ONCE; per-lane slicing below is
        # numpy, so it can't trigger device compilations after mark_steady
        st_all = jax.tree_util.tree_map(np.asarray, run.state)

        link_errs, srv_errs, e2e_errs = [], [], []
        total = {"generated": 0, "delivered": 0, "dropped": 0, "in_flight": 0}
        for i, (inst, jobs) in enumerate(scaled):
            st = jax.tree_util.tree_map(lambda x: x[i], st_all)
            dt = float(params_list[i].dt)
            emp_l, emp_s = empirical_queue_delays(st, spec, dt, min_served)
            lam = np.asarray(outcomes[i].delays.link_lambda, np.float64)
            link_errs.append(
                _weighted_err(emp_l, analytic_link_delay(inst, outcomes[i]),
                              np.where(np.isfinite(emp_l), lam, 0.0))
            )
            load = np.asarray(outcomes[i].delays.server_load, np.float64)
            srv_errs.append(
                _weighted_err(emp_s, analytic_server_delay(inst, outcomes[i]),
                              np.where(np.isfinite(emp_s), load, 0.0))
            )
            e2e_errs.append(_end_to_end(inst, jobs, outcomes[i], st, spec, dt))
            total["generated"] += int(np.asarray(st.generated).sum())
            total["delivered"] += int(np.asarray(st.delivered).sum())
            total["dropped"] += int(np.asarray(st.dropped).sum())
            total["in_flight"] += int(np.asarray(st.count[:-1]).sum())

        def pool(errs):
            ok = [e for e in errs if e["weighted_rel_err"] is not None]
            if not ok:
                return {"weighted_rel_err": None, "max_rel_err": None,
                        "compared": 0}
            return {
                "weighted_rel_err": float(
                    np.mean([e["weighted_rel_err"] for e in ok])
                ),
                "max_rel_err": float(max(e["max_rel_err"] for e in ok)),
                "compared": int(sum(e["compared"] for e in ok)),
            }

        sweep.append({
            "util": float(u),
            "link": pool(link_errs),
            "server": pool(srv_errs),
            "end_to_end": pool(e2e_errs),
            "devmetrics": _devmetrics_row(
                sim.last_devmetrics, outcomes, scaled, fleet,
                rounds * slots_per_round,
            ),
            **total,
        })
        if first:
            # every program in one full iteration (policy eval, fleet scan,
            # host analysis) has now compiled; later utilizations must only
            # swap array values
            sim.mark_steady()
            first = False

    gate = [
        r["link"]["weighted_rel_err"] for r in sweep
        if r["util"] <= 0.5 and r["link"]["weighted_rel_err"] is not None
    ]
    retraces = jaxhooks.unexpected_retraces()
    record = {
        "config": {
            "utils": [float(u) for u in utils],
            "fleet": fleet, "n_nodes": n_nodes, "num_jobs": num_jobs,
            "rounds": rounds, "slots_per_round": slots_per_round,
            "slots": rounds * slots_per_round,
            "margin": margin, "cap": cap, "seed": seed,
            "min_served": min_served, "policy": "baseline",
        },
        "sweep": sweep,
        "acceptance": {
            "max_link_rel_err_util_le_0.5": float(max(gate)) if gate else None,
            "threshold": 0.10,
            "pass": bool(gate) and max(gate) <= 0.10,
            "unexpected_retraces_after_steady": retraces,
        },
    }
    return record


def write_record(record: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")

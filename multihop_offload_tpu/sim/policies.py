"""Policy adapters: offloading decision + forwarding tables for the sim.

The analytic evaluation path (`env.policies.evaluate_spmatrix_policy`)
composes decision -> route trace -> M/M/1 scoring; the simulator needs the
same front half (decision + next-hop table) but keeps the scoring to its
own packet dynamics.  `make_policy` returns a pure function

    policy_fn(inst, jobs_est, node_up, link_up, key) -> SimRoutes

shared by the three methods the drivers benchmark: the trained GNN
(`agent.policy` forward pass), the congestion-agnostic greedy baseline,
and local-only compute.  `jobs_est` carries the simulator's *empirical*
arrival-rate estimates — the policy sees measured traffic, not the ground
truth the arrival process samples from (closed-loop evaluation).  Failures
are respected by pricing down links/nodes at +inf before the shortest-path
step, so re-offloading and re-routing around a failure happens at the next
policy round without recompilation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multihop_offload_tpu.env.apsp import (
    apsp_minplus,
    next_hop_table,
    weight_matrix_from_link_delays,
)
from multihop_offload_tpu.env.baseline import baseline_unit_delays
from multihop_offload_tpu.env.offloading import offload_decide
from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.layouts import (
    NEXT_HOP_DTYPE,
    next_hop_from_edges,
    pack_next_hop,
    resolve_layout,
    weight_matrix_from_edges,
)
from multihop_offload_tpu.sim.state import SimRoutes

POLICY_KINDS = ("gnn", "baseline", "local")


def decide_routes(
    inst: Instance,
    jobs_est: JobSet,
    link_delays: jnp.ndarray,
    unit_diag: jnp.ndarray,
    node_up: jnp.ndarray,
    link_up: jnp.ndarray,
    key: jax.Array,
    explore=0.0,
    prob: bool = False,
    apsp_fn=None,
    layout=None,
    objective=None,
) -> SimRoutes:
    """Shared decision skeleton on arbitrary unit delays (the sim-side twin
    of `evaluate_spmatrix_policy`, returning the forwarding table instead
    of analytic scores).  The forwarding table ships compact (int16,
    `layouts.pack_next_hop`) under EVERY layout — node ids are tiny and the
    (N, N) table rides the scan carry through the whole run."""
    inf = jnp.inf
    lay = resolve_layout(layout)
    link_delays = jnp.where(link_up, link_delays, inf)
    unit_diag = jnp.where(node_up, unit_diag, inf)
    if lay.sparse:
        w = weight_matrix_from_edges(
            inst.link_ends, inst.link_mask, link_delays, inst.num_pad_nodes
        )
    else:
        w = weight_matrix_from_link_delays(
            inst.adj, inst.link_index, link_delays
        )
    sp = (apsp_fn or apsp_minplus)(w)
    dec = offload_decide(
        inst, jobs_est, sp, inst.hop, unit_diag, key, explore, prob,
        objective=objective,
    )
    # a destination that became unreachable (failure cut the graph) degrades
    # to local compute — packets must never chase an infinite-cost route
    reachable = jnp.isfinite(
        sp[jobs_est.src, dec.dst]
    ) & node_up[dec.dst]
    dst = jnp.where(reachable, dec.dst, jobs_est.src.astype(jnp.int32))
    nh = (next_hop_from_edges(inst.link_ends, inst.link_mask, sp)
          if lay.sparse else next_hop_table(inst.adj, sp))
    return SimRoutes(
        dst=dst.astype(jnp.int32),
        next_hop=pack_next_hop(nh),
        reach=jnp.isfinite(sp),
    )


def make_policy(
    kind: str,
    model=None,
    variables=None,
    support=None,
    explore=0.0,
    prob: bool = False,
    apsp_fn=None,
    fp_fn=None,
    precision=None,
    layout=None,
    objective=None,
):
    """Build the per-round policy function for `sim.runner.simulate`.

    `precision` (str | `precision.PrecisionPolicy` | None) narrows the APSP
    inside the decision skeleton under the bf16 policy — resolved here at
    build time and closed over, so the compiled sim program never retraces.
    The decision read-back stays an fp32 island (`env.offloading`).
    `layout` follows the same contract: resolved once, closed over, and the
    instances fed to the returned function must have been built with it.
    `objective` (`env.offloading.ObjectiveWeights` | None) folds energy/cost
    weights into the decision's cost table — plain floats, closed over like
    the other build-time knobs; None/all-zero is bit-identical to today.
    """
    from multihop_offload_tpu.precision import resolve_precision

    if kind not in POLICY_KINDS:
        raise ValueError(f"unknown sim policy '{kind}'; one of {POLICY_KINDS}")
    apsp_fn = resolve_precision(precision).wrap_apsp(apsp_fn)
    lay = resolve_layout(layout)

    if kind == "local":

        def local_fn(inst, jobs_est, node_up, link_up, key):
            n = inst.num_pad_nodes
            return SimRoutes(
                dst=jobs_est.src.astype(jnp.int32),
                next_hop=jnp.zeros((n, n), NEXT_HOP_DTYPE),   # dense-ok(never consulted; scan-carry shape must match the deciding policies)
                reach=jnp.zeros((n, n), bool),                # dense-ok(same carry-shape constraint)
            )

        return local_fn

    if kind == "baseline":

        def baseline_fn(inst, jobs_est, node_up, link_up, key):
            link_d, node_d = baseline_unit_delays(inst)
            return decide_routes(
                inst, jobs_est, link_d, node_d, node_up, link_up, key,
                explore=explore, prob=prob, apsp_fn=apsp_fn, layout=lay,
                objective=objective,
            )

        return baseline_fn

    if model is None or variables is None:
        raise ValueError("kind='gnn' needs model and variables")

    def gnn_fn(inst, jobs_est, node_up, link_up, key):
        from multihop_offload_tpu.agent.actor import (
            actor_delay_matrix,
            default_support,
        )

        sup = (default_support(model, inst, layout=lay)
               if support is None else support)
        actor = actor_delay_matrix(
            model, variables, inst, jobs_est, sup, fp_fn=fp_fn, layout=lay
        )
        if lay.sparse:
            unit_diag = jnp.where(inst.comp_mask, actor.node_delay, jnp.inf)
        else:
            unit_diag = jnp.diagonal(actor.delay_matrix)
        return decide_routes(
            inst, jobs_est, actor.link_delay, unit_diag,
            node_up, link_up, key,
            explore=explore, prob=prob, apsp_fn=apsp_fn, layout=lay,
            objective=objective,
        )

    return gnn_fn

"""Two-level DCN-aware placement: buckets -> hosts -> chips.

The single-host `serve.placement.PlacementPlanner` lays each bucket's batch
axis over a subset of ONE fleet of chips.  Crossing the host boundary adds
a second, much more expensive axis: the data-center network between hosts
is orders of magnitude slower than the on-host ICI, so the plan must never
ask a bucket's batch to span it.  This module encodes that as a structural
invariant rather than a tuning choice:

  * level 1 (DCN): every bucket is assigned to exactly ONE host.  Weights
    are replicated per host (each process loads the same checkpoint), so
    moving a bucket between hosts moves only future traffic, never state;
  * level 2 (ICI): within its host, the bucket's batch axis is laid over a
    chip subset by the SAME divisor-ladder greedy the single-host planner
    uses (`serve.placement.plan_assignments` is called per host) — slots
    stay evenly divisible, no new program variants.

The planner keeps the single-host planner's contract exactly: EWMA
arrival-rate observation, deterministic plans for fixed rates, a
hysteresis gate so rate jitter never thrashes a compile, and forced
re-planning when a host is removed (an invalid plan is never held).

Everything here is pure host-side Python — no jax import — so the planner
is unit-testable without `jax.distributed` (tests/test_multihost.py) and
every process of a fleet, given the same host table and rates, derives the
same plan with no coordination traffic at all.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs.registry import registry as obs_registry
from multihop_offload_tpu.serve.placement import (
    PlacementPlan,
    peak_device_load,
    plan_assignments,
)

_RATE_FLOOR = 1e-9


@dataclasses.dataclass(frozen=True)
class TwoLevelPlan:
    """One immutable bucket -> (host, chip-tuple) map.

    `hosts[b]` names the host serving bucket `b`; `devices[b]` are the
    chips of THAT host carrying its batch axis (host-local descriptors —
    `jax.Device`s in a live process, opaque ids in tests and in remote
    processes' views of each other)."""

    hosts: Tuple[str, ...]
    devices: Tuple[Tuple[object, ...], ...]

    def host_of(self, bucket: int) -> str:
        return self.hosts[bucket]

    def devices_for(self, bucket: int) -> Tuple[object, ...]:
        return self.devices[bucket]

    def buckets_on_host(self, host: str) -> List[int]:
        return [b for b, h in enumerate(self.hosts) if h == host]

    def describe(self) -> dict:
        """JSON-friendly: bucket -> {host, devices}."""
        def dev_id(d):
            return getattr(d, "id", d)

        return {
            str(b): {"host": h, "devices": [dev_id(d) for d in devs]}
            for b, (h, devs) in enumerate(zip(self.hosts, self.devices))
        }


def validate_plan(plan: TwoLevelPlan, hosts: Dict[str, Sequence]) -> None:
    """The DCN invariant, checked structurally: every bucket's chips are a
    subset of its OWN host's chips — a bucket spanning hosts is a planner
    bug and raises before anything compiles against it."""
    for b, (h, devs) in enumerate(zip(plan.hosts, plan.devices)):
        if h not in hosts:
            raise ValueError(f"bucket {b} assigned to unknown host '{h}'")
        if not devs:
            raise ValueError(f"bucket {b} has no devices on host '{h}'")
        host_devs = list(hosts[h])
        missing = [d for d in devs if d not in host_devs]
        if missing:
            raise ValueError(
                f"bucket {b} spans the DCN boundary: devices {missing} "
                f"are not on its host '{h}'"
            )


def plan_two_level(
    rates: Sequence[float], hosts: Dict[str, Sequence], slots: int
) -> TwoLevelPlan:
    """The deterministic two-level greedy.

    Level 1: buckets in descending-rate order (ties -> lower bucket index)
    each go to the host with the lowest resulting per-chip load (ties ->
    lexicographically first host id).  Level 2: each host's bucket set is
    laid over its chips by `serve.placement.plan_assignments` — the exact
    single-host ladder, so within-host behavior is unchanged.

    Same rates + same host table -> same plan, on every process."""
    if not hosts:
        raise ValueError("two-level placement needs at least one host")
    for h, devs in hosts.items():
        if not list(devs):
            raise ValueError(f"host '{h}' has no devices")
    n_buckets = len(rates)
    host_ids = sorted(hosts)
    load = [max(float(r), _RATE_FLOOR) for r in rates]
    # level 1: greedy balance of per-chip host load
    host_load = {h: 0.0 for h in host_ids}
    assigned: Dict[str, List[int]] = {h: [] for h in host_ids}
    bucket_host: List[Optional[str]] = [None] * n_buckets
    order = sorted(range(n_buckets), key=lambda b: (-load[b], b))
    for b in order:
        best = min(
            host_ids,
            key=lambda h: ((host_load[h] + load[b]) / len(list(hosts[h])), h),
        )
        bucket_host[b] = best
        host_load[best] += load[b]
        assigned[best].append(b)
    # level 2: the single-host ladder per host, over that host's chips only
    bucket_devs: List[Tuple[object, ...]] = [()] * n_buckets
    for h in host_ids:
        bs = sorted(assigned[h])
        if not bs:
            continue
        sub = plan_assignments([load[b] for b in bs], list(hosts[h]), slots)
        for b, devs in zip(bs, sub):
            bucket_devs[b] = devs
    plan = TwoLevelPlan(hosts=tuple(bucket_host), devices=tuple(bucket_devs))
    validate_plan(plan, hosts)
    return plan


class TwoLevelPlanner:
    """EWMA per-bucket rates -> hysteretic two-level plan.

    The single-host planner's contract, host-aware: `observe` folds one
    window's admitted-arrival counts, `replan` returns the plan to serve
    with — the CURRENT one unless the candidate's peak per-chip load beats
    it by the `hysteresis` margin or the current plan references a removed
    host.  `remove_host` force-replans (an invalid plan is never held);
    `add_host` restores capacity for the next clearing re-plan."""

    def __init__(self, num_buckets: int, hosts: Dict[str, Sequence],
                 slots: int, alpha: float = 0.5, hysteresis: float = 0.2):
        if num_buckets < 1:
            raise ValueError("planner needs at least one bucket")
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.hosts: Dict[str, List] = {h: list(d) for h, d in hosts.items()}
        self.slots = int(slots)
        self.alpha = float(alpha)
        self.hysteresis = float(hysteresis)
        self.rates = [0.0] * num_buckets
        self.replans = 0
        self.plan = plan_two_level(self.rates, self.hosts, self.slots)

    def observe(self, arrivals: Sequence[float]) -> None:
        """Same rate unit as the single-host planner: admitted arrivals per
        re-plan window, no wall clock involved."""
        if len(arrivals) != len(self.rates):
            raise ValueError(
                f"got {len(arrivals)} arrival counts for "
                f"{len(self.rates)} buckets"
            )
        a = self.alpha
        self.rates = [
            (1.0 - a) * r + a * float(n) for r, n in zip(self.rates, arrivals)
        ]

    def _invalid(self) -> bool:
        cur = self.plan
        for h, devs in zip(cur.hosts, cur.devices):
            if h not in self.hosts:
                return True
            host_devs = self.hosts[h]
            if any(d not in host_devs for d in devs):
                return True
        return False

    def replan(self) -> TwoLevelPlan:
        """Adopt the candidate only when it is enough better (hysteresis)
        or the current plan is invalid (host removed)."""
        invalid = self._invalid()
        candidate = plan_two_level(self.rates, self.hosts, self.slots)
        if (candidate.hosts == self.plan.hosts
                and candidate.devices == self.plan.devices):
            return self.plan
        if not invalid:
            cur_peak = peak_device_load(self.plan.devices, self.rates)
            new_peak = peak_device_load(candidate.devices, self.rates)
            if new_peak * (1.0 + self.hysteresis) >= cur_peak:
                return self.plan  # not enough better: keep, don't thrash
        self.plan = candidate
        self.replans += 1
        obs_registry().counter(
            "mho_mesh_replans_total", "two-level placement switches applied"
        ).inc()
        obs_events.emit(
            "mesh_placement", plan=self.plan.describe(),
            rates=[round(r, 4) for r in self.rates],
            hosts=sorted(self.hosts), forced=bool(invalid),
        )
        return self.plan

    def remove_host(self, host: str) -> TwoLevelPlan:
        """Host loss: drop it from the table and re-plan immediately —
        hysteresis cannot hold a plan that references a dead host."""
        self.hosts.pop(host, None)
        if not self.hosts:
            raise ValueError("two-level fleet is empty after host removal")
        obs_registry().counter(
            "mho_mesh_hosts_lost_total", "hosts dropped from the fleet"
        ).inc(host=str(host))
        return self.replan()

    def add_host(self, host: str, devices: Sequence) -> TwoLevelPlan:
        """Host recovery: restore its chips; adoption waits for a re-plan
        that clears hysteresis (recovery is never forced mid-window)."""
        if not list(devices):
            raise ValueError(f"host '{host}' has no devices")
        self.hosts[host] = list(devices)
        return self.replan()


def local_placement(
    plan: TwoLevelPlan,
    host: str,
    local_devices: Sequence,
    fallback_device=None,
) -> PlacementPlan:
    """Project the fleet plan onto ONE process: buckets owned by `host`
    keep their chip assignment translated onto this process's local device
    objects (position-for-position — the plan was built against this
    host's advertised chip list, same length and order); buckets owned by
    OTHER hosts get a single-device placeholder so the executor's plan
    stays total.  Placeholder buckets are never dispatched locally —
    host-level routing sends their traffic elsewhere — except during a
    kill-a-host takeover, where the placeholder IS the failover placement
    (an expected compile, bit-identical decisions, exactly like any other
    re-placement)."""
    locals_ = list(local_devices)
    if not locals_:
        raise ValueError("local_placement needs at least one local device")
    fb = fallback_device if fallback_device is not None else locals_[0]
    out = []
    for b, (h, devs) in enumerate(zip(plan.hosts, plan.devices)):
        if h != host:
            out.append((fb,))
            continue
        if len(devs) > len(locals_):
            raise ValueError(
                f"bucket {b} plans {len(devs)} chips but host '{host}' "
                f"has {len(locals_)} locally"
            )
        out.append(tuple(locals_[: len(devs)]))
    return PlacementPlan(tuple(out))

"""`jax.distributed` process-group bring-up, owned in one place.

All `jax.distributed.initialize` / `jax.process_index` calls for the repo
live in this module (lint rule JX010 keeps it that way): scattering
process-group bring-up across entry points is how a fleet ends up with n
independent single-process runs that LOOK like a cluster.

Two entry points:

  * `init_distributed` — env-hint autodetection (GKE/Slurm/TPU-pod
    metadata), moved here verbatim from `parallel.mesh` which re-exports
    it.  Single-process runs no-op; a named coordinator that fails stays
    an error.
  * `bootstrap` — the serving path: explicit coordinator/process identity
    (args or `MHO_MESH_*` env), retry with exponential backoff until a
    deadline (workers routinely start before their coordinator binds),
    and a `MeshRuntime` handle that names this process's host and can
    tabulate every host's chips for the two-level planner.

The CPU-provable mode is nothing special: two local processes over
`XLA_FLAGS=--xla_force_host_platform_device_count=N` virtual devices form
a real `jax.distributed` group on localhost (`free_port` + `worker_env`
build the child environment; `mho-mesh --smoke` drives it end to end).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import time
from typing import Dict, List, Optional

import jax

from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs.registry import registry as obs_registry

# env carrying explicit process identity into `bootstrap` (worker_env sets
# these for smoke-mode children; a launcher can set them for real fleets)
ENV_COORDINATOR = "MHO_MESH_COORDINATOR"
ENV_NUM_PROCESSES = "MHO_MESH_NUM_PROCESSES"
ENV_PROCESS_ID = "MHO_MESH_PROCESS_ID"

_initialized = False  # jax.distributed.initialize is once-per-process


def host_name(process_index: int) -> str:
    """The canonical host id for a process index — the `host=` label value
    in federated metrics and the host key in two-level plans."""
    return f"host{int(process_index)}"


def free_port() -> int:
    """An OS-assigned localhost port for a smoke-mode coordinator."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker_env(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_devices: int = 2,
    base_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """The child environment for one CPU smoke-mode worker process.

    `XLA_FLAGS` must be in the environment BEFORE the child imports jax —
    that is why smoke mode spawns subprocesses instead of threads: the
    virtual-device count is a backend-init-time setting."""
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={int(local_devices)}"
    )
    env[ENV_COORDINATOR] = coordinator
    env[ENV_NUM_PROCESSES] = str(int(num_processes))
    env[ENV_PROCESS_ID] = str(int(process_id))
    return env


@dataclasses.dataclass(frozen=True)
class MeshRuntime:
    """One process's view of the formed group."""

    process_id: int
    num_processes: int
    coordinator_address: Optional[str]

    @property
    def host(self) -> str:
        return host_name(self.process_id)

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    def local_devices(self) -> List:
        """The devices THIS process may place computations on.  Under
        `jax.distributed`, `jax.devices()` is the global fleet — placing
        onto a non-addressable device is an error, so serving always
        builds from the local list."""
        return list(jax.local_devices())

    def host_table(self) -> Dict[str, List[int]]:
        """Every host's chips as global device ids, grouped by owning
        process — identical on every process of the group (it is read off
        the shared global device list), which is what lets each process
        derive the same two-level plan with zero coordination traffic."""
        table: Dict[str, List[int]] = {}
        for d in jax.devices():
            table.setdefault(host_name(d.process_index), []).append(d.id)
        return {h: sorted(ids) for h, ids in sorted(table.items())}

    def describe(self) -> dict:
        return {
            "host": self.host,
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "coordinator": self.coordinator_address,
            "local_devices": [d.id for d in self.local_devices()],
            "global_devices": len(jax.devices()),
        }


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else None


def bootstrap(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    timeout_s: float = 60.0,
    backoff_s: float = 0.25,
    max_backoff_s: float = 2.0,
) -> MeshRuntime:
    """Join (or be) the process group, retrying until `timeout_s`.

    Identity comes from the explicit args, else the `MHO_MESH_*` env set
    by `worker_env` / a launcher.  With neither (or a group of one) this
    is a single-process runtime — no coordination service is started, the
    returned handle just says so.

    Workers starting before their coordinator binds are the NORMAL case,
    not an error: each failed attempt backs off exponentially (counted in
    `mho_mesh_bootstrap_retries_total`) until the deadline, and only a
    coordinator still unreachable at the deadline raises."""
    global _initialized
    coordinator_address = coordinator_address or os.environ.get(
        ENV_COORDINATOR) or None
    if num_processes is None:
        num_processes = _env_int(ENV_NUM_PROCESSES)
    if process_id is None:
        process_id = _env_int(ENV_PROCESS_ID)

    if coordinator_address is None or (num_processes or 1) <= 1:
        rt = MeshRuntime(process_id=0, num_processes=1,
                         coordinator_address=None)
        obs_events.emit("mesh_bootstrap", **rt.describe(), attempts=0)
        return rt

    if _initialized:
        # initialize() is once-per-process; a second bootstrap just
        # re-reads the already-formed group
        rt = MeshRuntime(process_id=jax.process_index(),
                         num_processes=jax.process_count(),
                         coordinator_address=coordinator_address)
        return rt

    retries = obs_registry().counter(
        "mho_mesh_bootstrap_retries_total",
        "failed jax.distributed bring-up attempts before success",
    )
    deadline = time.monotonic() + float(timeout_s)  # nondet-ok(bring-up deadline is real wall time: the coordinator is an external process)
    delay = float(backoff_s)
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()  # nondet-ok(same wall-clock deadline)
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=max(1, int(remaining)),
            )
            break
        except Exception as exc:
            if time.monotonic() + delay >= deadline:  # nondet-ok(same wall-clock deadline)
                raise RuntimeError(
                    f"mesh bootstrap: coordinator {coordinator_address} "
                    f"unreachable after {attempt} attempt(s) over "
                    f"{timeout_s:.0f}s"
                ) from exc
            retries.inc()
            time.sleep(delay)
            delay = min(delay * 2.0, float(max_backoff_s))
    _initialized = True
    rt = MeshRuntime(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        coordinator_address=coordinator_address,
    )
    obs_events.emit("mesh_bootstrap", **rt.describe(), attempts=attempt)
    return rt


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Multi-host bring-up: join the JAX distributed runtime so
    `jax.devices()` spans every host and `make_mesh` lays the `data` axis
    across DCN while `graph` stays on-host ICI.

    The reference has no distributed backend at all (SURVEY.md §5.8) — this
    is the framework's NCCL/MPI-equivalent entry point, built on JAX's own
    coordination service.  Explicit args win; otherwise standard cluster env
    detection (GKE/Slurm/TPU pod metadata) applies; single-process runs
    no-op.  Returns this process's index.
    """
    global _initialized
    if any(a is not None for a in (coordinator_address, num_processes, process_id)):
        # any explicit arg selects the explicit path; incomplete sets are
        # jax.distributed's own error to raise, not ours to mask
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        return jax.process_index()
    # strong hints name a coordinator outright; weak hints suggest a
    # scheduler/pod context, but only count when they actually imply more
    # than one process — axon hosts export TPU_WORKER_HOSTNAMES=localhost
    # (one entry) on plain single-process runs, and a 1-task SLURM
    # allocation is not a cluster either
    strong_hints = (
        "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",
    )
    has_strong = any(h in os.environ for h in strong_hints)

    def _weak_multiprocess() -> bool:
        def as_int(name):
            try:
                return int(os.environ.get(name, ""))
            except ValueError:
                return 0

        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        n_hosts = len([h for h in hosts.split(",") if h.strip()])
        return (
            n_hosts > 1
            or as_int("OMPI_COMM_WORLD_SIZE") > 1
            or ("SLURM_JOB_ID" in os.environ
                and max(as_int("SLURM_NTASKS"), as_int("SLURM_NPROCS")) > 1)
            # Cloud TPU pods export a task id; jax auto-detects the rest
            # from TPU metadata, so its presence alone warrants an attempt
            or "CLOUD_TPU_TASK_ID" in os.environ
        )

    if not has_strong and not _weak_multiprocess():
        return 0  # genuinely single-process: no multi-process context
    try:
        jax.distributed.initialize()
    except ValueError:
        if not has_strong:
            # auto-detection could not assemble a cluster spec from weak
            # hints alone — "no cluster", not a failed bring-up (no
            # exception-text parsing: ValueError is jax.distributed's
            # incomplete-spec signal; RuntimeErrors still propagate)
            return 0
        raise  # a named coordinator that fails to resolve IS misconfiguration
    # real bring-up failures (RuntimeError: coordinator unreachable, RPC
    # errors) propagate — never silently degrade a configured cluster into
    # n independent single-process runs
    _initialized = True
    return jax.process_index()

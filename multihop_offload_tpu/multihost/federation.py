"""Cross-process metric and SLO federation.

Every serving process already exposes its whole metric surface as the
Prometheus text exposition (`obs.registry.MetricRegistry.prometheus_text`).
Federation adds nothing to the data plane: each process gets a tiny stdlib
HTTP endpoint (`MetricsEndpoint`) serving that text, and the coordinator
runs a `FleetFederation` that scrapes every endpoint and merges the series
into ONE registry with a `host=` label per source process.

The merge is DELTA-based, not copy-based: counters and histogram buckets
are monotone on the source, so each scrape applies `current - last_seen`
to the federated series (gauges are plain last-write).  That makes the
federated registry a real registry — `Counter.total`, `Histogram.le_total`
and quantiles all work — so the existing `obs.slo.SLOEngine` pointed at it
(`federated_slo_engine`) computes FLEET-WIDE burn rates with zero changes
to the SLO code, and per-host breakdowns fall out of the `host=` label.

A dead host is data, not an exception: its scrape failure sets
`mho_mesh_host_up{host=...} 0`, bumps the failure counter, and its
last-known series stay in the federated registry (a crashed host's
requests still count toward the fleet totals — conservation checks in the
kill-a-host drill depend on that).
"""

from __future__ import annotations

import re
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from multihop_offload_tpu.obs.registry import (
    MetricRegistry,
    registry as default_registry,
)
from multihop_offload_tpu.obs.slo import SLOEngine, default_serving_slos

_LabelKey = Tuple[Tuple[str, str], ...]

_SAMPLE_RE = re.compile(
    r'^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$'
)
_LABEL_RE = re.compile(r'(?P<k>[A-Za-z_][A-Za-z0-9_]*)="(?P<v>[^"]*)"')


def _parse_labels(raw: Optional[str]) -> _LabelKey:
    if not raw:
        return ()
    return tuple(sorted(
        (m.group("k"), m.group("v")) for m in _LABEL_RE.finditer(raw)
    ))


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Reassemble an exposition into typed metric families.

    Returns {name: {"kind": kind, "series": {...}}}.  Counter/gauge series
    map label-key -> float.  Histogram series are re-assembled from their
    `_bucket`/`_sum`/`_count` sample lines into label-key ->
    {"buckets": [per-bucket counts, +Inf tail last], "sum": float,
    "count": int}, with the family carrying "boundaries" (the finite `le`
    edges) — exactly the shape `Histogram.observe_bucketed` merges."""
    kinds: Dict[str, str] = {}
    flat: Dict[str, Dict[_LabelKey, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value").replace("+Inf", "inf"))
        except ValueError:
            continue
        flat.setdefault(m.group("name"), {})[
            _parse_labels(m.group("labels"))] = value

    out: Dict[str, dict] = {}
    for name, kind in kinds.items():
        if kind != "histogram":
            out[name] = {"kind": kind, "series": dict(flat.get(name, {}))}
            continue
        # histograms: decumulate _bucket lines grouped by their non-le labels
        series: Dict[_LabelKey, dict] = {}
        boundaries: List[float] = []
        cum: Dict[_LabelKey, List[Tuple[float, float]]] = {}
        for key, v in flat.get(f"{name}_bucket", {}).items():
            le = dict(key).get("le", "")
            base = tuple(kv for kv in key if kv[0] != "le")
            edge = float("inf") if le == "+Inf" else float(le)
            cum.setdefault(base, []).append((edge, v))
        for base, pairs in cum.items():
            pairs.sort()
            edges = [e for e, _ in pairs if e != float("inf")]
            if len(edges) > len(boundaries):
                boundaries = edges
            counts, prev = [], 0.0
            for _, c in pairs:
                counts.append(int(c - prev))
                prev = c
            series[base] = {
                "buckets": counts,
                "sum": flat.get(f"{name}_sum", {}).get(base, 0.0),
                "count": int(flat.get(f"{name}_count", {}).get(base, 0)),
            }
        out[name] = {"kind": kind, "series": series,
                     "boundaries": boundaries}
    return out


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        body = self.server.render().encode()  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsEndpoint:
    """This process's scrape target: a daemon-thread stdlib HTTP server
    rendering the (default) registry's text exposition at every GET.
    Port 0 (the default) takes an OS-assigned port; `url` is what the
    coordinator scrapes."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        reg = registry if registry is not None else default_registry()
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.render = reg.prometheus_text  # type: ignore[attr-defined]
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="mho-metrics", daemon=True
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._server.server_address[0]}:{self.port}/metrics"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def _http_fetch(url: str, timeout_s: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode()


class FleetFederation:
    """Scrape every host's endpoint, merge deltas under `host=` labels.

    `targets` maps host id -> scrape URL (or, for tests, a zero-arg
    callable returning exposition text).  The merged registry defaults to
    a PRIVATE one so fleet series never collide with this process's own
    serving metrics — pass `registry=` to merge elsewhere."""

    def __init__(self, targets: Dict[str, object],
                 registry: Optional[MetricRegistry] = None,
                 timeout_s: float = 2.0):
        self.targets = dict(targets)
        self.registry = registry if registry is not None else MetricRegistry()
        self.timeout_s = float(timeout_s)
        # last cumulative value per (host, metric, labelkey): delta base
        self._last: Dict[Tuple[str, str, _LabelKey], object] = {}

    def _fetch(self, target) -> str:
        if callable(target):
            return target()
        return _http_fetch(str(target), self.timeout_s)

    def scrape(self) -> Dict[str, bool]:
        """One federation pass.  Returns {host: scrape_ok}."""
        up = self.registry.gauge(
            "mho_mesh_host_up", "1 if the host's last scrape succeeded")
        fails = self.registry.counter(
            "mho_mesh_scrape_failures_total", "failed federation scrapes")
        ok: Dict[str, bool] = {}
        for host in sorted(self.targets):
            try:
                families = parse_prometheus_text(self._fetch(
                    self.targets[host]))
            except Exception:
                fails.inc(host=host)
                up.set(0.0, host=host)
                ok[host] = False
                continue  # last-known series stay merged
            self._merge(host, families)
            up.set(1.0, host=host)
            ok[host] = True
        return ok

    def _merge(self, host: str, families: Dict[str, dict]) -> None:
        for name, fam in sorted(families.items()):
            kind = fam["kind"]
            if kind == "counter":
                c = self.registry.counter(name)
                for key, v in fam["series"].items():
                    mark = (host, name, key)
                    prev = float(self._last.get(mark, 0.0))  # type: ignore[arg-type]
                    if v < prev:
                        prev = 0.0  # source restarted: treat as fresh
                    delta = v - prev
                    self._last[mark] = v
                    if delta > 0:
                        c.inc(delta, host=host, **dict(key))
            elif kind == "gauge":
                g = self.registry.gauge(name)
                for key, v in fam["series"].items():
                    g.set(v, host=host, **dict(key))
            elif kind == "histogram":
                boundaries = fam.get("boundaries") or []
                if not boundaries:
                    continue
                h = self.registry.histogram(name, buckets=boundaries)
                if tuple(h.buckets) != tuple(boundaries):
                    continue  # boundary clash with an existing family
                for key, s in fam["series"].items():
                    mark = (host, name, key)
                    prev = self._last.get(mark)
                    pb = list(prev["buckets"]) if prev else [0] * len(s["buckets"])  # type: ignore[index]
                    psum = float(prev["sum"]) if prev else 0.0  # type: ignore[index]
                    if s["count"] < (int(prev["count"]) if prev else 0):  # type: ignore[index]
                        pb, psum = [0] * len(s["buckets"]), 0.0
                    delta = [int(c) - int(p) for c, p in zip(s["buckets"], pb)]
                    self._last[mark] = s
                    if any(d > 0 for d in delta):
                        h.observe_bucketed(
                            delta, s["sum"] - psum, host=host, **dict(key))


def federated_slo_engine(
    federation: FleetFederation,
    specs: Optional[Sequence] = None,
    **engine_kw,
) -> SLOEngine:
    """The fleet-wide SLO view: the stock serving SLO specs (or `specs`)
    evaluated over the federation's merged registry — burn rates across
    every host's traffic at once, because the merged histograms/counters
    ARE the fleet totals."""
    return SLOEngine(
        list(specs) if specs is not None else default_serving_slos(),
        registry=federation.registry,
        **engine_kw,
    )

"""Multi-host mesh federation: crossing the host boundary.

Three layers, each usable alone:

  * `multihost.runtime`    — `jax.distributed` process-group bootstrap with
    coordinator retry/timeout/backoff, plus the CPU-provable two-local-
    process mode over `XLA_FLAGS=--xla_force_host_platform_device_count`
    virtual devices (`mho-mesh --smoke`);
  * `multihost.plan`       — the two-level DCN-aware placement planner:
    buckets -> hosts (level 1, weights replicated per host), bucket batch
    axes -> chips within the host (level 2, delegated to the existing
    `serve.placement` divisor ladder).  A bucket NEVER spans the DCN
    boundary; same EWMA/hysteresis contract as the single-host planner;
  * `multihost.federation` — cross-process metric/SLO federation: every
    process keeps its existing Prometheus text exposition, a coordinator-
    side scraper merges the registries under `host=` labels so burn-rate
    SLOs and the flight recorder see fleet-wide series.

Serving decisions are bit-identical to the single-host path under ANY
placement: the per-bucket compiled closures are reused untouched — the
host boundary only moves WHERE a bucket's program runs, never what it
computes (request decisions are PRNG-keyed by request id).
"""

from multihop_offload_tpu.multihost.federation import (  # noqa: F401
    FleetFederation,
    MetricsEndpoint,
    federated_slo_engine,
    parse_prometheus_text,
)
from multihop_offload_tpu.multihost.plan import (  # noqa: F401
    TwoLevelPlan,
    TwoLevelPlanner,
    local_placement,
    plan_two_level,
    validate_plan,
)
from multihop_offload_tpu.multihost.runtime import (  # noqa: F401
    MeshRuntime,
    bootstrap,
    init_distributed,
)

__all__ = [
    "FleetFederation",
    "MetricsEndpoint",
    "federated_slo_engine",
    "parse_prometheus_text",
    "TwoLevelPlan",
    "TwoLevelPlanner",
    "local_placement",
    "plan_two_level",
    "validate_plan",
    "MeshRuntime",
    "bootstrap",
    "init_distributed",
]

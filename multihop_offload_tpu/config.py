"""Single dataclass-based configuration shared by every entry point.

Replaces the reference's `tf.compat.v1.flags` singleton
(`/root/reference/src/gnn_offloading_agent.py:42-60`) and the argparse CLI of
its data generator (`data_generation_offloading.py:18-23`).  Flag names and
defaults mirror the reference so the bash workflows translate 1:1; TPU-specific
knobs (padding, batching, mesh shape, dtype, Chebyshev order) are new.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Optional

_DEFAULTS_PATH = os.path.join(os.path.dirname(__file__), "_defaults.json")
_SHIPPED_FALLBACK = {"precision": "fp32", "layout": "dense"}
_SHIPPED_CHOICES = {
    "precision": ("fp32", "bf16", "auto"),
    "layout": ("dense", "sparse", "auto"),
}


def shipped_defaults() -> dict:
    """The shipped `--precision` / `--layout` defaults.

    `multihop_offload_tpu/_defaults.json` is OWNED by the bench campaign
    (`mho-bench --matrix`, docs/OPERATIONS.md "Bench campaign"): the runner
    rewrites it to auto/auto only when every on-chip gate in
    `benchmarks/bench_matrix.json` passes.  Hand-editing skips the gates —
    don't.  A missing or invalid file (or any unknown value) falls back to
    the conservative fp32+dense, so a broken record can never flip the
    defaults by accident."""
    out = dict(_SHIPPED_FALLBACK)
    try:
        with open(_DEFAULTS_PATH, encoding="utf-8") as fh:
            raw = json.load(fh)
    except (OSError, ValueError):
        return out
    if not isinstance(raw, dict):
        return out
    for knob, allowed in _SHIPPED_CHOICES.items():
        if raw.get(knob) in allowed:
            out[knob] = raw[knob]
    return out


@dataclasses.dataclass
class Config:
    # ---- reference flags (gnn_offloading_agent.py:42-60) -------------------
    datapath: str = "data/aco_data_ba_100"
    out: str = "out"
    T: int = 1000                  # congestion-penalty scale t_max
    prob: bool = False             # softmax-sample the offloading decision
    training_set: str = "BAm2"     # checkpoint directory tag
    learning_rate: float = 1e-4
    learning_decay: float = 1.0    # exponential LR decay rate (1.0 = constant)
    arrival_scale: float = 0.1
    epochs: int = 201
    num_layer: int = 5
    dropout: float = 0.0
    weight_decay: float = 5e-4     # L2 regularization scale (kept for parity)
    epsilon: float = 1.0           # legacy replay-epsilon (decayed, unused by
    epsilon_min: float = 0.001     # action selection — reference quirk kept
    epsilon_decay: float = 0.985   # for parity; see SURVEY.md §8)
    gamma: float = 1.0             # unused by the reference; kept for parity
    batch: int = 100               # replay minibatch (number of stored grads)
    critic_weight: float = 1.0     # scale of the analytic-critic policy-
    #                                sensitivity term (1.0 = reference math;
    #                                0.0 trains on MSE supervision alone)
    mse_weight: float = 0.001      # scale of the MSE pull toward empirical
    #                                unit delays (`gnn_offloading_agent.py:443`)

    # ---- reference driver-level constants (AdHoc_train.py) -----------------
    num_instances: int = 10        # job-placement instances per network
    files_limit: Optional[int] = None  # cap network files visited per epoch
    #                                (bounded training slices; None = all)
    best_window: int = 20          # rolling window (file visits) of GNN-test
    #                                tau used for best-checkpoint tracking;
    #                                0 disables.  Motivated by the measured
    #                                late-training collapse (training/README)
    explore: float = 0.1           # driver-level epsilon-greedy exploration
    explore_decay: float = 0.99
    memory_size: int = 5000        # gradient-replay capacity (train); 1000 test
    ul_data: float = 100.0         # per-task uplink data size (Job defaults)
    dl_data: float = 1.0           # per-task downlink data size

    # ---- model ------------------------------------------------------------
    hidden: int = 32
    cheb_k: int = 1                # Chebyshev order; 1 reproduces the shipped
    #                                reference checkpoints (SURVEY.md §2.3);
    #                                >=2 enables the real spectral GNN.
    leaky_relu_alpha: float = 0.2  # keras LeakyReLU default negative slope
    max_norm: float = 1.0          # per-column kernel/bias max-norm constraint
    clipnorm: float = 1.0          # Adam global-norm gradient clip

    # ---- TPU-native knobs -------------------------------------------------
    dtype: str = "float32"         # computation dtype ("float64" for parity)
    precision: str = dataclasses.field(   # mixed-precision compute policy:
        default_factory=lambda: shipped_defaults()["precision"])
    #                                fp32 | bf16 | auto.  The default is
    #                                READ FROM `_defaults.json` (bench-
    #                                campaign owned — fp32 until the
    #                                precision gates pass on chip, see
    #                                `shipped_defaults`).  fp32 = identity
    #                                (everything in `dtype`); bf16 =
    #                                bfloat16 storage/compute
    #                                with fp32 params, fp32 matmul
    #                                accumulation, and the fp32 islands of
    #                                multihop_offload_tpu/precision.py
    #                                (fixed point, tau reductions, decision
    #                                costs, Laplacian constants); auto =
    #                                bf16 on a TPU backend, fp32 elsewhere.
    #                                See docs/OPERATIONS.md "Precision".
    layout: str = dataclasses.field(      # instance memory layout:
        default_factory=lambda: shipped_defaults()["layout"])
    #                                dense | sparse | auto.  The default is
    #                                READ FROM `_defaults.json` (bench-
    #                                campaign owned — dense until the
    #                                layout gates pass on chip).  dense =
    #                                the (N, N)/(L, L) matrix layout — the
    #                                parity reference; sparse =
    #                                pad-to-static edge lists + segment
    #                                reductions (layouts/ module: edge-list
    #                                ChebConv, gathered delay math, compact
    #                                int16 indices); auto = sparse on a TPU
    #                                backend, dense elsewhere.  Resolved once
    #                                at build time (never retraces a steady
    #                                program).  See docs/OPERATIONS.md
    #                                "Layouts".
    apsp_impl: str = "xla"         # all-pairs-shortest-path kernel for the
    #                                decision paths: xla | pallas | auto.
    #                                auto = fastest measured path per shape
    #                                (benchmarks/pallas_tpu.json: XLA below
    #                                padded N=512, Pallas blocked-FW above);
    #                                pallas forces the kernel (XLA fallback
    #                                off-TPU or beyond size caps).  See
    #                                ops.minplus.resolve_apsp.
    fp_impl: str = "auto"          # interference-fixed-point kernel for the
    #                                actor / critic / empirical evaluator:
    #                                xla | pallas | auto.  auto = the Pallas
    #                                VMEM-resident kernel where its on-chip
    #                                win is measured (padded L<=256: 2.44x,
    #                                benchmarks/pallas_tpu.json), XLA scan
    #                                elsewhere and off-TPU.  See
    #                                ops.fixed_point.resolve_fixed_point.
    compat_diagonal_bug: bool = False  # reproduce the reference's cycled
    #                                decision-path diagonal (A/B validation;
    #                                see agent.actor.compat_cycled_diagonal)
    prefetch: bool = True          # one-deep host/device pipeline in the
    #                                sequential Trainer/Evaluator loops:
    #                                build file fid+1 host-side while the
    #                                device runs fid.  Holds TWO files'
    #                                instance/jobset buffers on device during
    #                                the overlap window — disable on
    #                                HBM-tight runs.
    file_batch: int = 1            # files evaluated per device program in
    #                                the Evaluator (vmap over stacked files;
    #                                multiplies with the data-mesh width)
    pad_nodes: Optional[int] = None    # None = derive from data (next multiple)
    pad_links: Optional[int] = None
    pad_ext: Optional[int] = None
    pad_jobs: Optional[int] = None
    pad_servers: Optional[int] = None
    round_to: int = 8              # pad sizes up to a multiple of this
    pad_buckets: int = 1           # size buckets per dataset: each bucket
    #                                compiles once at its own pad shape
    #                                (1 = single global shape)
    seed: int = 0                  # workload RNG (reference is unseeded)
    mesh_data: int = 0             # data-parallel mesh axis size: 0 = auto
    #                                (all local devices — Trainer/Evaluator
    #                                shard episodes/files when >1 chip is
    #                                present), 1 = force single-device, N =
    #                                explicit axis size
    mesh_graph: int = 1            # graph-partition (ring APSP) axis size
    csv_write_all_hosts: bool = False  # multi-process runs: every process
    #                                writes its own (shard) CSV instead of
    #                                gating on process_index()==0 — used by
    #                                per-process file-sharded evaluation
    #                                (scripts/multiprocess_eval.py); keep
    #                                False when all hosts share one out dir
    # ---- serving (serve/ subsystem; cli.serve + scripts/serve_loadgen) -----
    serve_slots: int = 8           # requests batched per bucket per tick —
    #                                the dispatch amortization factor (one
    #                                fused program serves `serve_slots`
    #                                requests)
    serve_queue_cap: int = 64      # bounded admission queue (backpressure:
    #                                submits beyond this are refused)
    serve_deadline_s: float = 0.5  # degradation budget: a tick whose oldest
    #                                pending request is older than this serves
    #                                that batch with the analytic greedy
    #                                baseline instead of the GNN
    serve_buckets: int = 2         # shape buckets in the serving ladder
    serve_sizes: str = "16,24"     # node sizes of the demo traffic pool
    #                                (cli.serve synthetic workload)
    serve_requests: int = 64       # demo request count (cli.serve)
    serve_mesh: int = 0            # sharded serving: lay each bucket's batch
    #                                axis over the first N local devices
    #                                (0/1 = single-device executor); the
    #                                placement planner assigns hot buckets
    #                                more chips from observed arrival rates
    serve_devices: str = ""        # explicit device-id list "0,2,5" for the
    #                                serving fleet (overrides serve_mesh)
    serve_replan_ticks: int = 16   # placement re-plan cadence (ticks); plans
    #                                change BETWEEN ticks, never mid-program
    serve_ragged: bool = False     # occupancy-aware serving: cold buckets tick
    #                                at a narrower compiled width chosen by the
    #                                EWMA occupancy ladder (single-device
    #                                executor; decisions stay bit-identical)
    serve_overlap: bool = False    # cross-tick double buffering: defer each
    #                                tick's device sync to the next tick so
    #                                host packing overlaps device compute
    serve_ladder_alpha: float = 0.5       # EWMA weight of the occupancy ladder
    serve_ladder_hysteresis: float = 0.25  # narrow a rung only when
    #                                EWMA*(1+h) fits it — jitter never
    #                                thrashes a compile
    model_root: str = "model"      # parent dir of checkpoint directories
    tb_logdir: str = ""            # TensorBoard scalars ("" = disabled); the
    #                                working version of the reference's
    #                                disabled log_init/log_scalar hooks
    # ---- simulation (sim/ subsystem; cli.sim + sim.fidelity) ---------------
    sim_policy: str = "baseline"   # offloading policy in the loop:
    #                                baseline | local | gnn (gnn loads the
    #                                configured checkpoint, fresh init if none)
    sim_fleet: int = 8             # instances simulated in one vmapped program
    sim_nodes: int = 10            # nodes per random BA scenario graph
    sim_jobs: int = 4              # jobs per instance
    sim_rounds: int = 5            # policy re-decisions per run (outer scan)
    sim_slots: int = 1000          # slots per policy round (inner scan)
    sim_util: float = 0.5          # analytic bottleneck-utilization target the
    #                                workload is rescaled to before simulating
    sim_margin: float = 5.0        # slot sizing: dt = 1/(margin * max link
    #                                rate) — larger = finer slots, less
    #                                discretization bias, more slots per unit
    #                                of model time
    sim_cap: int = 128             # ring-buffer capacity per queue (overflow
    #                                packets are dropped and counted)
    sim_fail_links: int = 0        # random links to fail at mid-horizon
    sim_fail_nodes: int = 0        # random non-server nodes to fail likewise
    sim_out: str = ""              # write the run/fidelity JSON record here
    #                                ("" = print only / default record path)
    # ---- scenario matrix (scenarios/ subsystem; cli.scenarios) -------------
    scenario_fleet: int = 4        # lanes (seeded draws) per scenario preset
    scenario_segments: int = 4     # sim segments per scenario — the traffic
    #                                model modulates arrivals PER SEGMENT and
    #                                mobility re-wires at segment boundaries
    scenario_rounds: int = 2       # policy re-decisions per segment
    scenario_slots: int = 300      # slots per policy round
    scenario_cap: int = 64         # per-queue ring-buffer capacity
    scenario_margin: float = 5.0   # slot sizing, as sim_margin
    scenario_names: str = ""       # comma list restricting the matrix to
    #                                these presets ("" = all presets)
    scenario_out: str = ""         # matrix record path ("" = the default
    #                                benchmarks/scenario_matrix.json)
    # ---- on-device RL (rl/ subsystem; cli.rl) ------------------------------
    rl_steps: int = 30             # compiled train steps per `mho-rl train`
    rl_fleet: int = 4              # episodes (instances) per train step —
    #                                the vmapped/sharded batch axis
    rl_rounds: int = 3             # policy re-decisions per episode (the
    #                                rollout's outer scan; scenario shape
    #                                comes from the sim_* knobs)
    rl_slots: int = 120            # sim slots per policy round (inner scan)
    rl_temp: float = 0.5           # categorical temperature over the offload
    #                                cost table (higher = more exploration)
    rl_delay_weight: float = 0.05  # reward = delivered_ratio - weight *
    #                                mean delivered delay (model-time units)
    rl_ent: float = 0.05           # entropy-bonus weight in the surrogate
    #                                loss (guards against premature
    #                                deterministic collapse of REINFORCE)
    rl_buffer: int = 64            # on-device reward ring capacity backing
    #                                the REINFORCE running-mean baseline
    rl_util: float = 0.7           # analytic bottleneck-utilization target
    #                                (rho) the RL scenarios are rescaled to
    rl_lr: float = 2e-3            # Adam learning rate for the in-scan
    #                                update (the offline `learning_rate` is
    #                                tuned for file visits, not episodes)
    rl_mesh: int = 1               # fleet-batch mesh axis size: 1 = single
    #                                device, N = shard_map the fleet over N
    #                                devices (grads pmean'd in-program)
    rl_out: str = ""               # write the smoke/train JSON record here
    #                                ("" = benchmarks/rl_smoke.json in
    #                                --smoke mode, print only otherwise)
    # ---- observability (obs/ subsystem; docs/OPERATIONS.md) ----------------
    obs_log: str = ""              # structured JSONL run-log sink ("" =
    #                                disabled): manifest header + typed
    #                                step/tick/checkpoint events; render with
    #                                `mho-obs <path>`.  Enabling also installs
    #                                the jax retrace/compile listeners
    obs_prom: str = ""             # write the final metric-registry snapshot
    #                                as Prometheus text exposition to this
    #                                path at loop exit ("" = disabled)
    obs_trace: bool = True         # request-scoped trace hops in the run log
    #                                (submit/pack/dispatch/... events; only
    #                                emitted when obs_log is active, so the
    #                                default costs nothing without a log)
    obs_flight_capacity: int = 256  # flight-recorder ring size (per-tick
    #                                diagnostics retained for breach dumps)
    obs_log_max_bytes: int = 0     # size-cap per JSONL segment: when the
    #                                active run log would grow past this, it
    #                                is rotated to `<path>.NNNN` and a fresh
    #                                segment opened (0 = never rotate).  The
    #                                continual-learning flywheel tails serve
    #                                logs forever, so long-running services
    #                                should set this; `obs.events.read_events`
    #                                spans segment boundaries transparently
    # ---- continual learning (loop/ subsystem; cli.loop) --------------------
    loop_capture_sample: float = 0.0   # fraction of served requests emitted
    #                                as `outcome` experience events through
    #                                the active run log (0 = capture off);
    #                                sampling is deterministic by request id
    loop_capture_requests: int = 48    # requests per capture window (cli.loop
    #                                drives its own synthetic traffic)
    loop_refit_steps: int = 20     # fine-tuning steps per background re-fit
    loop_refit_slots: int = 4      # experience outcomes batched per refit step
    loop_holdout_frac: float = 0.25    # outcome fraction held out of the
    #                                refit and replayed in sim for the A/B
    loop_gate_delivered_drop: float = 0.02  # promotion gate: candidate sim
    #                                delivered ratio may trail the champion
    #                                by at most this (absolute)
    loop_gate_tau_ratio: float = 1.10  # promotion gate: candidate mean sim
    #                                packet delay at most champion * this
    loop_monitor_regression: float = 1.5   # post-promotion watchdog: measured
    #                                tau beyond pre-promotion * this triggers
    #                                automatic rollback
    loop_cycles: int = 1           # flywheel cycles for `mho-loop run`
    loop_sim_rounds: int = 2       # A/B validation sim: policy rounds
    loop_sim_slots: int = 200      # A/B validation sim: slots per round
    loop_out: str = ""             # write the cycle/smoke JSON record here
    loop_drift: bool = False       # gate flywheel capture on obs.drift: a
    #                                cycle only enters `capturing` when a
    #                                detector trips on the outcome stream
    #                                (`drift_triggered` transitions)
    loop_candidate_keep: int = 2   # bounded retention in orbax_candidate/:
    #                                after a reject/rollback keep only the
    #                                newest K candidate checkpoints, delete
    #                                older ones with a typed `gc` event
    loop_cooldown_s: float = 0.0   # post-rollback cool-down: no new flywheel
    #                                cycle starts until this many seconds
    #                                after the rollback (journaled, so it
    #                                survives a process restart; 0 = off)
    # ---- health (obs/slo + flightrec; `mho-health`) ------------------------
    health_short_s: float = 60.0   # SLO burn-rate short window (seconds)
    health_long_s: float = 300.0   # SLO burn-rate long window (seconds)
    health_out: str = ""           # write the health-smoke JSON record here
    health_watchdog_s: float = 0.0  # serve-tick watchdog: a bucket dispatch
    #                                slower than this is `slow` (counter +
    #                                event); one slower than 10x is `stuck`
    #                                (flight-recorder dump + degrade the
    #                                bucket to the greedy baseline). 0 = off
    health_watchdog_recovery_s: float = 0.0  # how long a stuck bucket stays
    #                                degraded-to-baseline before the GNN
    #                                program is retried
    # ---- durability (utils.durable; chaos drills) --------------------------
    io_retries: int = 3            # bounded-retry attempts around fallible
    #                                I/O (orbax save/restore, event-log
    #                                writes, journal writes)
    io_backoff_s: float = 0.05     # initial retry backoff (doubles per
    #                                attempt)
    chaos_out: str = ""            # write the chaos-smoke JSON record here

    # --- performance observability (obs/prof, mho-prof) ---
    prof_seconds: float = 1.0      # mho-prof capture: seconds of bench-step
    #                                work to run under the profiler trace
    prof_out: str = ""             # mho-prof: capture bundle dir (default
    #                                prof_trace/) or smoke record path
    #                                (default benchmarks/prof_smoke.json)

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp

        table = {"float32": jnp.float32, "float64": jnp.float64,
                 "bfloat16": jnp.bfloat16}
        if self.dtype not in table:
            raise ValueError(
                f"unsupported dtype '{self.dtype}'; choose one of {sorted(table)}"
            )
        return table[self.dtype]

    @property
    def precision_policy(self):
        """The resolved `multihop_offload_tpu.precision.PrecisionPolicy` for
        this (precision, dtype) pair — build-time configuration, resolved
        once per consumer and baked into closures (never traced)."""
        from multihop_offload_tpu.precision import resolve_precision

        return resolve_precision(self.precision, self.jnp_dtype)

    @property
    def layout_policy(self):
        """The resolved `multihop_offload_tpu.layouts.LayoutPolicy` for this
        config — same build-time contract as `precision_policy`: resolved
        once per consumer, baked into closures, never traced."""
        from multihop_offload_tpu.layouts import resolve_layout

        return resolve_layout(self.layout)

    def model_dir(self, root: Optional[str] = None) -> str:
        """Checkpoint directory; naming mirrors `AdHoc_train.py:59`."""
        import os

        return os.path.join(
            root if root is not None else self.model_root,
            "model_ChebConv_{}_a{}_c{}_ACO_agent".format(
                self.training_set, self.num_layer, self.num_layer
            ),
        )


def _add_bool(parser: argparse.ArgumentParser, name: str, default: bool, help_: str):
    parser.add_argument(
        f"--{name}", type=lambda s: s.lower() in ("1", "true", "yes"),
        default=default, help=help_,
    )


def build_parser(defaults: Optional[Config] = None) -> argparse.ArgumentParser:
    cfg = defaults or Config()
    p = argparse.ArgumentParser(description=__doc__)
    for f in dataclasses.fields(Config):
        d = getattr(cfg, f.name)
        if f.type == "bool" or isinstance(d, bool):
            _add_bool(p, f.name, d, f.name)
        elif d is None:
            p.add_argument(f"--{f.name}", type=int, default=None)
        else:
            p.add_argument(f"--{f.name}", type=type(d), default=d)
    return p


def from_args(argv=None, defaults: Optional[Config] = None) -> Config:
    ns = build_parser(defaults).parse_args(argv)
    return Config(**vars(ns))

"""Instance memory layouts: dense (N, N) parity reference vs padded edge lists.

The wireless graphs are sparse (BA, |E| ~ 2N-4N out of N^2 pairs), yet the
dense layout streams (N, N) Laplacians, (L, L) conflict matrices, and (L, J)
incidence scatters through HBM every step — BENCH_r05 pins the step at
arithmetic intensity 0.117.  The `sparse` layout stores graph structure as
pad-to-static edge lists (arXiv:1906.11786: padded src/dst index vectors +
segment-sum instead of dense matmul) and rewrites the ChebConv recurrence,
the per-link arrival/delay reductions, and the next-hop construction as
gathers + segment reductions.  APSP keeps its (N, N) all-pairs OUTPUT
(inherently dense) but runs k-blocked min-plus squarings
(`env.apsp.apsp_minplus_blocked`, bit-identical) so the (N, N, N)
squaring temp never materializes; its input weight matrix is
scatter-built on device.

Like `precision`, the knob is resolved ONCE at build time into a frozen
`LayoutPolicy` baked into closures — switching layouts never retraces a
steady program, and `dense` remains the default (and the parity reference)
until the on-chip gates in benchmarks/layout_ab.json pass.
"""

from multihop_offload_tpu.layouts.policy import (  # noqa: F401
    LAYOUT_CHOICES,
    LayoutPolicy,
    resolve_layout,
)
from multihop_offload_tpu.layouts.sparse import (  # noqa: F401
    SparseInstance,
    SparseSupport,
    build_sparse_instance,
    cf_nnz_count,
    ext_nnz_count,
    make_sparse_propagate,
    next_hop_from_edges,
    sparse_chebyshev_support,
    weight_matrix_from_edges,
    zeros_support,
)
from multihop_offload_tpu.layouts.compact import (  # noqa: F401
    NEXT_HOP_DTYPE,
    compact_index_dtype,
    compact_value_dtype,
    pack_next_hop,
    unpack_next_hop,
)

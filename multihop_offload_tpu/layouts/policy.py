"""The layout knob, resolved once at build time (mirror of `precision`).

`cfg.layout` is a string (dense | sparse | auto); every builder resolves it
through `resolve_layout` into a frozen, hashable `LayoutPolicy` BEFORE any
tracing happens, and bakes the resolved policy into its jitted closures —
exactly the `resolve_precision` contract, so flipping the knob costs one
rebuild, never a mid-steady retrace.

`auto` picks `sparse` on a TPU backend (where the bandwidth wall bites) and
`dense` elsewhere — same shape as precision's `auto -> bf16 on TPU`.  The
config DEFAULT stays `dense` until the on-chip gates recorded in
benchmarks/layout_ab.json pass (see OPERATIONS.md "Layouts").
"""

from __future__ import annotations

import dataclasses

import numpy as np

LAYOUT_CHOICES = ("dense", "sparse", "auto")


@dataclasses.dataclass(frozen=True)
class LayoutPolicy:
    """Frozen, hashable layout descriptor — safe to close over in jit."""

    name: str  # "dense" | "sparse" (auto is resolved away)

    @property
    def sparse(self) -> bool:
        return self.name == "sparse"

    @property
    def index_dtype(self):
        """Dtype for packed integer index vectors (jobs' src, link_index):
        int16 under the sparse layout (compact-storage satellite; every
        padded dimension fits 15 bits — guarded in the builders), int32
        under dense so the parity reference stays byte-identical to r05."""
        return np.int16 if self.sparse else np.int32


DENSE = LayoutPolicy("dense")
SPARSE = LayoutPolicy("sparse")


def resolve_layout(layout=None) -> LayoutPolicy:
    """str | LayoutPolicy | None -> LayoutPolicy.  None means dense."""
    if layout is None:
        return DENSE
    if isinstance(layout, LayoutPolicy):
        return layout
    if layout not in LAYOUT_CHOICES:
        raise ValueError(
            f"layout must be one of {LAYOUT_CHOICES}, got '{layout}'"
        )
    if layout == "auto":
        import jax

        return SPARSE if jax.default_backend() == "tpu" else DENSE
    return SPARSE if layout == "sparse" else DENSE

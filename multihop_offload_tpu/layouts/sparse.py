"""Padded edge-list instance representation and its segment-sum kernels.

The sparse layout stores each instance's graph structure as COO edge lists
padded to a STATIC nnz per shape bucket (`PadSpec.ext_nnz` / `cf_nnz`), so
every program stays fixed-shape across a bucket — the same pad-to-static
discipline as node/link/job counts (arXiv:1906.11786).  Padding entries are
(row=0, col=0, val=0): inert under every segment reduction here.

Three device-side kernel families replace dense (N, N) / (L, L) math:

- `sparse_chebyshev_support` + `make_sparse_propagate`: the ChebConv
  recurrence as gather + segment-sum with fp32 accumulation (composing with
  `PrecisionPolicy` — contributions are upcast before the segment-sum, the
  result narrowed back to the compute dtype);
- `weight_matrix_from_edges` / `next_hop_from_edges`: APSP stays dense
  min-plus (genuinely all-pairs), but its input weight matrix is
  scatter-built from the link list on device, and the greedy next-hop table
  comes from a directed-edge segment-min instead of an (N, N, N) cost
  volume.  Both reproduce the dense path BIT-EXACTLY (same gathered values,
  same lowest-index tie-breaking), which is what makes the dense/sparse
  decision-agreement-1.0 gate in tests/test_layouts.py possible;
- the conflict fixed point and the per-route delay reductions consume the
  conflict edge list / the route step sequence directly (env/queueing.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multihop_offload_tpu.ops.sparse import COO
from multihop_offload_tpu.precision import island_dtype


@struct.dataclass
class SparseInstance:
    """Edge-list twin of the Instance's dense structural matrices.

    Lives as an Optional field ON the Instance (`inst.sparse`): None under
    the dense layout (an empty pytree subtree — stack/vmap/jit all ignore
    it), populated by `build_instance(..., layout=sparse)`.  Dense leaves a
    sparse program never reads are pruned from the compiled executable by
    jit (`keep_unused=False`), so the bytes win needs no signature changes.
    """

    ext: COO  # (E, E) extended-line-graph adjacency (ChebConv support input)
    cf: COO   # (L, L) conflict adjacency (interference fixed point)


@struct.dataclass
class SparseSupport:
    """Chebyshev support in edge-list form: off-diagonal COO + diagonal."""

    edges: COO
    diag: jnp.ndarray  # (E,)

    def astype(self, dtype):
        return SparseSupport(
            edges=COO(
                rows=self.edges.rows, cols=self.edges.cols,
                vals=self.edges.vals.astype(dtype), shape=self.edges.shape,
            ),
            diag=self.diag.astype(dtype),
        )


# ---- host-side builders ----------------------------------------------------


def _coo_from_dense_np(mat: np.ndarray, nnz_pad: int, val_dtype) -> COO:
    """Numpy COO extraction with pad-to-static nnz (host-side sibling of
    `ops.sparse.dense_to_coo` — numpy leaves so `stack_instances` keeps its
    one-transfer-per-leaf fast path)."""
    mat = np.asarray(mat)
    rows, cols = np.nonzero(mat)
    nnz = int(rows.size)
    if nnz > nnz_pad:
        raise ValueError(
            f"matrix has {nnz} nonzeros > nnz pad {nnz_pad}; raise the "
            "PadSpec nnz bound (enn/cnn) for this bucket"
        )
    r = np.zeros((nnz_pad,), np.int32)
    c = np.zeros((nnz_pad,), np.int32)
    v = np.zeros((nnz_pad,), val_dtype)
    r[:nnz] = rows
    c[:nnz] = cols
    v[:nnz] = mat[rows, cols]
    return COO(rows=r, cols=c, vals=v, shape=tuple(mat.shape))


def build_sparse_instance(adj_ext, adj_conflict, ext_nnz: int, cf_nnz: int,
                          dtype=np.float32) -> SparseInstance:
    """Extract the edge lists from the already-built padded dense matrices.

    Host numpy, once per instance build — the padded dense matrices exist in
    both layouts (they stay on the Instance as the parity reference and are
    DCE'd from sparse programs), so extraction is the cheap part."""
    return SparseInstance(
        ext=_coo_from_dense_np(adj_ext, ext_nnz, dtype),
        cf=_coo_from_dense_np(adj_conflict, cf_nnz, dtype),
    )


def ext_nnz_count(topo, comp_mask: np.ndarray) -> int:
    """Exact nonzero count of the extended adjacency a topology will build:
    line-graph entries + both incidence blocks (each endpoint that carries a
    computing role contributes an (link, node) and (node, link) entry).
    Used to size per-bucket nnz pads from real data (train.data) and to
    refuse oversized requests at serve admission."""
    lg = int(np.count_nonzero(np.asarray(topo.adj_lg)))
    comp = np.asarray(comp_mask, bool)
    inc = int(np.count_nonzero(comp[np.asarray(topo.link_ends)]))
    return lg + 2 * inc


def cf_nnz_count(topo) -> int:
    return int(np.count_nonzero(np.asarray(topo.adj_conflict)))


# ---- ChebConv: gather + segment-sum ----------------------------------------


def sparse_chebyshev_support(edges: COO, mask=None, lmax: float = 2.0,
                             dtype=None) -> SparseSupport:
    """Edge-list twin of `models.chebconv.chebyshev_support`.

    Same fp32-island Laplacian math (degrees, symmetric normalization, the
    rescale `(2/lmax) * L - I`) computed over the edge list: off-diagonal
    entries are `-(2/lmax) * a[u,v] / sqrt(deg_u * deg_v)`, the diagonal is
    `(2/lmax - 1)` on valid nodes.  `lmax=None` (power iteration) is a
    dense-only feature — raise rather than silently diverge."""
    if lmax is None:
        raise ValueError(
            "sparse layout requires a static lmax (the dense power-iteration "
            "estimate reads the full matrix); use lmax=2.0"
        )
    wide = island_dtype(edges.vals.dtype)  # fp32-island(laplacian)
    vals = edges.vals.astype(wide)
    n = edges.shape[0]
    deg = jax.ops.segment_sum(vals, edges.rows, num_segments=n)
    valid = deg > 0
    if mask is not None:
        valid = valid & mask
    inv_sqrt = jnp.where(deg > 0, 1.0 / jnp.sqrt(jnp.where(deg > 0, deg, 1.0)), 0.0)
    scale = 2.0 / lmax
    evals = -scale * vals * inv_sqrt[edges.rows] * inv_sqrt[edges.cols]
    diag = (scale - 1.0) * valid.astype(wide)
    out = dtype or edges.vals.dtype
    return SparseSupport(
        edges=COO(rows=edges.rows, cols=edges.cols,
                  vals=evals.astype(out), shape=edges.shape),
        diag=diag.astype(out),
    )


def make_sparse_propagate(accum_dtype=None):
    """Build the ChebConv `propagate` callable for `SparseSupport`.

    `support @ x` as gather + segment-sum: per-edge contributions are upcast
    to the accumulation dtype (>= fp32 — the fp32-accumulation contract of
    the sparse layout, independent of the storage dtype) BEFORE the
    segment-sum, and the result is narrowed back to x's dtype so the
    Chebyshev recurrence keeps the precision policy's compute dtype."""

    def propagate(support: SparseSupport, x: jnp.ndarray) -> jnp.ndarray:
        e = support.edges
        acc = accum_dtype or island_dtype(x.dtype)
        contrib = (e.vals[:, None] * x[e.cols]).astype(acc)
        agg = jax.ops.segment_sum(contrib, e.rows, num_segments=x.shape[0])
        agg = agg + support.diag.astype(acc)[:, None] * x.astype(acc)
        return agg.astype(x.dtype)

    return propagate


def zeros_support(pad, dtype, layout=None) -> object:
    """Shape-correct all-zero support for param init / warmup (`pad` is a
    PadSpec, duck-typed to avoid a graphs<->layouts import cycle)."""
    from multihop_offload_tpu.layouts.policy import resolve_layout

    if not resolve_layout(layout).sparse:
        return jnp.zeros((pad.e, pad.e), dtype)
    nnz = pad.ext_nnz
    return SparseSupport(
        edges=COO(rows=jnp.zeros((nnz,), jnp.int32),
                  cols=jnp.zeros((nnz,), jnp.int32),
                  vals=jnp.zeros((nnz,), dtype), shape=(pad.e, pad.e)),
        diag=jnp.zeros((pad.e,), dtype),
    )


# ---- decision path: weight matrix + next-hop from the link list ------------


def weight_matrix_from_edges(link_ends, link_mask, link_delays,
                             num_nodes: int) -> jnp.ndarray:
    """Scatter per-link delays into the (N, N) one-hop weight matrix.

    The dense twin gathers `link_delays[link_index]` through an (N, N) int32
    map shipped from host; here the same matrix is built on device from the
    (L, 2) link list — identical VALUES bit for bit (same per-edge delay,
    +inf elsewhere, pad links write inf at (0, 0) which `.min` keeps inert),
    so the downstream APSP and every decision are unchanged.  The (N, N)
    output is the APSP input — genuinely all-pairs by design."""
    u, v = link_ends[:, 0], link_ends[:, 1]
    vals = jnp.where(link_mask, link_delays, jnp.inf)
    w = jnp.full((num_nodes, num_nodes), jnp.inf, link_delays.dtype)
    w = w.at[u, v].min(vals)
    w = w.at[v, u].min(vals)
    return w


def next_hop_from_edges(link_ends, link_mask, sp: jnp.ndarray) -> jnp.ndarray:
    """Greedy next-hop table from the directed link list.

    Dense twin (`env.apsp.next_hop_table`) builds an (N, N, N) masked cost
    volume and argmins it.  Here each undirected link contributes both
    directions (derived on device — no extra storage), a segment-min over
    edge sources finds each row's best cost, and a second segment-min over
    the cost-tied candidates reproduces the dense argmin's lowest-index
    tie-breaking exactly.  Rows with no finite option (or no neighbors at
    all) resolve to 0, as `jnp.argmin` does over an all-inf row."""
    n = sp.shape[-1]
    u, v = link_ends[:, 0], link_ends[:, 1]
    src = jnp.concatenate([u, v])
    dst = jnp.concatenate([v, u])
    m = jnp.concatenate([link_mask, link_mask])
    cost = jnp.where(m[:, None], sp[dst], jnp.inf)                 # (2L, N)
    best = jax.ops.segment_min(cost, src, num_segments=n)          # (N, N)
    cand = jnp.where(cost <= best[src], dst[:, None], n)
    nh = jax.ops.segment_min(cand, src, num_segments=n)            # (N, N)
    return jnp.where(jnp.isfinite(best) & (nh < n), nh, 0).astype(jnp.int32)

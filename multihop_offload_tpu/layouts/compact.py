"""Compact integer storage: int16 next-hop tables, narrow counters.

Graph indices are tiny — N <= a few hundred, streams 2J <= a few hundred —
yet the dense layout ships them as int32.  These helpers pick the narrowest
signed dtype a (static) range allows and guard the choice with host-side
asserts: the bounds are Python ints known at build time, so the guards are
free in the compiled program and stripped entirely under `python -O`
("debug mode" overflow guards, per the compact-storage satellite).

int16 is the floor for anything used as a gather/scatter INDEX (XLA
handles narrow index dtypes fine; int8 buys little and risks surprising
promotions), while pure value buffers (the simulator's per-packet stream
ids) may drop to int8 when the range allows.
"""

from __future__ import annotations

import numpy as np

NEXT_HOP_DTYPE = np.int16


def _guard(name: str, max_value: int, dtype) -> None:
    # host-side debug assert on a STATIC bound; `python -O` removes it
    assert int(max_value) <= np.iinfo(dtype).max, (
        f"{name}: max value {max_value} overflows {np.dtype(dtype).name}"
    )


def compact_index_dtype(max_value: int):
    """Narrowest signed integer dtype holding [0, max_value] (>= int16 so
    the result is always a valid XLA gather index dtype)."""
    for dt in (np.int16, np.int32, np.int64):
        if int(max_value) <= np.iinfo(dt).max:
            return dt
    raise ValueError(f"index range {max_value} exceeds int64")


def compact_value_dtype(max_value: int):
    """Narrowest signed integer dtype for pure value storage (int8 floor)."""
    for dt in (np.int8, np.int16, np.int32, np.int64):
        if int(max_value) <= np.iinfo(dt).max:
            return dt
    raise ValueError(f"value range {max_value} exceeds int64")


def pack_next_hop(next_hop):
    """(N, N) int next-hop table -> int16.  Node ids are < N <= 32767
    (guarded on the static shape); unpack with `unpack_next_hop` — the
    round trip is exact, pinned by tests/test_layouts.py."""
    n = next_hop.shape[-1]
    _guard("next_hop", n - 1, NEXT_HOP_DTYPE)
    return next_hop.astype(NEXT_HOP_DTYPE)


def unpack_next_hop(next_hop):
    return next_hop.astype(np.int32)

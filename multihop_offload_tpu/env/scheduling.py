"""Distributed link scheduling: local-greedy maximum-weight independent set.

The reference ships `util.local_greedy_search` (`/root/reference/src/util.py:
12-51`) — the authors' distributed MWIS heuristic for conflict-graph link
scheduling (its analytic stand-in in the queueing model is the conflict-degree
service rate, SURVEY.md §2.7).  Here it is a fixed-shape masked fixed point:
each sweep, every remaining vertex compares its weight against its remaining
neighbors and joins the set when it strictly wins — or ties and has a lower
index than the lowest-indexed tied neighbor; winners' neighbors are
eliminated.  All sweeps are data-parallel (the reference's Python loop over a
set is order-independent within a sweep), so one sweep is one masked matvec —
MXU work, `vmap`-able over batches of conflict graphs.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def local_greedy_mwis(
    adj: jnp.ndarray,
    wts: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy MWIS on a conflict graph.

    adj:  (L, L) 0/1 adjacency; wts: (L,) vertex weights; mask: (L,) bool
    active vertices (padding stays out of the set).  Returns (in_set bool
    (L,), total weight).  Matches the reference's result exactly, including
    its equal-weight tie rule (`util.py:41-46`): on a tie, vertex v joins iff
    v is smaller than its lowest-indexed remaining neighbor of equal weight.
    """
    n = wts.shape[-1]
    remain0 = jnp.ones((n,), bool) if mask is None else mask.astype(bool)
    idx = jnp.arange(n, dtype=jnp.int32)
    adj_b = adj > 0

    def cond(state):
        remain, _ = state
        return remain.any()

    def body(state):
        remain, in_set = state
        nb = adj_b & remain[None, :]  # nb[v, u]: u is a remaining neighbor of v
        has_nb = nb.any(axis=1)
        w_nb = jnp.where(nb, wts[None, :], -jnp.inf)
        nb_max = w_nb.max(axis=1)
        tied = nb & (wts[None, :] == nb_max[:, None])
        first_tied = jnp.argmax(tied, axis=1)  # lowest index achieving the max
        join = (~has_nb) | (wts > nb_max) | ((wts == nb_max) & (idx < first_tied))
        new = remain & join
        eliminated = (adj_b & new[None, :]).any(axis=1)
        return remain & ~new & ~eliminated, in_set | new

    _, in_set = lax.while_loop(cond, body, (remain0, jnp.zeros((n,), bool)))
    return in_set, jnp.sum(jnp.where(in_set, wts, 0.0))

"""All-pairs shortest paths as dense min-plus linear algebra.

The reference's hottest host routine is per-source Dijkstra over NetworkX
(`util.py:101-110`, 2-4 calls per instance per method).  On TPU the graphs are
tiny (N <= ~110) and dense O(N^3) min-plus matrix squaring is both exact and
a perfectly tiled XLA computation: ceil(log2(N-1)) squarings reach every
simple path.  Weights are nonnegative (delays), so min-plus squaring equals
Dijkstra distances.

Also provides the greedy next-hop table: `next_hop[u, d]` = the neighbor of u
minimizing `sp[v, d]`, lowest index on ties — exactly the reference's
distributed forwarding rule (`offloading_v3.py:441-453`, `np.argmin` over the
ascending neighbor list).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax


def _minplus_square(d: jnp.ndarray) -> jnp.ndarray:
    """One squaring step: d[i,j] <- min_k d[i,k] + d[k,j] (and keep d)."""
    return jnp.minimum(d, jnp.min(d[:, :, None] + d[None, :, :], axis=1))


def apsp_minplus(
    weights: jnp.ndarray,
    num_iters: int | None = None,
    early_stop: bool = True,
) -> jnp.ndarray:
    """Shortest-path distance matrix from a one-hop weight matrix.

    `weights`: (N, N), w[u,v] = edge weight (inf where no edge), any diagonal
    (it is forced to 0).  Returns distances with zero diagonal.

    `early_stop` (default): run the squarings in a `lax.while_loop` that
    exits once a squaring leaves the matrix unchanged.  Min-plus squaring is
    idempotent at the fixed point, so the result is IDENTICAL to the full
    ceil(log2(N-1)) schedule; convergence arrives after
    ceil(log2(longest-shortest-path-edge-count)) squarings, which on the
    small-diameter workload graphs is 3-4 of the worst-case 7 — and the
    APSP term dominates the step (benchmarks/profile_r04.md), so the saved
    O(N^3) passes are the single biggest step-time lever.  Under `vmap` the
    loop runs until every lane converges (still <= the static schedule).
    The decision paths consume APSP on stopped values only, so the
    (non-reverse-differentiable) while_loop changes no gradient path.
    """
    n = weights.shape[-1]
    d = jnp.where(jnp.eye(n, dtype=bool), jnp.zeros_like(weights), weights)
    iters = num_iters if num_iters is not None else max(1, math.ceil(math.log2(max(n - 1, 2))))
    if not early_stop:
        return lax.fori_loop(0, iters, lambda _, x: _minplus_square(x), d)

    def cond(state):
        i, _, done = state
        return jnp.logical_and(i < iters, jnp.logical_not(done))

    def body(state):
        i, cur, _ = state
        nxt = _minplus_square(cur)
        return i + 1, nxt, jnp.all(nxt == cur)

    _, d, _ = lax.while_loop(cond, body, (jnp.int32(0), d, jnp.bool_(False)))
    return d


def _minplus_square_blocked(d: jnp.ndarray, block: int) -> jnp.ndarray:
    """`_minplus_square` with the contraction axis processed in k-blocks.

    BIT-IDENTICAL to the dense squaring: the candidate sums d[i,k] + d[k,j]
    are the very same fp ops, and `min` is exact under any reduction order,
    so folding block-minima into the accumulator loses nothing.  What changes
    is the live temp: (N, Kb, N) per lane instead of (N, N, N).  Padding the
    k axis with +inf (when block doesn't divide N) is inert — weights are
    nonnegative, so inf + x = inf never wins a min."""
    n = d.shape[-1]
    nb = -(-n // block)
    kpad = nb * block - n
    dik = jnp.pad(d, ((0, 0), (0, kpad)), constant_values=jnp.inf)
    dkj = jnp.pad(d, ((0, kpad), (0, 0)), constant_values=jnp.inf)
    dik = jnp.moveaxis(dik.reshape(n, nb, block), 1, 0)  # (nb, N, Kb)
    dkj = dkj.reshape(nb, block, n)                      # (nb, Kb, N)

    def body(acc, xs):
        a, b = xs
        return (
            jnp.minimum(acc, jnp.min(a[:, :, None] + b[None, :, :], axis=1)),
            None,
        )

    out, _ = lax.scan(body, d, (dik, dkj))
    return out


def apsp_minplus_blocked(
    weights: jnp.ndarray,
    block: int = 8,
    num_iters: int | None = None,
    early_stop: bool = True,
) -> jnp.ndarray:
    """`apsp_minplus` with k-blocked squarings — same distances bit for bit.

    The dense squaring materializes an (N, N, N) broadcast per batch lane; at
    paper shapes (B=40, N=112) that one f32 buffer is ~225 MB of peak temp and
    dominates the compiled step's byte traffic (BENCH_r05).  Blocking caps the
    live temp at (N, block, N) per lane while computing exactly the same
    min-plus product (see `_minplus_square_blocked`), so routing decisions are
    unchanged by construction.  The sparse instance layout uses this as its
    default APSP core; the dense layout keeps the broadcast squaring as the
    parity reference."""
    n = weights.shape[-1]
    d = jnp.where(jnp.eye(n, dtype=bool), jnp.zeros_like(weights), weights)
    iters = num_iters if num_iters is not None else max(1, math.ceil(math.log2(max(n - 1, 2))))
    if not early_stop:
        return lax.fori_loop(
            0, iters, lambda _, x: _minplus_square_blocked(x, block), d
        )

    def cond(state):
        i, _, done = state
        return jnp.logical_and(i < iters, jnp.logical_not(done))

    def body(state):
        i, cur, _ = state
        nxt = _minplus_square_blocked(cur, block)
        return i + 1, nxt, jnp.all(nxt == cur)

    _, d, _ = lax.while_loop(cond, body, (jnp.int32(0), d, jnp.bool_(False)))
    return d


def hop_matrix(adj: jnp.ndarray) -> jnp.ndarray:
    """Unweighted shortest-path hop counts (reference `sp_hop`,
    `AdHoc_train.py:135`)."""
    w = jnp.where(adj > 0, jnp.ones_like(adj), jnp.full_like(adj, jnp.inf))
    return apsp_minplus(w)


def weight_matrix_from_link_delays(
    adj: jnp.ndarray, link_index: jnp.ndarray, link_delays: jnp.ndarray
) -> jnp.ndarray:
    """Scatter per-link delays into an (N, N) one-hop weight matrix.

    Replaces the reference's per-edge attribute writes + Dijkstra
    (`gnn_offloading_agent.py:281-287`).  Non-edges get +inf.
    """
    gathered = link_delays[link_index]  # (N, N), garbage where no edge
    return jnp.where(adj > 0, gathered, jnp.full_like(gathered, jnp.inf))


def next_hop_table(adj: jnp.ndarray, sp: jnp.ndarray) -> jnp.ndarray:
    """next_hop[u, d]: neighbor v of u minimizing sp[v, d] (ties -> lowest v).

    Greedy shortest-path forwarding (`offloading_v3.py:447-451`): because the
    reference enumerates neighbors with `np.nonzero` (ascending) and takes the
    first argmin, a plain masked argmin over the full vertex set reproduces
    its tie-breaking exactly.
    """
    # cost[u, v, d] = sp[v, d] if (u,v) is an edge else +inf
    cost = jnp.where(
        (adj > 0)[:, :, None],
        jnp.broadcast_to(sp[None, :, :], adj.shape[:1] + sp.shape),
        jnp.inf,
    )
    return jnp.argmin(cost, axis=1).astype(jnp.int32)

"""Congestion-agnostic baseline unit delays.

Reimplements `dmtx_baseline` (`offloading_v3.py:341-361`): per-link unit delay
1/rate, per-node unit processing delay 1/proc_bw (inf for relays, whose
proc_bw is 0 — making them transparent transit nodes that never attract
compute).
"""

from __future__ import annotations

from multihop_offload_tpu.graphs.instance import Instance


def baseline_unit_delays(inst: Instance):
    """Returns (link_delays (L,), node_delays (N,)).

    The drivers replace non-positive node delays with T
    (`AdHoc_train.py:129`); with nonnegative capacities 1/bw is never
    negative, and relays' 1/0 = +inf already excludes them, so the
    replacement is a no-op we do not replicate.
    """
    link = 1.0 / inst.link_rates          # inf on zero-capacity links
    node = 1.0 / inst.proc_bws            # inf on relays / padding
    return link, node

"""Contention-coupled M/M/1 queueing model — the empirical evaluator.

Reimplements `AdhocCloud.run` (`offloading_v3.py:455-550`) as fixed-shape
array math:

1. per-link packet arrival rates accumulated over realized routes (a single
   incidence @ rates matmul instead of the reference's per-flow route walk);
2. a 10-iteration fixed point coupling link service rates through conflict-
   graph busyness (`:500-506`) — one dense (L, L) matmul per iteration;
3. per-(link, job) empirical delays `1/(mu - lambda)` with the congestion
   fallback `T * lambda / ((ul + dl) * mu)` when `mu <= lambda` (`:537-542`),
   and per-job server delays with their fallback (`:545-549`).

Also emits the (N, N) empirical unit-delay matrix + written-entry mask the
training MSE term supervises against (`:508,540-548`), with the reference's
last-write-wins job ordering.

Under `layout=sparse` the (L, J) incidence and (L, L) conflict matmuls are
replaced by gathers/segment reductions over the realized route steps and the
conflict edge list (`layouts.SparseInstance`) — no (L, J)/(L, L)/(N, N)
intermediates beyond the supervised unit matrix itself.  Dense stays the
parity reference; tests/test_layouts.py pins decision agreement at 1.0.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.env.routing import RouteSet
from multihop_offload_tpu.layouts import resolve_layout
from multihop_offload_tpu.precision import island_dtype


@struct.dataclass
class EmpiricalDelays:
    job_total: jnp.ndarray     # (J,) link + server delay per job (0 if padded)
    job_link: jnp.ndarray      # (J,) transport component
    job_server: jnp.ndarray    # (J,) compute component
    congested: jnp.ndarray     # (J,) bool: total > T (real jobs only)
    link_lambda: jnp.ndarray   # (L,) aggregate link arrival rates
    link_mu: jnp.ndarray       # (L,) converged service rates
    server_load: jnp.ndarray   # (N,) aggregate server arrival rates
    unit_matrix: jnp.ndarray   # (N, N) empirical unit delays (0 where unwritten)
    unit_mask: jnp.ndarray     # (N, N) bool: entry written by some flow


def interference_fixed_point_raw(
    adj_conflict: jnp.ndarray,
    link_rates: jnp.ndarray,
    cf_degs: jnp.ndarray,
    link_lambda: jnp.ndarray,
    num_iters: int = 10,
) -> jnp.ndarray:
    """Raw-array fixed-point core (batched-aware); THE single definition of
    the busy/mu update — the Pallas kernel's VJP recompute
    (`ops.fixed_point`) and the tests pull from here so the math can never
    drift between copies."""
    mu0 = link_rates / (cf_degs + 1.0)

    def body(mu, _):
        busy = jnp.clip(link_lambda / mu, 0.0, 1.0)
        neighbor_busy = jnp.einsum("...ij,...j->...i", adj_conflict, busy)
        return link_rates / (1.0 + neighbor_busy), None

    # lax.scan (not fori_loop) so both differentiable critics can reverse-
    # differentiate through the unrolled iterations, as the reference's
    # GradientTape does (`gnn_offloading_agent.py:240-244`, `:348-352`).
    mu, _ = lax.scan(body, mu0, None, length=num_iters)
    return mu


def interference_fixed_point(
    inst: Instance, link_lambda: jnp.ndarray, num_iters: int = 10, fp_fn=None,
    layout=None,
) -> jnp.ndarray:
    """Converged per-link service rates mu under conflict coupling.

    mu_0 = rate / (cf_deg + 1); iterate: busy = clip(lambda/mu, 0, 1),
    mu = rate / (1 + A_conflict @ busy)   (`offloading_v3.py:500-506`).
    Shared by the empirical evaluator and both differentiable critics
    (`gnn_offloading_agent.py:240-244`, `:348-352`).  `fp_fn` overrides the
    XLA scan with a drop-in core (the `fp_impl` knob resolves to the Pallas
    VMEM-resident kernel, `ops.fixed_point.resolve_fixed_point`).

    This is an fp32 ISLAND (`precision.FP32_ISLANDS`: "fixed_point"): the
    M/M/1 denominators `1 - lambda/mu` near saturation lose the gradient
    signal in bf16, so every operand is promoted to >= fp32 before the core
    — the XLA scan and the Pallas kernel alike then iterate wide, and the
    returned mu keeps downstream delay math wide by dtype promotion.  A
    no-op under the identity (fp32/fp64) policy.

    Under the sparse layout (and no `fp_fn` override — the Pallas kernel
    stays dense in VMEM), the (L, L) neighbor-busyness matvec runs as a
    segment-sum over the conflict edge list (`inst.sparse.cf`), never
    materializing the conflict matrix.  Same update, same iteration count,
    same fp32 island; only the reduction association differs.
    """
    dt = island_dtype(link_lambda.dtype, inst.link_rates.dtype)
    lay = resolve_layout(layout)
    if fp_fn is None and lay.sparse and inst.sparse is not None:
        cf = inst.sparse.cf
        rates = inst.link_rates.astype(dt)
        lam = link_lambda.astype(dt)
        cf_vals = cf.vals.astype(dt)
        num_links = rates.shape[0]
        mu0 = rates / (inst.cf_degs.astype(dt) + 1.0)

        def body(mu, _):
            busy = jnp.clip(lam / mu, 0.0, 1.0)
            neighbor_busy = jax.ops.segment_sum(
                cf_vals * busy[cf.cols], cf.rows, num_segments=num_links
            )
            return rates / (1.0 + neighbor_busy), None

        mu, _ = lax.scan(body, mu0, None, length=num_iters)
        return mu
    fp = fp_fn or interference_fixed_point_raw
    return fp(
        inst.adj_conflict.astype(dt), inst.link_rates.astype(dt),
        inst.cf_degs.astype(dt), link_lambda.astype(dt), num_iters
    )


def run_empirical(
    inst: Instance, jobs: JobSet, routes: RouteSet, fp_fn=None, layout=None
) -> EmpiricalDelays:
    num_links = inst.num_pad_links
    n = inst.num_pad_nodes
    lay = resolve_layout(layout)
    sparse = lay.sparse
    # fp32-island(delay_reduction): the arrival accumulation, every
    # 1/(mu - lambda) unit delay, and the per-job totals run >= fp32 —
    # bf16 routes/rates feed in, wide EmpiricalDelays come out.  lambda
    # accuracy feeds the fixed point's denominators directly, so the
    # incidence matmul is re-accumulated wide, not just its result.
    inc_dt = (routes.inc_ext.dtype if routes.inc_ext is not None
              else inst.link_rates.dtype)  # inc may be skipped (train sparse)
    dt = island_dtype(inc_dt, jobs.rate.dtype, inst.link_rates.dtype)
    jmask = jobs.mask
    ul = jobs.ul.astype(dt)
    dl = jobs.dl.astype(dt)
    nhop = routes.nhop.astype(dt)
    ul_rate = ul * jobs.rate.astype(dt)
    dl_rate = dl * jobs.rate.astype(dt)

    if sparse:
        # Route-step form: seq_slot/seq_active hold the realized (hop, job)
        # link ids, so the (L, J) incidence never materializes.  Routes are
        # simple (trace_routes walks a greedy next-hop table, horizon N), so
        # per-step accumulation == per-traversed-link-once, same as `inc`.
        inc = None
        seq = routes.seq_slot                             # (H, J)
        act = routes.seq_active                           # (H, J) bool
        step_rate = jnp.where(act, (ul_rate + dl_rate)[None, :], 0.0)
        link_lambda = (
            jnp.zeros((num_links,), dt).at[seq].add(step_rate)
        )                                                 # (`:494`)
    else:
        inc = routes.inc_ext[:num_links].astype(dt)       # (L, J)
        link_lambda = inc @ (ul_rate + dl_rate)           # (`:494`)
    server_load = jnp.zeros((n,), dtype=ul_rate.dtype).at[routes.dst].add(
        jnp.where(jmask, ul_rate, 0.0)
    )                                                     # (`:496`)

    link_mu = interference_fixed_point(inst, link_lambda, fp_fn=fp_fn,
                                       layout=lay)

    # per-(link, job) unit delay with per-job congestion fallback (`:537-539`)
    slack = link_mu - link_lambda                 # (L,)
    congested_l = slack <= 0.0
    safe_slack = jnp.where(congested_l, 1.0, slack)
    unit_ok = 1.0 / safe_slack

    if sparse:
        # gather the per-link quantities at each realized route step and
        # reduce over hops — (H, J) intermediates, H = horizon, not (L, J)
        lam_h = link_lambda[seq]
        mu_h = link_mu[seq]
        cong_h = congested_l[seq]
        unit_h = jnp.where(
            cong_h,
            inst.T * lam_h / ((ul + dl)[None, :] * mu_h),
            unit_ok[seq],
        )
        d_ul_h = jnp.maximum(ul[None, :] * unit_h, nhop[None, :])
        d_dl_h = jnp.maximum(dl[None, :] * unit_h, nhop[None, :])
        job_link = jnp.sum(jnp.where(act, d_ul_h + d_dl_h, 0.0), axis=0)
    else:
        unit_cong = inst.T * link_lambda[:, None] / (
            (ul + dl)[None, :] * link_mu[:, None]
        )
        unit_lj = jnp.where(congested_l[:, None], unit_cong, unit_ok[:, None])

        # per-link per-job empirical delay, only on traversed links (`:542`)
        d_ul = jnp.maximum(ul[None, :] * unit_lj, nhop[None, :])
        d_dl = jnp.maximum(dl[None, :] * unit_lj, nhop[None, :])
        # untraversed (link, job) pairs may hold inf/NaN (e.g. zero-rate links
        # the reference simply never visits) — mask before summing, don't
        # multiply
        job_link = jnp.sum(jnp.where(inc > 0, d_ul + d_dl, 0.0), axis=0)

    # server component (`:545-549`)
    bw = inst.proc_bws[routes.dst].astype(dt)
    sload = server_load[routes.dst]
    s_slack = bw - sload
    s_cong = s_slack <= 0.0
    unit_s = jnp.where(
        s_cong,
        inst.T * sload / (ul * jnp.where(bw > 0, bw, 1.0)),
        1.0 / jnp.where(s_cong, 1.0, s_slack),
    )
    job_server = jnp.maximum(ul * unit_s, 1.0)

    job_link = jnp.where(jmask, job_link, 0.0)
    job_server = jnp.where(jmask, job_server, 0.0)
    total = job_link + job_server

    # ---- empirical unit-delay matrix, last-write-wins over job order -------
    if sparse:
        # "last write wins" == highest job index among a link's/node's
        # writers: one segment-max of job ids over route steps replaces the
        # dense scan over jobs, and the winner's unit delay is recomputed
        # from the per-link scalars (identical to unit_lj at that column).
        jidx = jnp.arange(jobs.src.shape[0], dtype=jnp.int32)
        jwin = jnp.full((num_links,), -1, jnp.int32).at[seq].max(
            jnp.where(act, jidx[None, :], -1)
        )
        link_written = jwin >= 0
        jw = jnp.maximum(jwin, 0)
        u_link = jnp.where(
            congested_l,
            inst.T * link_lambda / ((ul + dl)[jw] * link_mu),
            unit_ok,
        )
        nwin = jnp.full((n,), -1, jnp.int32).at[routes.dst].max(
            jnp.where(jmask, jidx, -1)
        )
        node_written = nwin >= 0
        u_node = unit_s[jnp.maximum(nwin, 0)]
    else:
        def write(carry, j):
            u_link, u_node = carry
            on_route = inc[:, j] > 0
            u_link = jnp.where(on_route, unit_lj[:, j], u_link)
            u_node = jnp.where(
                jmask[j],
                u_node.at[routes.dst[j]].set(unit_s[j]),
                u_node,
            )
            return (u_link, u_node), None

        (u_link, u_node), _ = lax.scan(
            write,
            (jnp.zeros((num_links,), total.dtype),
             jnp.zeros((n,), total.dtype)),
            jnp.arange(jobs.src.shape[0], dtype=jnp.int32),
        )
        link_written = (inc @ jnp.where(jmask, 1.0, 0.0)) > 0
        node_written = jnp.zeros((n,), bool).at[routes.dst].max(jmask)

    u, v = inst.link_ends[:, 0], inst.link_ends[:, 1]
    unit_matrix = jnp.zeros((n, n), total.dtype)  # dense-ok(train target: the (N, N) unit-delay matrix IS the supervised output)
    unit_matrix = unit_matrix.at[u, v].set(jnp.where(link_written, u_link, 0.0))
    unit_matrix = unit_matrix.at[v, u].max(jnp.where(link_written, u_link, 0.0))
    iota = jnp.arange(n, dtype=jnp.int32)
    unit_matrix = unit_matrix.at[iota, iota].set(
        jnp.where(node_written, u_node, 0.0)
    )
    unit_mask = jnp.zeros((n, n), bool)  # dense-ok(train target mask, same shape as the supervised unit matrix)
    unit_mask = unit_mask.at[u, v].max(link_written)
    unit_mask = unit_mask.at[v, u].max(link_written)
    unit_mask = unit_mask.at[iota, iota].max(node_written)

    return EmpiricalDelays(
        job_total=total,
        job_link=job_link,
        job_server=job_server,
        congested=(total > inst.T) & jmask,
        link_lambda=link_lambda,
        link_mu=link_mu,
        server_load=server_load,
        unit_matrix=unit_matrix,
        unit_mask=unit_mask,
    )

"""Route tracing as a fixed-length scan over the next-hop table.

The reference walks each flow's route with a Python while-loop and O(L)
`list.index` calls (`offloading_v3.py:441-453`, `:485-496`); here every job
descends the next-hop table in lock-step inside one `lax.scan` of at most
N-1 steps, emitting the visited extended-line-graph slot per step.  From that
step sequence we build, with one scatter-add, the route incidence matrices
the critic needs (`gnn_offloading_agent.py:310-331`).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct
from jax import lax

from multihop_offload_tpu.graphs.instance import Instance, JobSet


@struct.dataclass
class RouteSet:
    """Realized routes for all jobs of one instance (the Flow records,
    `offloading_v3.py:140-150`, in array form)."""

    dst: jnp.ndarray         # (J,) int32 compute destination (== src if local)
    nhop: jnp.ndarray        # (J,) float hop count of the uplink route
    seq_slot: jnp.ndarray    # (H, J) int32 ext slot visited at each step
    seq_active: jnp.ndarray  # (H, J) bool step is a real traversal
    inc_ext: jnp.ndarray     # (E, J) 0/1 incidence incl. final pseudo-link
    #                          (the critic's `routes` matrix); slots [0, L)
    #                          are real links — slice with `link_incidence`.
    #                          None when traced with `with_inc=False` (the
    #                          sparse-layout train path works entirely from
    #                          the step sequence).


def trace_routes(
    inst: Instance,
    next_hop: jnp.ndarray,
    jobs: JobSet,
    dst: jnp.ndarray,
    with_inc: bool = True,
) -> RouteSet:
    """Walk every job's greedy route src -> dst simultaneously.

    `next_hop`: (N, N) table from `env.apsp.next_hop_table`.  Local jobs
    (dst == src) traverse no links.  Padded jobs contribute nothing (their
    incidence column is zeroed by the job mask).

    `with_inc=False` skips the (E, J) incidence scatter and sets
    `inc_ext=None` — the sparse-layout train path consumes routes purely
    as the (H, J) step sequence.
    """
    n = inst.num_pad_nodes
    num_links = inst.num_pad_links
    num_jobs = jobs.src.shape[0]
    horizon = n  # a simple route visits < N nodes

    def step(carry, _):
        node, hops = carry
        active = node != dst
        nxt = next_hop[node, dst]
        link = inst.link_index[node, nxt]          # valid only while active
        node2 = jnp.where(active, nxt, node)
        hops2 = hops + active.astype(hops.dtype)
        return (node2, hops2), (link, active)

    (final_node, nhop), (seq_link, seq_active) = lax.scan(
        step,
        # src may be stored compact (int16 under the sparse layout); the
        # carry must match the int32 next-hop gather the body emits
        (jobs.src.astype(jnp.int32),
         jnp.zeros((num_jobs,), dtype=inst.link_rates.dtype)),
        None,
        length=horizon,
    )
    # mask out padded jobs entirely
    seq_active = seq_active & jobs.mask[None, :]
    seq_slot = jnp.where(seq_active, seq_link, 0).astype(jnp.int32)

    # incidence over extended slots: real links from the step sequence,
    # then the compute pseudo-link at the destination for every real job
    # (reference `routes_np`, gnn_offloading_agent.py:310-331).
    inc = None
    if with_inc:
        cols = jnp.broadcast_to(
            jnp.arange(num_jobs, dtype=jnp.int32)[None, :], seq_slot.shape
        )
        inc = jnp.zeros(
            (num_links + n, num_jobs), dtype=inst.link_rates.dtype
        ).at[seq_slot.reshape(-1), cols.reshape(-1)].add(
            seq_active.reshape(-1).astype(inst.link_rates.dtype)
        )
        pseudo = num_links + dst
        inc = inc.at[pseudo, jnp.arange(num_jobs, dtype=jnp.int32)].add(
            jobs.mask.astype(inc.dtype)
        )

    return RouteSet(
        dst=dst,
        nhop=jnp.where(jobs.mask, nhop, 0.0),
        seq_slot=seq_slot,
        seq_active=seq_active,
        inc_ext=inc,
    )


def link_incidence(routes: RouteSet, num_links: int) -> jnp.ndarray:
    """(L, J) real-link incidence slice of the extended incidence."""
    return routes.inc_ext[:num_links]

"""End-to-end policy evaluations: decision -> routing -> empirical delays.

These compose the env kernels into the three non-learned methods the drivers
benchmark on every instance (`AdHoc_train.py:124-157`): `baseline`
(congestion-agnostic greedy offloading), `local` (compute at the source), and
the generic "evaluate a unit-delay matrix" path that the GNN agent also uses.
Each is a pure function of (Instance, JobSet, key) — jit/vmap-ready.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.env.apsp import (
    apsp_minplus,
    next_hop_table,
    weight_matrix_from_link_delays,
)
from multihop_offload_tpu.env.baseline import baseline_unit_delays
from multihop_offload_tpu.env.offloading import OffloadDecision, offload_decide
from multihop_offload_tpu.env.queueing import EmpiricalDelays, run_empirical
from multihop_offload_tpu.env.routing import RouteSet, trace_routes


@struct.dataclass
class PolicyOutcome:
    decision: OffloadDecision
    routes: RouteSet
    delays: EmpiricalDelays

    @property
    def job_total(self):
        return self.delays.job_total


def evaluate_spmatrix_policy(
    inst: Instance,
    jobs: JobSet,
    link_delays: jnp.ndarray,
    unit_diag: jnp.ndarray,
    key: jax.Array,
    explore=0.0,
    prob: bool = False,
    apsp_fn=None,
    fp_fn=None,
) -> PolicyOutcome:
    """Offload + route + run given per-link unit delays and a node diagonal.

    This is the shared skeleton of the baseline method
    (`AdHoc_train.py:128-141`) and the GNN policy (`forward_env`,
    `gnn_offloading_agent.py:278-291`): build the one-hop weight matrix, run
    min-plus APSP + hop counts, take the greedy decision, trace routes, and
    score empirically.  `apsp_fn` overrides the APSP kernel (e.g. the
    mesh-sharded ring variant from `parallel.ring` for large graphs).
    """
    apsp = apsp_fn or apsp_minplus
    w = weight_matrix_from_link_delays(inst.adj, inst.link_index, link_delays)
    sp = apsp(w)
    # hop counts are topology-only and precomputed at Instance build time
    dec = offload_decide(inst, jobs, sp, inst.hop, unit_diag, key, explore, prob)
    nh = next_hop_table(inst.adj, sp)
    routes = trace_routes(inst, nh, jobs, dec.dst)
    delays = run_empirical(inst, jobs, routes, fp_fn=fp_fn)
    return PolicyOutcome(decision=dec, routes=routes, delays=delays)


def baseline_policy(
    inst: Instance, jobs: JobSet, key: jax.Array, explore=0.0, prob: bool = False,
    apsp_fn=None, fp_fn=None,
) -> PolicyOutcome:
    """Congestion-agnostic greedy offloading (`AdHoc_train.py:128-141`)."""
    link_d, node_d = baseline_unit_delays(inst)
    return evaluate_spmatrix_policy(
        inst, jobs, link_d, node_d, key, explore, prob, apsp_fn=apsp_fn,
        fp_fn=fp_fn,
    )


def local_policy(inst: Instance, jobs: JobSet, fp_fn=None) -> PolicyOutcome:
    """Everything computes at its source (`local_compute`,
    `offloading_v3.py:363-386`)."""
    _, node_d = baseline_unit_delays(inst)
    num_jobs = jobs.src.shape[0]
    dec = OffloadDecision(
        dst=jobs.src,
        is_local=jnp.ones((num_jobs,), bool),
        delay_est=jnp.maximum(node_d[jobs.src] * jobs.ul, 1.0),
        costs=jnp.zeros((num_jobs, inst.servers.shape[0] + 1), node_d.dtype),
    )
    # no links traversed: an identity "route" of zero hops
    horizon = inst.num_pad_nodes
    routes = RouteSet(
        dst=jobs.src,
        nhop=jnp.zeros((num_jobs,), node_d.dtype),
        seq_slot=jnp.zeros((horizon, num_jobs), jnp.int32),
        seq_active=jnp.zeros((horizon, num_jobs), bool),
        inc_ext=jnp.zeros(
            (inst.num_pad_links + inst.num_pad_nodes, num_jobs), node_d.dtype
        ).at[inst.num_pad_links + jobs.src, jnp.arange(num_jobs)].add(
            jobs.mask.astype(node_d.dtype)
        ),
    )
    delays = run_empirical(inst, jobs, routes, fp_fn=fp_fn)
    return PolicyOutcome(decision=dec, routes=routes, delays=delays)

"""End-to-end policy evaluations: decision -> routing -> empirical delays.

These compose the env kernels into the three non-learned methods the drivers
benchmark on every instance (`AdHoc_train.py:124-157`): `baseline`
(congestion-agnostic greedy offloading), `local` (compute at the source), and
the generic "evaluate a unit-delay matrix" path that the GNN agent also uses.
Each is a pure function of (Instance, JobSet, key) — jit/vmap-ready.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.env.apsp import (
    apsp_minplus,
    apsp_minplus_blocked,
    next_hop_table,
    weight_matrix_from_link_delays,
)
from multihop_offload_tpu.env.baseline import baseline_unit_delays
from multihop_offload_tpu.env.offloading import OffloadDecision, offload_decide
from multihop_offload_tpu.env.queueing import EmpiricalDelays, run_empirical
from multihop_offload_tpu.env.routing import RouteSet, trace_routes
from multihop_offload_tpu.layouts import (
    next_hop_from_edges,
    resolve_layout,
    weight_matrix_from_edges,
)


@struct.dataclass
class PolicyOutcome:
    decision: OffloadDecision
    routes: RouteSet
    delays: EmpiricalDelays

    @property
    def job_total(self):
        return self.delays.job_total


def evaluate_spmatrix_policy(
    inst: Instance,
    jobs: JobSet,
    link_delays: jnp.ndarray,
    unit_diag: jnp.ndarray,
    key: jax.Array,
    explore=0.0,
    prob: bool = False,
    apsp_fn=None,
    fp_fn=None,
    layout=None,
    apsp_edges_fn=None,
    objective=None,
) -> PolicyOutcome:
    """Offload + route + run given per-link unit delays and a node diagonal.

    This is the shared skeleton of the baseline method
    (`AdHoc_train.py:128-141`) and the GNN policy (`forward_env`,
    `gnn_offloading_agent.py:278-291`): build the one-hop weight matrix, run
    min-plus APSP + hop counts, take the greedy decision, trace routes, and
    score empirically.  `apsp_fn` overrides the APSP kernel (e.g. the
    mesh-sharded ring variant from `parallel.ring` for large graphs);
    `apsp_edges_fn` (sparse layout only) replaces the whole scatter+APSP
    chain with a COO-fed kernel (`ops.minplus.resolve_coo_apsp`).

    Under `layout=sparse` the weight matrix is scatter-built from the link
    list, the next-hop table comes from a directed-edge segment-min, and the
    min-plus APSP runs k-blocked (`apsp_minplus_blocked`) — all three
    BIT-IDENTICAL to their dense twins, so the decisions here never depend
    on the layout knob.  The all-pairs OUTPUT is inherently (N, N); what the
    sparse layout removes is the (N, N, N) squaring temp.
    """
    lay = resolve_layout(layout)
    apsp = apsp_fn or (apsp_minplus_blocked if lay.sparse else apsp_minplus)
    if lay.sparse and apsp_edges_fn is not None:
        # COO-fed regime (`ops.minplus.resolve_coo_apsp`): skip the dense
        # scatter entirely — bit-identical to the chain below
        sp = apsp_edges_fn(
            inst.link_ends, inst.link_mask, link_delays, inst.num_pad_nodes
        )
    else:
        if lay.sparse:
            w = weight_matrix_from_edges(
                inst.link_ends, inst.link_mask, link_delays,
                inst.num_pad_nodes
            )
        else:
            w = weight_matrix_from_link_delays(
                inst.adj, inst.link_index, link_delays
            )
        sp = apsp(w)
    # hop counts are topology-only and precomputed at Instance build time
    dec = offload_decide(inst, jobs, sp, inst.hop, unit_diag, key, explore,
                         prob, objective=objective)
    if lay.sparse:
        nh = next_hop_from_edges(inst.link_ends, inst.link_mask, sp)
    else:
        nh = next_hop_table(inst.adj, sp)
    routes = trace_routes(inst, nh, jobs, dec.dst)
    delays = run_empirical(inst, jobs, routes, fp_fn=fp_fn, layout=lay)
    return PolicyOutcome(decision=dec, routes=routes, delays=delays)


def baseline_policy(
    inst: Instance, jobs: JobSet, key: jax.Array, explore=0.0, prob: bool = False,
    apsp_fn=None, fp_fn=None, layout=None, objective=None,
) -> PolicyOutcome:
    """Congestion-agnostic greedy offloading (`AdHoc_train.py:128-141`)."""
    link_d, node_d = baseline_unit_delays(inst)
    return evaluate_spmatrix_policy(
        inst, jobs, link_d, node_d, key, explore, prob, apsp_fn=apsp_fn,
        fp_fn=fp_fn, layout=layout, objective=objective,
    )


def local_policy(
    inst: Instance, jobs: JobSet, fp_fn=None, layout=None
) -> PolicyOutcome:
    """Everything computes at its source (`local_compute`,
    `offloading_v3.py:363-386`)."""
    _, node_d = baseline_unit_delays(inst)
    num_jobs = jobs.src.shape[0]
    # src may be stored compact (int16 under the sparse layout) — decisions
    # and routes carry int32 node ids everywhere else
    src32 = jobs.src.astype(jnp.int32)
    dec = OffloadDecision(
        dst=src32,
        is_local=jnp.ones((num_jobs,), bool),
        delay_est=jnp.maximum(node_d[jobs.src] * jobs.ul, 1.0),
        costs=jnp.zeros((num_jobs, inst.servers.shape[0] + 1), node_d.dtype),
    )
    # no links traversed: an identity "route" of zero hops
    horizon = inst.num_pad_nodes
    routes = RouteSet(
        dst=src32,
        nhop=jnp.zeros((num_jobs,), node_d.dtype),
        seq_slot=jnp.zeros((horizon, num_jobs), jnp.int32),
        seq_active=jnp.zeros((horizon, num_jobs), bool),
        inc_ext=jnp.zeros(
            (inst.num_pad_links + inst.num_pad_nodes, num_jobs), node_d.dtype
        ).at[inst.num_pad_links + src32,
             jnp.arange(num_jobs, dtype=jnp.int32)].add(
            jobs.mask.astype(node_d.dtype)
        ),
    )
    delays = run_empirical(inst, jobs, routes, fp_fn=fp_fn, layout=layout)
    return PolicyOutcome(decision=dec, routes=routes, delays=delays)

from multihop_offload_tpu.env.apsp import (  # noqa: F401
    apsp_minplus,
    hop_matrix,
    next_hop_table,
    weight_matrix_from_link_delays,
)
from multihop_offload_tpu.env.routing import trace_routes, RouteSet  # noqa: F401
from multihop_offload_tpu.env.offloading import offload_decide, OffloadDecision  # noqa: F401
from multihop_offload_tpu.env.queueing import (  # noqa: F401
    interference_fixed_point,
    run_empirical,
    EmpiricalDelays,
)
from multihop_offload_tpu.env.baseline import baseline_unit_delays  # noqa: F401
from multihop_offload_tpu.env.policies import (  # noqa: F401
    baseline_policy,
    local_policy,
    evaluate_spmatrix_policy,
    PolicyOutcome,
)
from multihop_offload_tpu.env.scheduling import local_greedy_mwis  # noqa: F401

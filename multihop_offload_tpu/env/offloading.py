"""The distributed greedy offloading decision as masked vector math.

Reimplements `AdhocCloud.offloading` (`offloading_v3.py:388-439`): each job
compares computing locally against every server (uplink SP delay x data +
downlink SP delay x data + server processing delay, each lower-bounded by hop
count / 1) and picks the argmin, with epsilon-greedy uniform exploration or
softmax sampling.  The per-job Python loop becomes one (J, S+1) cost matrix;
`jnp.argmin` reproduces NumPy's first-minimum tie-breaking because the padded
server list is ascending.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from multihop_offload_tpu.graphs.instance import Instance, JobSet
from multihop_offload_tpu.precision import island_dtype


@dataclasses.dataclass(frozen=True)
class ObjectiveWeights:
    """Energy/cost weights folded into the offloading cost table.

    Plain Python floats resolved at policy BUILD time and closed over
    (compile-once discipline: changing a weight is a new program, wrapped
    in `jaxhooks.expected_rebuild()` by the scenario runner).  The weights
    bias only the DECISION — delay scoring downstream stays physical:

      transport_energy  cost per hop per unit of data shipped (radio energy
                        proxy): charged to the server options as
                        ``w * (hop_ul * ul + hop_dl * dl)``
      compute_energy    remote-compute premium per unit of uplink data
                        (cloud $/J proxy): charged flat to every server

    Local compute is the zero-cost reference point, so rising weights pull
    decisions toward local / nearer servers.  The default (all-zero) is
    bit-identical to the unweighted objective.
    """

    transport_energy: float = 0.0
    compute_energy: float = 0.0

    @property
    def is_null(self) -> bool:
        return self.transport_energy == 0.0 and self.compute_energy == 0.0


@struct.dataclass
class OffloadDecision:
    dst: jnp.ndarray        # (J,) int32 chosen compute node (src when local)
    is_local: jnp.ndarray   # (J,) bool
    delay_est: jnp.ndarray  # (J,) float predicted delay of the chosen option
    costs: jnp.ndarray      # (J, S+1) full cost table (inf on padded servers)


def offload_decide(
    inst: Instance,
    jobs: JobSet,
    sp: jnp.ndarray,
    hop: jnp.ndarray,
    unit_diag: jnp.ndarray,
    key: jax.Array,
    explore: float | jnp.ndarray = 0.0,
    prob: bool = False,
    objective: ObjectiveWeights | None = None,
) -> OffloadDecision:
    """Choose a compute destination per job.

    `sp`/`hop`: (N, N) shortest-path delay / hop matrices with zero diagonal
    (the reference zeroes the diagonal before use, `offloading_v3.py:396-397`).
    `unit_diag`: (N,) per-node unit processing delays — the diagonal the
    caller would have written into the SP matrix (`:395`).

    fp32 ISLAND (`precision.FP32_ISLANDS`: "decision_costs"): under the
    bf16 policy the SP matrix arrives narrow; its (J, S) gathers — not the
    (N, N) matrix — are upcast and the cost table is re-accumulated >= fp32
    before the argmin, so near-ties degrade by gather rounding only, never
    by quantizing whole cost rows.  A no-op under the identity policy.
    """
    servers = inst.servers                       # (S,) ascending
    smask = inst.server_mask
    src = jobs.src

    dt = island_dtype(sp.dtype, unit_diag.dtype, jobs.ul.dtype)
    ul_d = jobs.ul.astype(dt)
    dl_d = jobs.dl.astype(dt)
    local_delay = unit_diag[src].astype(dt) * ul_d               # (J,)
    ul = sp[src[:, None], servers[None, :]].astype(dt) * ul_d[:, None]  # (J, S)
    dl = sp[servers[None, :], src[:, None]].astype(dt) * dl_d[:, None]
    proc = unit_diag[servers].astype(dt)[None, :] * ul_d[:, None]
    # lower bounds: hop counts for transport, 1 for processing (:411-413)
    ul = jnp.maximum(ul, hop[src[:, None], servers[None, :]].astype(dt))
    dl = jnp.maximum(dl, hop[servers[None, :], src[:, None]].astype(dt))
    proc = jnp.maximum(proc, 1.0)
    server_delays = ul + dl + proc                               # (J, S)
    if objective is not None and not objective.is_null:
        # energy/cost-weighted objective: penalize the server options by the
        # shipped-data x hop-distance transport cost and a flat remote-
        # compute premium; local (the reference point) stays unpenalized
        hop_ul = hop[src[:, None], servers[None, :]].astype(dt)  # (J, S)
        hop_dl = hop[servers[None, :], src[:, None]].astype(dt)
        server_delays = server_delays + (
            objective.transport_energy
            * (hop_ul * ul_d[:, None] + hop_dl * dl_d[:, None])
            + objective.compute_energy * ul_d[:, None]
        )

    inf = jnp.array(jnp.inf, dtype=server_delays.dtype)
    server_delays = jnp.where(smask[None, :], server_delays, inf)
    costs = jnp.concatenate([server_delays, local_delay[:, None]], axis=1)

    num_jobs = src.shape[0]
    k_expl, k_pick, k_prob = jax.random.split(key, 3)
    valid = jnp.concatenate(
        [smask, jnp.ones((1,), dtype=bool)]
    )[None, :].repeat(num_jobs, axis=0)                          # (J, S+1)

    greedy = jnp.argmin(costs, axis=1)
    if prob:
        # softmax over raw costs (reference `util.softmax` over costs, :420-422
        # — note: *higher* cost => higher probability, kept verbatim)
        logits = jnp.where(valid, costs, -inf)
        chosen = jax.random.categorical(k_prob, logits, axis=1)
        base = chosen
    else:
        base = greedy
    # epsilon-greedy: uniform over the valid options incl. local (:416-417)
    uniform = jax.random.categorical(
        k_pick, jnp.where(valid, 0.0, -inf), axis=1
    )
    do_explore = jax.random.uniform(k_expl, (num_jobs,)) < explore
    jidx = jnp.where(do_explore, uniform, base).astype(jnp.int32)

    num_slots = servers.shape[0]
    is_local = jidx >= num_slots
    dst = jnp.where(is_local, src, servers[jnp.clip(jidx, 0, num_slots - 1)])
    delay_est = jnp.take_along_axis(costs, jidx[:, None], axis=1)[:, 0]
    return OffloadDecision(
        dst=dst.astype(jnp.int32), is_local=is_local,
        delay_est=delay_est, costs=costs,
    )

"""Named fault sites + seeded corruption helpers (stdlib-only).

Production code marks its interruptible moments with `crashpoint("site")`
and its fallible I/O with `io_gate("site")`.  Both are no-ops (one dict
lookup) unless a drill has armed a `FaultPlan`, so the hooks are safe to
leave in hot paths.  A drill arms a plan, runs the workload, and the hooks
raise at exactly the named site:

- `crashpoint` raises `SimulatedCrash` — a `BaseException` subclass so no
  `except Exception` recovery path in the workload can swallow it; the
  drill catches it at the top and "restarts the process" by re-running the
  entry point against the same on-disk state (a SIGKILL equivalent).
- `io_gate` raises `TransientIOError` (an `OSError`) for the first
  `plan.fail_count` hits at the site — the retry/backoff machinery must
  absorb it.

Corruption helpers (`truncate_file`, `bit_flip_file`, `torn_tail`) mutate
files the way real crashes and bit-rot do, seeded for determinism.
"""

from __future__ import annotations

import os
import random
from typing import Dict, Optional


class SimulatedCrash(BaseException):
    """Process death at a named site.  BaseException on purpose: recovery
    code under test must never be able to catch and absorb it."""

    def __init__(self, site: str):
        super().__init__(f"simulated crash at {site}")
        self.site = site


class TransientIOError(OSError):
    """An injected transient I/O failure (storage hiccup, flaky mount)."""


class FaultPlan:
    """One drill's armed faults.

    crash_at: site name -> SimulatedCrash on the Nth hit (1-based, default
    first).  io_fail: site name -> number of consecutive TransientIOErrors
    to inject before letting the call through."""

    def __init__(self, crash_at: Optional[Dict[str, int]] = None,
                 io_fail: Optional[Dict[str, int]] = None):
        self.crash_at = dict(crash_at or {})
        self.io_fail = dict(io_fail or {})
        self.hits: Dict[str, int] = {}       # crashpoint visit counts
        self.io_hits: Dict[str, int] = {}    # io_gate injected-failure counts
        self.fired: Dict[str, int] = {}      # site -> hit index that crashed


_plan: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> None:
    global _plan
    _plan = plan


def clear() -> None:
    install(None)


def active_plan() -> Optional[FaultPlan]:
    return _plan


def crashpoint(site: str) -> None:
    """Mark an interruptible moment.  No-op unless a plan arms `site`."""
    p = _plan
    if p is None:
        return
    n = p.hits.get(site, 0) + 1
    p.hits[site] = n
    want = p.crash_at.get(site)
    if want is not None and n >= want:
        del p.crash_at[site]           # fire once, then the restart survives
        p.fired[site] = n
        raise SimulatedCrash(site)


def io_gate(site: str) -> None:
    """Mark fallible I/O.  Raises TransientIOError for the first
    `plan.io_fail[site]` hits, then lets calls through."""
    p = _plan
    if p is None:
        return
    left = p.io_fail.get(site, 0)
    if left > 0:
        p.io_fail[site] = left - 1
        p.io_hits[site] = p.io_hits.get(site, 0) + 1
        raise TransientIOError(f"injected transient I/O failure at {site}")


# ---- seeded corruption helpers ---------------------------------------------


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate `path` to `keep_fraction` of its size (a partial write).
    Returns the new size."""
    size = os.path.getsize(path)
    new = max(int(size * keep_fraction), 0)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def bit_flip_file(path: str, seed: int, flips: int = 8) -> list:
    """Flip `flips` seeded-random bits in `path` (bit-rot).  Returns the
    byte offsets touched."""
    rng = random.Random(seed)  # nondet-ok(seeded stdlib RNG: deterministic corruption pattern)
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        if not data:
            return []
        offsets = []
        for _ in range(flips):
            i = rng.randrange(len(data))
            data[i] ^= 1 << rng.randrange(8)
            offsets.append(i)
        f.seek(0)
        f.write(data)
        f.truncate(len(data))
    return offsets


def torn_tail(path: str, garbage: bytes = b'{"event": "tick", "ts\xff\xfe') -> None:
    """Append a torn final record — a partial JSON line with invalid UTF-8,
    exactly what a crash mid-`write()` leaves behind (no trailing
    newline)."""
    with open(path, "ab") as f:
        f.write(garbage)


# ---- semantic fault families -----------------------------------------------
# The corruption helpers above break BYTES; these break MEANING.  A
# weight-poisoned checkpoint is saved through the normal path and therefore
# carries a perfectly valid integrity checksum — it is exactly the fault
# class `train.checkpoints.restore_verified` cannot see and the semantic
# canary (`loop.canary`) exists to catch.  The request mutations produce
# OffloadRequests that are shape-compatible with the buckets but
# semantically wrong — the admission guards' (`serve.guards`) fault diet.

POISON_MODES = ("nan", "inf", "scale")


def poison_checkpoint(directory: str, mode: str = "nan", seed: int = 0,
                      fraction: float = 0.25) -> int:
    """Save a weight-poisoned — but checksum-VALID — checkpoint at
    `latest+1` of an orbax tree.

    Restores the latest verified step, poisons `fraction` of each float
    leaf's entries (seeded): NaN / Inf injection, or a 1e6 scale blowup
    (finite, so finiteness checks alone miss it — only the canary's
    decision-agreement probe can).  The poisoned tree goes through the
    NORMAL `save_checkpoint` path, so it gets a fresh, valid integrity
    checksum and `source="poison"` lineage; orbax keeps the first save per
    step id, hence the new step.  Returns the poisoned step id."""
    import numpy as np

    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    if mode not in POISON_MODES:
        raise ValueError(f"unknown poison mode '{mode}'; one of {POISON_MODES}")
    restored, step = ckpt_lib.restore_verified(directory)
    if restored is None:
        raise ValueError(f"no verified checkpoint to poison in {directory}")
    rng = np.random.default_rng(seed)

    def poison(x):
        a = np.array(x, copy=True)
        if not np.issubdtype(a.dtype, np.floating):
            return a
        flat = a.reshape(-1)
        k = max(int(flat.size * fraction), 1)
        idx = rng.choice(flat.size, size=min(k, flat.size), replace=False)
        if mode == "nan":
            flat[idx] = np.nan
        elif mode == "inf":
            flat[idx] = np.inf
        else:
            flat[idx] = flat[idx] * 1e6
        return a

    import jax

    poisoned = jax.tree_util.tree_map(poison, restored)
    new_step = step + 1
    ckpt_lib.save_checkpoint(
        directory, new_step, poisoned,
        lineage=ckpt_lib.make_lineage(
            "poison", parent_step=step, parent_dir=directory,
            extra={"poison": mode, "fraction": fraction, "seed": seed},
        ),
    )
    return new_step


# request mutations: name -> expected admission-guard rejection reason
REQUEST_MUTATIONS = (
    ("nan_rate", "nonfinite"),
    ("negative_rate", "nonpositive_rate"),
    ("oob_src", "bad_node_id"),
    ("relay_src", "bad_role"),
    ("len_mismatch", "bad_shape"),
    ("nonfinite_bw", "nonfinite"),
    ("saturated", "saturated"),
)


def fuzz_request(req, mutation: str, seed: int = 0):
    """Return a semantically-broken copy of a VALID OffloadRequest.

    Each mutation is minimal — one field family perturbed — so the
    admission guards' typed `reason` is predictable (the second element of
    the matching `REQUEST_MUTATIONS` row); everything else stays
    bit-identical to the input."""
    import dataclasses as _dc

    import numpy as np

    rng = np.random.default_rng(seed)
    job_rate = np.array(req.job_rate, dtype=np.float64, copy=True)
    if mutation == "nan_rate":
        job_rate[rng.integers(job_rate.size)] = np.nan
        return _dc.replace(req, job_rate=job_rate)
    if mutation == "negative_rate":
        job_rate[rng.integers(job_rate.size)] = -0.25
        return _dc.replace(req, job_rate=job_rate)
    if mutation == "oob_src":
        job_src = np.array(req.job_src, copy=True)
        job_src[rng.integers(job_src.size)] = req.topo.n + 7
        return _dc.replace(req, job_src=job_src)
    if mutation == "relay_src":
        # point one job at a non-mobile node: valid id, wrong role
        non_mobile = np.flatnonzero(np.asarray(req.roles) != 0)
        job_src = np.array(req.job_src, copy=True)
        job_src[rng.integers(job_src.size)] = int(non_mobile[-1])
        return _dc.replace(req, job_src=job_src)
    if mutation == "len_mismatch":
        return _dc.replace(req, job_rate=job_rate[:-1])
    if mutation == "nonfinite_bw":
        proc = np.array(req.proc_bws, dtype=np.float64, copy=True)
        proc[rng.integers(proc.size)] = np.inf
        return _dc.replace(req, proc_bws=proc)
    if mutation == "saturated":
        return _dc.replace(req, job_rate=job_rate * 1e9)
    raise ValueError(f"unknown request mutation '{mutation}'; one of "
                     f"{[m for m, _ in REQUEST_MUTATIONS]}")

"""Named fault sites + seeded corruption helpers (stdlib-only).

Production code marks its interruptible moments with `crashpoint("site")`
and its fallible I/O with `io_gate("site")`.  Both are no-ops (one dict
lookup) unless a drill has armed a `FaultPlan`, so the hooks are safe to
leave in hot paths.  A drill arms a plan, runs the workload, and the hooks
raise at exactly the named site:

- `crashpoint` raises `SimulatedCrash` — a `BaseException` subclass so no
  `except Exception` recovery path in the workload can swallow it; the
  drill catches it at the top and "restarts the process" by re-running the
  entry point against the same on-disk state (a SIGKILL equivalent).
- `io_gate` raises `TransientIOError` (an `OSError`) for the first
  `plan.fail_count` hits at the site — the retry/backoff machinery must
  absorb it.

Corruption helpers (`truncate_file`, `bit_flip_file`, `torn_tail`) mutate
files the way real crashes and bit-rot do, seeded for determinism.
"""

from __future__ import annotations

import os
import random
from typing import Dict, Optional


class SimulatedCrash(BaseException):
    """Process death at a named site.  BaseException on purpose: recovery
    code under test must never be able to catch and absorb it."""

    def __init__(self, site: str):
        super().__init__(f"simulated crash at {site}")
        self.site = site


class TransientIOError(OSError):
    """An injected transient I/O failure (storage hiccup, flaky mount)."""


class FaultPlan:
    """One drill's armed faults.

    crash_at: site name -> SimulatedCrash on the Nth hit (1-based, default
    first).  io_fail: site name -> number of consecutive TransientIOErrors
    to inject before letting the call through."""

    def __init__(self, crash_at: Optional[Dict[str, int]] = None,
                 io_fail: Optional[Dict[str, int]] = None):
        self.crash_at = dict(crash_at or {})
        self.io_fail = dict(io_fail or {})
        self.hits: Dict[str, int] = {}       # crashpoint visit counts
        self.io_hits: Dict[str, int] = {}    # io_gate injected-failure counts
        self.fired: Dict[str, int] = {}      # site -> hit index that crashed


_plan: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> None:
    global _plan
    _plan = plan


def clear() -> None:
    install(None)


def active_plan() -> Optional[FaultPlan]:
    return _plan


def crashpoint(site: str) -> None:
    """Mark an interruptible moment.  No-op unless a plan arms `site`."""
    p = _plan
    if p is None:
        return
    n = p.hits.get(site, 0) + 1
    p.hits[site] = n
    want = p.crash_at.get(site)
    if want is not None and n >= want:
        del p.crash_at[site]           # fire once, then the restart survives
        p.fired[site] = n
        raise SimulatedCrash(site)


def io_gate(site: str) -> None:
    """Mark fallible I/O.  Raises TransientIOError for the first
    `plan.io_fail[site]` hits, then lets calls through."""
    p = _plan
    if p is None:
        return
    left = p.io_fail.get(site, 0)
    if left > 0:
        p.io_fail[site] = left - 1
        p.io_hits[site] = p.io_hits.get(site, 0) + 1
        raise TransientIOError(f"injected transient I/O failure at {site}")


# ---- seeded corruption helpers ---------------------------------------------


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate `path` to `keep_fraction` of its size (a partial write).
    Returns the new size."""
    size = os.path.getsize(path)
    new = max(int(size * keep_fraction), 0)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def bit_flip_file(path: str, seed: int, flips: int = 8) -> list:
    """Flip `flips` seeded-random bits in `path` (bit-rot).  Returns the
    byte offsets touched."""
    rng = random.Random(seed)  # nondet-ok(seeded stdlib RNG: deterministic corruption pattern)
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        if not data:
            return []
        offsets = []
        for _ in range(flips):
            i = rng.randrange(len(data))
            data[i] ^= 1 << rng.randrange(8)
            offsets.append(i)
        f.seek(0)
        f.write(data)
        f.truncate(len(data))
    return offsets


def torn_tail(path: str, garbage: bytes = b'{"event": "tick", "ts\xff\xfe') -> None:
    """Append a torn final record — a partial JSON line with invalid UTF-8,
    exactly what a crash mid-`write()` leaves behind (no trailing
    newline)."""
    with open(path, "ab") as f:
        f.write(garbage)

"""The input-fuzzing smoke: semantic garbage in, typed rejections out.

One `FuzzSmoke` run builds a single tiny compiled service and throws the
whole `faults.REQUEST_MUTATIONS` catalogue at its front door — NaN and
negative rates, out-of-range and wrong-role sources, length mismatches,
non-finite bandwidths, saturating load — across several seeds each,
interleaved with valid traffic.  Four invariants make it a guardrail
proof rather than a crash hunt:

- zero uncontained faults: no fuzzed input ever raises out of `submit`
  or reaches a compiled program; every one is refused at admission with
  the typed `reason` its mutation predicts (`serve.guards`);
- valid traffic unperturbed: the same valid request ids served before,
  among, and after the garbage produce bit-identical decisions — the
  guards add a veto, never a perturbation;
- conservation: every admitted request is answered exactly once
  (admitted == served, queue drains to zero) and every fuzzed one is
  counted in `rejected_invalid` / `mho_serve_rejected_total`;
- zero unexpected retraces: garbage at the edge never reshapes the
  compiled programs (`obs.jaxhooks` steady-state discipline).

Two weight-surface legs ride along so `mho-fuzz --smoke` is the one
self-contained guardrail record: a checksum-valid NaN-poisoned
checkpoint refused by the semantic canary at hot-reload, and
byte-corrupt checkpoints quarantined by verification — the two halves
(semantic vs byte) of the poisoned-weights fault class.  The committed
record is `benchmarks/fuzz_smoke.json`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

import numpy as np

from multihop_offload_tpu.chaos import faults
from multihop_offload_tpu.config import Config

FUZZ_SEEDS = (0, 1, 2)


def fuzz_config(cfg: Config, tmp: str) -> Config:
    """Tiny two-bucket service shared by every leg: small enough to
    compile in seconds on CPU, two buckets so routing stays exercised."""
    return dataclasses.replace(
        cfg,
        serve_sizes="10,14", serve_buckets=2, serve_slots=4,
        serve_queue_cap=64, serve_deadline_s=60.0,
        model_root=os.path.join(tmp, "model"),
        obs_log=os.path.join(tmp, "fuzz_run.jsonl"),
        loop_capture_sample=0.0,
        io_retries=3, io_backoff_s=0.0,
    )


class FuzzSmoke:
    """State shared across the legs: ONE compiled service, one registry."""

    def __init__(self, cfg: Config, tmp: str):
        from multihop_offload_tpu.cli.serve import build_service

        self.tmp = tmp
        self.base = fuzz_config(cfg, tmp)
        self.t = {"now": 0.0}
        self.clock: Callable[[], float] = lambda: self.t["now"]
        self.service, self.pool = build_service(self.base, clock=self.clock)
        self.legs: list = []

    # ---- shared plumbing ---------------------------------------------------

    def _stream(self, count: int, id_offset: int) -> list:
        from multihop_offload_tpu.serve.workload import request_stream

        cfg = self.base
        return list(request_stream(
            self.pool, count, seed=cfg.seed + 1 + id_offset,
            arrival_scale=cfg.arrival_scale, ul=cfg.ul_data, dl=cfg.dl_data,
            t_max=float(cfg.T), id_offset=id_offset,
        ))

    def _serve(self, reqs: list) -> dict:
        """Closed loop over `reqs`; returns {request_id: response}.  Only
        backpressure is retried — anything else dropped is the drop the
        leg is asserting on."""
        pending = list(reqs)
        pending.reverse()
        out = {}
        while pending or self.service.queue_depth:
            while pending:
                req = pending.pop()
                if not self.service.submit(req):
                    if self.service.last_submit_outcome == "backpressure":
                        pending.append(req)
                    break
            for r in self.service.tick():
                out[r.request_id] = r
        return out

    def _finish(self, rec: dict) -> dict:
        rec["ok"] = all(rec["checks"].values())
        self.legs.append(rec)
        return rec

    # ---- legs --------------------------------------------------------------

    def run_typed_rejections(self) -> dict:
        """Every mutation family x seed: the guard must refuse it with
        exactly the reason the catalogue predicts, both through the pure
        validator and through the full `submit` path."""
        from multihop_offload_tpu.obs.registry import registry as obs_registry
        from multihop_offload_tpu.serve.guards import validate_request

        reg = obs_registry()
        before = reg.counter("mho_serve_rejected_total").total()
        invalid_before = self.service.stats.invalid
        cases = []
        uncontained = 0
        for i, (mutation, want) in enumerate(faults.REQUEST_MUTATIONS):
            for seed in FUZZ_SEEDS:
                base = self._stream(1, id_offset=200_000 + 100 * i + seed)[0]
                assert validate_request(base) is None
                try:
                    bad = faults.fuzz_request(base, mutation, seed=seed)
                    rej = validate_request(bad)
                    admitted = self.service.submit(bad)
                except Exception as e:  # swallow-ok(the leg's whole point: an escape IS the recorded failure)
                    uncontained += 1
                    cases.append({"mutation": mutation, "seed": seed,
                                  "error": repr(e)})
                    continue
                cases.append({
                    "mutation": mutation, "seed": seed,
                    "want": want,
                    "got": rej.reason if rej is not None else None,
                    "submit_refused": not admitted,
                    "outcome": self.service.last_submit_outcome,
                })
        n = len(faults.REQUEST_MUTATIONS) * len(FUZZ_SEEDS)
        after = reg.counter("mho_serve_rejected_total").total()
        rec = {
            "name": "typed_rejections",
            "injected": f"{n} fuzzed requests "
                        f"({len(faults.REQUEST_MUTATIONS)} mutation "
                        f"families x {len(FUZZ_SEEDS)} seeds)",
            "cases": cases,
            "checks": {
                "zero_uncontained": uncontained == 0,
                "all_refused": all(c.get("submit_refused") for c in cases),
                "typed_reasons_match": all(
                    c.get("got") == c.get("want") for c in cases
                ),
                "outcome_recorded": all(
                    c.get("outcome") == "rejected_invalid" for c in cases
                ),
                "stats_counted":
                    self.service.stats.invalid - invalid_before == n,
                "registry_counted": int(after - before) == n,
            },
        }
        return self._finish(rec)

    def run_valid_bit_parity(self) -> dict:
        """The SAME valid request ids served clean, then re-served with
        fuzzed garbage interleaved: decisions must be bit-identical
        (decisions are PRNG-keyed by request id) — the guards veto, they
        never perturb."""
        reqs = self._stream(8, id_offset=210_000)
        control = self._serve(list(reqs))
        # interleave one fuzzed copy of each valid request into the replay
        mixed, garbage = [], 0
        for k, req in enumerate(reqs):
            mixed.append(req)
            mutation = faults.REQUEST_MUTATIONS[
                k % len(faults.REQUEST_MUTATIONS)][0]
            mixed.append(faults.fuzz_request(req, mutation, seed=k))
            garbage += 1
        replay = self._serve(mixed)
        parity = {
            rid: bool(np.array_equal(replay[rid].dst, control[rid].dst)
                      and np.array_equal(replay[rid].is_local,
                                         control[rid].is_local))
            for rid in control
            if rid in replay
        }
        rec = {
            "name": "valid_bit_parity",
            "injected": f"{garbage} fuzzed requests interleaved with "
                        f"{len(reqs)} valid replays",
            "checks": {
                "all_valid_served": len(parity) == len(control) == len(reqs),
                "decisions_bit_identical": bool(parity)
                and all(parity.values()),
                "all_gnn": all(r.served_by == "gnn"
                               for r in replay.values()),
            },
        }
        return self._finish(rec)

    def run_conservation(self) -> dict:
        """Across everything this smoke has thrown at the service: every
        admitted request answered exactly once, queue drained, every
        fuzzed one counted — nothing lost, nothing double-served."""
        s = self.service.stats.summary()
        rec = {
            "name": "conservation",
            "injected": None,
            "summary": {k: s[k] for k in ("admitted", "served",
                                          "rejected_invalid",
                                          "rejected_backpressure",
                                          "rejected_too_large")},
            "checks": {
                "admitted_eq_served": s["admitted"] == s["served"],
                "queue_drained": self.service.queue_depth == 0,
                "rejections_counted": s["rejected_invalid"] > 0,
            },
        }
        return self._finish(rec)

    def run_poisoned_checkpoint(self) -> dict:
        """The weight surface: a checksum-valid NaN-poisoned checkpoint
        must be refused at hot-reload (semantic gate), champion untouched
        and still serving."""
        import jax

        from multihop_offload_tpu.loop.canary import CheckpointCanary
        from multihop_offload_tpu.train import checkpoints as ckpt_lib

        cfg = self.base
        directory = os.path.join(cfg.model_dir(), "orbax")
        ex = self.service.executor
        host = jax.tree_util.tree_map(np.asarray, ex.variables)
        ckpt_lib.save_checkpoint(
            directory, 1, {"params": host["params"]},
            lineage=ckpt_lib.make_lineage("offline"),
        )
        champion = self.service.hot_reload(cfg.model_dir())
        canary = CheckpointCanary(self.service, self.pool, count=6,
                                  seed=cfg.seed + 77)
        canary.record_champion()
        ex.canary = canary
        try:
            poisoned = faults.poison_checkpoint(directory, mode="nan",
                                                seed=cfg.seed)
            checksum_valid = ckpt_lib.has_verified(directory, poisoned)
            step = self.service.hot_reload(cfg.model_dir())
            served = self._serve(self._stream(4, id_offset=220_000))
        finally:
            ex.canary = None
            ex._canary_rejected.clear()
        rec = {
            "name": "poisoned_checkpoint",
            "injected": f"checksum-valid NaN poison at step {poisoned}",
            "checks": {
                "champion_loaded": champion == 1,
                "poison_passes_checksum": checksum_valid,
                "reload_refused": step is None and ex.loaded_step == 1,
                "champion_still_serving": len(served) == 4 and all(
                    r.served_by == "gnn" for r in served.values()
                ),
            },
        }
        return self._finish(rec)

    def run_corrupt_bytes(self) -> dict:
        """The other half of the weight surface: byte corruption (a
        truncated step) is caught by integrity verification and
        quarantined — the canary never even runs."""
        import jax

        from multihop_offload_tpu.train import checkpoints as ckpt_lib

        cfg = self.base
        directory = os.path.join(cfg.model_dir(), "orbax")
        ex = self.service.executor
        host = jax.tree_util.tree_map(np.asarray, ex.variables)
        step = (ckpt_lib.latest_step(directory) or 0) + 1
        ckpt_lib.save_checkpoint(
            directory, step, {"params": host["params"]},
            lineage=ckpt_lib.make_lineage("refit"),
        )
        n = 0
        for root, _, files in os.walk(os.path.join(directory, str(step))):
            for f in files:
                p = os.path.join(root, f)
                if os.path.getsize(p) > 0:
                    faults.truncate_file(p, keep_fraction=0.3)
                    n += 1
        got = self.service.hot_reload(cfg.model_dir())
        served = self._serve(self._stream(4, id_offset=230_000))
        rec = {
            "name": "corrupt_bytes",
            "injected": f"{n} files truncated at step {step}",
            "checks": {
                "stayed_on_last_good": got in (None, 1)
                and ex.loaded_step == 1,
                "quarantine_dir_populated": bool(os.listdir(
                    os.path.join(directory, "quarantine"))),
                "kept_serving": len(served) == 4,
            },
        }
        return self._finish(rec)

    # ---- the matrix --------------------------------------------------------

    def run_all(self) -> dict:
        from multihop_offload_tpu.obs import jaxhooks
        from multihop_offload_tpu.obs.registry import registry as obs_registry

        # warm the compiled programs with one clean window, then freeze:
        # nothing the fuzz throws afterwards may trace a new program
        jaxhooks.install()
        self._serve(self._stream(4, id_offset=190_000))
        jaxhooks.mark_steady()
        try:
            self.run_typed_rejections()
            self.run_valid_bit_parity()
            self.run_poisoned_checkpoint()
            self.run_corrupt_bytes()
            self.run_conservation()
            retraces = jaxhooks.unexpected_retraces()
        finally:
            jaxhooks.clear_steady()
        reg = obs_registry()
        record = {
            "legs": self.legs,
            "counters": {
                "rejected_invalid": int(reg.counter(
                    "mho_serve_rejected_total").total()),
                "canary_rejections": int(reg.counter(
                    "mho_canary_rejections_total").total()),
                "quarantined": int(reg.counter(
                    "mho_ckpt_quarantined_total").total()),
                "serve_nonfinite": int(reg.counter(
                    "mho_dev_serve_nonfinite_total").total()),
            },
            "checks": {
                "all_legs_ok": all(leg["ok"] for leg in self.legs),
                "leg_count": len(self.legs),
                "zero_unexpected_retraces": retraces == 0,
                "zero_live_nonfinite": int(reg.counter(
                    "mho_dev_serve_nonfinite_total").total()) == 0,
            },
        }
        record["ok"] = all(record["checks"].values())
        return record


def run_smoke(cfg: Config) -> dict:
    """The full fuzz matrix in one temp tree; asserts every leg's checks.
    The committed record is `benchmarks/fuzz_smoke.json`."""
    import tempfile

    with tempfile.TemporaryDirectory(prefix="mho_fuzz_smoke_") as tmp:
        harness = FuzzSmoke(cfg, tmp)
        record = harness.run_all()
    failed = [leg["name"] for leg in record["legs"] if not leg["ok"]]
    assert record["ok"], f"fuzz smoke failed: {failed or record['checks']}"
    return record

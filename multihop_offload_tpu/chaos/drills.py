"""The chaos drill matrix: inject every fault class, observe every recovery.

One `ChaosSmoke` run builds a single tiny compiled service (a manual
clock, one bucket) and drives every drill against it — kill-and-restart
of the flywheel at mid-refit / mid-promotion / mid-rollback sites,
checkpoint truncation and bit-flip, checksum-valid weight poisoning
(refused by the semantic canary, not byte verification), event-log torn
final record and missing segment, slow/stuck ticks through the watchdog,
backward clock skew, and transient I/O errors through the retry/backoff
machinery.

Every drill returns a record `{name, injected, recovered, checks{...},
ok}`; the smoke asserts three global invariants on top:

- decisions never wrong: after every crash-recovery the service answers a
  golden request set bit-identically to the pre-fault champion (requests
  are keyed by id, rollback re-pins the champion params) — faults may
  DEGRADE service to the baseline, never silently change GNN decisions;
- conservation: every admitted request is answered exactly once per
  window (admitted == served, queue drains to zero), and every captured
  outcome event is counted;
- zero unexpected retraces after recovery: crash-resume and quarantine
  fallback swap weights, never programs.

Process death is simulated by `faults.crashpoint` raising
`SimulatedCrash` (a BaseException — no recovery path can swallow it) out
of `cli.loop.run_loop`; the "restarted process" re-enters `run_loop`
against the same on-disk state with the executor's loaded-step cache
cleared, exactly what a supervisor restart does.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional

import numpy as np

from multihop_offload_tpu.chaos import faults
from multihop_offload_tpu.config import Config

# the crash sites the kill drills (and tests/test_chaos.py) cover; one per
# promote.py transition plus the long-running phases between them
KILL_SITES = (
    "capture:mid",
    "refit:mid",
    "refit:pre_save",
    "refit:post_save",
    "promote:pre_save",
    "promote:post_save",
    "promote:post_reload",
    "monitor:mid",
    "rollback:pre_save",
    "rollback:post_save",
)


def smoke_config(cfg: Config, tmp: str) -> Config:
    """Tiny single-bucket flywheel config shared by every drill: near-zero
    LR so promotion gates pass deterministically, full capture, zero retry
    backoff (drills inject transient failures on purpose)."""
    return dataclasses.replace(
        cfg,
        serve_sizes="10", serve_buckets=1, serve_slots=4,
        serve_queue_cap=64, serve_deadline_s=60.0,
        model_root=os.path.join(tmp, "model"),
        obs_log=os.path.join(tmp, "chaos_run.jsonl"),
        obs_log_max_bytes=4096,
        loop_capture_sample=1.0, loop_capture_requests=12,
        loop_refit_steps=2, loop_refit_slots=2, loop_holdout_frac=0.25,
        loop_sim_rounds=1, loop_sim_slots=60, loop_cycles=1,
        loop_candidate_keep=1, loop_cooldown_s=0.0,
        sim_cap=64, sim_margin=5.0,
        learning_rate=1e-6, learning_decay=1.0,
        io_retries=3, io_backoff_s=0.0,
    )


class ChaosSmoke:
    """State shared across the drill matrix: ONE compiled service."""

    def __init__(self, cfg: Config, tmp: str):
        import jax

        from multihop_offload_tpu.cli.serve import build_service

        self.tmp = tmp
        self.base = smoke_config(cfg, tmp)
        self.t = {"now": 0.0}
        self.clock: Callable[[], float] = lambda: self.t["now"]
        self.service, self.pool = build_service(self.base, clock=self.clock)
        # pristine weight snapshot: every drill starts from this champion
        self.init_vars = jax.tree_util.tree_map(
            np.asarray, self.service.executor.variables
        )
        self.golden: dict = {}
        self.drills: list = []

    # ---- shared plumbing ---------------------------------------------------

    def _reset_service(self) -> None:
        from multihop_offload_tpu.serve.metrics import ServingStats

        ex = self.service.executor
        ex.variables = {"params": self.init_vars["params"]}
        ex.loaded_step = None
        ex.loaded_lineage = None
        ex.canary = None
        ex._canary_rejected.clear()
        self.service.stats = ServingStats()
        self.service.watchdog = None
        self.service._degraded_until.clear()
        for q in self.service._queues:
            q.clear()

    def _drill_cfg(self, name: str) -> Config:
        d = os.path.join(self.tmp, name.replace(":", "_"))
        return dataclasses.replace(
            self.base,
            model_root=os.path.join(d, "model"),
            obs_log=os.path.join(d, "run.jsonl"),
        )

    def _serve_ids(self, cfg: Config, id_offset: int, count: int = 6):
        """Serve a deterministic window; returns {request_id: response}."""
        from multihop_offload_tpu.serve.workload import request_stream

        pending = list(request_stream(
            self.pool, count, seed=cfg.seed + 1 + id_offset,
            arrival_scale=cfg.arrival_scale, ul=cfg.ul_data, dl=cfg.dl_data,
            t_max=float(cfg.T), id_offset=id_offset,
        ))
        pending.reverse()
        out = {}
        while pending or self.service.queue_depth:
            while pending:
                req = pending.pop()
                if not self.service.submit(req):
                    pending.append(req)
                    break
            for r in self.service.tick():
                out[r.request_id] = r
        return out

    def _decisions_match(self, got: dict) -> bool:
        """Golden check: every request either matches the champion's GNN
        decision bit-for-bit or was EXPLICITLY degraded to the baseline —
        wrong answers are the one unacceptable failure mode."""
        for rid, ref in self.golden.items():
            r = got.get(rid)
            if r is None:
                return False
            if r.served_by == "baseline":
                continue  # degraded, honestly labeled — allowed
            if not (np.array_equal(r.dst, ref.dst)
                    and np.array_equal(r.is_local, ref.is_local)):
                return False
        return True

    def _run_flywheel(self, cfg: Config, plan: Optional[faults.FaultPlan],
                      inject_regression: bool = True) -> tuple:
        """One run_loop attempt under `plan`; returns (out, crash_site).
        `out` is None when the injected crash killed the "process"."""
        from multihop_offload_tpu import obs
        from multihop_offload_tpu.cli.loop import run_loop

        faults.install(plan)
        runlog = obs.start_run(cfg, role="chaos")
        try:
            out = run_loop(cfg, inject_regression=inject_regression,
                           service=self.service, pool=self.pool)
            return out, None
        except faults.SimulatedCrash as c:
            return None, c.site
        finally:
            faults.clear()
            obs.finish_run(runlog)

    # ---- kill-and-restart drills -------------------------------------------

    def run_baseline(self) -> dict:
        """The uninterrupted reference cycle every kill drill must match:
        promote at step 2, injected regression, rollback at step 3."""
        self._reset_service()
        cfg = self._drill_cfg("baseline")
        out, site = self._run_flywheel(cfg, plan=None)
        assert site is None and out is not None
        self.baseline_terminal = {
            "final_state": out["final_state"],
            "final_loaded_step": out["final_loaded_step"],
            "lineage_source": (out["final_lineage"] or {}).get("source"),
            "lineage_parent_step":
                (out["final_lineage"] or {}).get("parent_step"),
        }
        rec = {
            "name": "baseline", "injected": None, "recovered": True,
            "terminal": self.baseline_terminal,
            "checks": {
                "rolled_back": out["final_state"] == "rolled_back",
                "rollback_lineage":
                    self.baseline_terminal["lineage_source"] == "rollback",
            },
        }
        # golden decisions on the champion params the rollback re-pinned
        self.golden = self._serve_ids(cfg, id_offset=50_000)
        rec["checks"]["golden_captured"] = len(self.golden) > 0
        return self._finish(rec)

    def run_kill(self, site: str) -> dict:
        """SIGKILL-equivalent at `site`, then restart-and-resume: the
        journaled state machine must reach the baseline's terminal state
        and lineage, and the recovered service must answer the golden set
        unchanged."""
        self._reset_service()
        cfg = self._drill_cfg(f"kill_{site}")
        out, crashed_at = self._run_flywheel(
            cfg, faults.FaultPlan(crash_at={site: 1})
        )
        killed = out is None and crashed_at == site
        # "restart": a fresh process has no loaded-step cache and no queue
        self.service.executor.loaded_step = None
        self.service.executor.loaded_lineage = None
        out2, site2 = self._run_flywheel(cfg, plan=None)
        recovered = site2 is None and out2 is not None
        terminal = {
            "final_state": out2["final_state"] if recovered else None,
            "final_loaded_step": out2["final_loaded_step"] if recovered else None,
            "lineage_source":
                ((out2["final_lineage"] or {}).get("source")
                 if recovered else None),
            "lineage_parent_step":
                ((out2["final_lineage"] or {}).get("parent_step")
                 if recovered else None),
        }
        resumed_from = (out2["cycles"][0].get("resumed_from")
                        if recovered and out2["cycles"] else None)
        got = self._serve_ids(cfg, id_offset=50_000) if recovered else {}
        rec = {
            "name": f"kill:{site}", "injected": f"SimulatedCrash at {site}",
            "recovered": recovered, "terminal": terminal,
            "resumed_from": resumed_from,
            "checks": {
                "crash_fired": killed,
                "resumed": recovered,
                "same_terminal": terminal == self.baseline_terminal,
                "decisions_never_wrong": recovered
                and self._decisions_match(got),
                "conservation": (
                    self.service.stats.admitted == self.service.stats.served
                    and self.service.queue_depth == 0
                ),
            },
        }
        return self._finish(rec)

    # ---- checkpoint corruption drills --------------------------------------

    def _bootstrap_dir(self, cfg: Config) -> str:
        from multihop_offload_tpu.cli.loop import _bootstrap_champion

        self._reset_service()
        _bootstrap_champion(cfg, self.service)
        return os.path.join(cfg.model_dir(), "orbax")

    def _corrupt_and_reload(self, name: str, corrupt) -> dict:
        """Shared shape of truncation/bit-flip: save a GOOD step 2, corrupt
        it, hot-reload — it must be quarantined with a typed event and the
        service must keep serving step 1 (last-good), never crash, never
        silently load corrupt bytes."""
        import jax

        from multihop_offload_tpu import obs
        from multihop_offload_tpu.obs import events as obs_events
        from multihop_offload_tpu.train import checkpoints as ckpt_lib

        cfg = self._drill_cfg(name)
        runlog = obs.start_run(cfg, role="chaos")
        try:
            directory = self._bootstrap_dir(cfg)
            host = jax.tree_util.tree_map(
                np.asarray, self.service.executor.variables
            )
            ckpt_lib.save_checkpoint(
                directory, 2, {"params": host["params"]},
                lineage=ckpt_lib.make_lineage("refit", parent_step=1),
            )
            n_corrupt = corrupt(directory)
            step = self.service.hot_reload(cfg.model_dir())
            served = self._serve_ids(cfg, id_offset=60_000)
            quarantined = [
                e for e in obs_events.read_events(cfg.obs_log)
                if e.get("event") == "ckpt_quarantine"
            ]
            rec = {
                "name": name,
                "injected": f"{n_corrupt} bytes/files corrupted at step 2",
                "recovered": True,
                "checks": {
                    "quarantine_event": len(quarantined) >= 1,
                    "quarantine_dir_populated": bool(os.listdir(
                        os.path.join(directory, "quarantine"))),
                    "stayed_on_last_good":
                        self.service.executor.loaded_step == 1
                        and step in (None, 1),
                    "kept_serving": len(served) > 0,
                    "still_gnn_on_last_good": all(
                        r.served_by == "gnn" for r in served.values()
                    ),
                },
            }
        finally:
            obs.finish_run(runlog)
        return self._finish(rec)

    def run_ckpt_truncation(self) -> dict:
        def corrupt(directory: str) -> int:
            n = 0
            for root, _, files in os.walk(os.path.join(directory, "2")):
                for f in files:
                    p = os.path.join(root, f)
                    if os.path.getsize(p) > 0:
                        faults.truncate_file(p, keep_fraction=0.3)
                        n += 1
            return n

        return self._corrupt_and_reload("ckpt_truncation", corrupt)

    def run_ckpt_bitflip(self) -> dict:
        def corrupt(directory: str) -> int:
            # flip bits in the LARGEST file under the step dir (the array
            # data), leaving metadata parseable: this is the silent-load
            # hole the content checksum exists to close
            biggest, size = None, -1
            for root, _, files in os.walk(os.path.join(directory, "2")):
                for f in files:
                    p = os.path.join(root, f)
                    if os.path.getsize(p) > size:
                        biggest, size = p, os.path.getsize(p)
            faults.bit_flip_file(biggest, seed=self.base.seed, flips=16)
            return 16

        return self._corrupt_and_reload("ckpt_bitflip", corrupt)

    # ---- semantic weight-poison drills -------------------------------------
    # the fault class the byte drills above CANNOT represent: the poisoned
    # checkpoint is saved through the normal path, so its integrity checksum
    # is perfectly valid — only the semantic canary can refuse it

    def run_weight_poison_hot_reload(self) -> dict:
        """A checksum-VALID NaN-poisoned checkpoint at step 2 must be
        refused by the serve-side semantic gate at hot-reload: loaded step
        stays 1, typed `canary_reject` event, NO quarantine (the bytes are
        fine — quarantining them would hide the real fault class), and the
        champion keeps serving GNN decisions."""
        from multihop_offload_tpu import obs
        from multihop_offload_tpu.loop.canary import CheckpointCanary
        from multihop_offload_tpu.obs import events as obs_events
        from multihop_offload_tpu.train import checkpoints as ckpt_lib

        cfg = self._drill_cfg("poison_hot_reload")
        runlog = obs.start_run(cfg, role="chaos")
        ex = self.service.executor
        try:
            directory = self._bootstrap_dir(cfg)
            canary = CheckpointCanary(self.service, self.pool, count=6,
                                      seed=self.base.seed + 77)
            canary.record_champion()
            ex.canary = canary
            poisoned = faults.poison_checkpoint(directory, mode="nan",
                                                seed=self.base.seed)
            checksum_valid = ckpt_lib.has_verified(directory, poisoned)
            step = self.service.hot_reload(cfg.model_dir())
            # a second poll must hit the cached rejection, not re-restore
            step2 = self.service.hot_reload(cfg.model_dir())
            served = self._serve_ids(cfg, id_offset=110_000)
            events = list(obs_events.read_events(cfg.obs_log))
            rejects = [e for e in events if e.get("event") == "canary_reject"]
            rec = {
                "name": "weight_poison_hot_reload",
                "injected": f"checksum-valid NaN poison at step {poisoned}",
                "recovered": True,
                "checks": {
                    "poison_passes_checksum": checksum_valid,
                    "reload_refused": step is None and step2 is None,
                    "stayed_on_champion": ex.loaded_step == 1,
                    "canary_reject_event": len(rejects) >= 1
                    and rejects[0].get("stage") == "hot_reload",
                    "no_quarantine": not any(
                        e.get("event") == "ckpt_quarantine" for e in events
                    ),
                    "still_gnn_on_champion": len(served) > 0 and all(
                        r.served_by == "gnn" for r in served.values()
                    ),
                },
            }
        finally:
            ex.canary = None
            ex._canary_rejected.clear()
            obs.finish_run(runlog)
        return self._finish(rec)

    def run_weight_poison_promotion(self) -> dict:
        """The same fault class offered through the flywheel's front door:
        a NaN-poisoned candidate handed to `PromotionController.promote`
        with the canary must be refused BEFORE the write-ahead `promoting`
        intent — journaled `canarying` then `rejected`, no serving step
        pinned, champion untouched."""
        import jax

        from multihop_offload_tpu import obs
        from multihop_offload_tpu.loop.canary import CheckpointCanary
        from multihop_offload_tpu.loop.promote import PromotionController
        from multihop_offload_tpu.obs import events as obs_events

        cfg = self._drill_cfg("poison_promotion")
        runlog = obs.start_run(cfg, role="chaos")
        try:
            self._bootstrap_dir(cfg)
            canary = CheckpointCanary(self.service, self.pool, count=6,
                                      seed=self.base.seed + 78)
            canary.record_champion()
            rng = np.random.default_rng(self.base.seed)

            def nan_poison(x):
                a = np.array(x, copy=True)
                if np.issubdtype(a.dtype, np.floating):
                    flat = a.reshape(-1)
                    idx = rng.choice(flat.size, size=max(flat.size // 4, 1),
                                     replace=False)
                    flat[idx] = np.nan
                return a

            host = jax.tree_util.tree_map(
                np.asarray, self.service.executor.variables
            )
            candidate = {"params": jax.tree_util.tree_map(
                nan_poison, host["params"]
            )}
            ctl = PromotionController(cfg.model_dir())
            before = self.service.executor.loaded_step
            got = ctl.promote(self.service, candidate, candidate_step=2,
                              canary=canary)
            served = self._serve_ids(cfg, id_offset=120_000)
            rejects = [e for e in obs_events.read_events(cfg.obs_log)
                       if e.get("event") == "canary_reject"]
            states = [h["state"] for h in ctl.history]
            rec = {
                "name": "weight_poison_promotion",
                "injected": "NaN-poisoned candidate offered for promotion",
                "recovered": True,
                "checks": {
                    "promotion_refused": got is None
                    and ctl.state == "rejected",
                    "canarying_journaled": states[:2]
                    == ["canarying", "rejected"],
                    "no_serving_step_pinned":
                        self.service.executor.loaded_step == before,
                    "canary_reject_event": len(rejects) >= 1
                    and rejects[0].get("stage") == "promote",
                    "typed_reason": len(rejects) >= 1
                    and rejects[0].get("reason") == "nonfinite_probe_outputs",
                    "champion_still_serving": len(served) > 0 and all(
                        r.served_by == "gnn" for r in served.values()
                    ),
                },
            }
        finally:
            obs.finish_run(runlog)
        return self._finish(rec)

    # ---- event-log drills --------------------------------------------------

    def _seeded_runlog(self, name: str):
        """A rotated 3+ segment chain with a known final marker event."""
        from multihop_offload_tpu.obs.events import RunLog, segment_paths

        path = os.path.join(self.tmp, name, "log.jsonl")
        log = RunLog(path, manifest={"event": "manifest", "drill": name},
                     max_bytes=512)
        for i in range(40):
            log.emit("tick", n=i, payload="x" * 48)
        log.emit("summary", marker="end-of-chain")
        log.close()
        return path, segment_paths(path)

    def run_log_torn_record(self) -> dict:
        """A byte-level torn write (invalid UTF-8, no newline) at the END
        of a MID-CHAIN segment — the exact shape that used to look like
        end-of-log and silently hide every later segment."""
        from multihop_offload_tpu.obs.events import read_events

        path, segs = self._seeded_runlog("log_torn")
        torn_seg = segs[1]  # mid-chain, crash interrupted the rotation
        faults.torn_tail(torn_seg)
        events = list(read_events(path))
        rec = {
            "name": "log_torn_record",
            "injected": f"torn invalid-UTF-8 tail on {os.path.basename(torn_seg)}",
            "recovered": True,
            "checks": {
                "reader_reaches_final_segment": any(
                    e.get("marker") == "end-of-chain" for e in events
                ),
                "events_from_all_other_segments":
                    sum(1 for e in events if e.get("event") == "tick") >= 30,
            },
        }
        return self._finish(rec)

    def run_log_missing_segment(self) -> dict:
        """A mid-chain segment deleted outright (lost volume, overeager
        cleanup): the reader must span the hole, and the flywheel's
        experience reader must still parse what survives."""
        from multihop_offload_tpu.obs.events import read_events

        path, segs = self._seeded_runlog("log_missing")
        os.remove(segs[1])
        events = list(read_events(path))
        rec = {
            "name": "log_missing_segment",
            "injected": f"deleted {os.path.basename(segs[1])}",
            "recovered": True,
            "checks": {
                "reader_reaches_final_segment": any(
                    e.get("marker") == "end-of-chain" for e in events
                ),
                "manifest_still_first": bool(events)
                and events[0].get("event") == "manifest",
            },
        }
        return self._finish(rec)

    # ---- watchdog / clock drills -------------------------------------------

    def run_stuck_tick(self) -> dict:
        """Slow then stuck dispatches on a manual clock: the watchdog must
        classify both, dump a flight bundle on stuck, degrade the bucket to
        the baseline for the recovery window, then restore the GNN."""
        from multihop_offload_tpu.obs import events as obs_events
        from multihop_offload_tpu.obs.flightrec import FlightRecorder
        from multihop_offload_tpu.serve.watchdog import TickWatchdog

        from multihop_offload_tpu import obs

        cfg = self._drill_cfg("stuck_tick")
        runlog = obs.start_run(cfg, role="chaos")
        try:
            self._bootstrap_dir(cfg)
            flight_dir = os.path.join(self.tmp, "stuck_tick", "flight")
            recorder = FlightRecorder(capacity=64, clock=self.clock)
            wd = TickWatchdog(threshold_s=0.5, recovery_s=30.0,
                              stuck_factor=10.0, recorder=recorder,
                              flight_dir=flight_dir)
            self.service.attach_watchdog(wd)
            self.service.attach_health(recorder=recorder)

            ex = self.service.executor
            # stall at `dispatch` — the device-work entry the service's
            # two-phase tick issues (run() routes through it too)
            orig_dispatch = ex.dispatch
            stall = {"s": 0.0}

            def stalling_dispatch(*a, **kw):
                self.t["now"] += stall["s"]
                return orig_dispatch(*a, **kw)

            ex.dispatch = stalling_dispatch
            try:
                stall["s"] = 1.0      # slow: 1.0 > 0.5, under 10x
                slow_resp = self._serve_ids(cfg, id_offset=70_000, count=4)
                stall["s"] = 6.0      # stuck: 6.0 > 0.5 * 10
                stuck_resp = self._serve_ids(cfg, id_offset=70_100, count=4)
                stall["s"] = 0.0      # wedge cleared, window still open
                held_resp = self._serve_ids(cfg, id_offset=70_200, count=4)
                self.t["now"] += 31.0  # recovery window expires
                back_resp = self._serve_ids(cfg, id_offset=70_300, count=4)
            finally:
                ex.dispatch = orig_dispatch
                self.service.attach_watchdog(None)
                self.service.attach_health()
            wd_events = [e for e in obs_events.read_events(cfg.obs_log)
                         if e.get("event") in ("watchdog",
                                               "watchdog_recovered")]
            rec = {
                "name": "stuck_tick",
                "injected": "1 s then 6 s dispatch stalls (0.5 s threshold)",
                "recovered": True,
                "checks": {
                    "slow_detected": wd.slow >= 1,
                    "stuck_detected": wd.stuck >= 1,
                    "flight_bundle_dumped": os.path.isdir(flight_dir)
                    and bool(os.listdir(flight_dir)),
                    "degraded_not_wrong": all(
                        r.served_by == "baseline"
                        for r in held_resp.values()
                    ),
                    "gnn_restored_after_recovery": all(
                        r.served_by == "gnn" for r in back_resp.values()
                    ),
                    "recovered_event": any(
                        e.get("event") == "watchdog_recovered"
                        for e in wd_events
                    ),
                    "all_served": all(len(r) == 4 for r in (
                        slow_resp, stuck_resp, held_resp, back_resp)),
                },
            }
        finally:
            obs.finish_run(runlog)
        return self._finish(rec)

    def run_clock_skew(self) -> dict:
        """The clock steps BACKWARD mid-serving (NTP correction): no
        watchdog trip, no negative latencies, decisions identical."""
        from multihop_offload_tpu.obs.flightrec import FlightRecorder
        from multihop_offload_tpu.serve.watchdog import TickWatchdog

        cfg = self._drill_cfg("clock_skew")
        self._bootstrap_dir(cfg)
        wd = TickWatchdog(threshold_s=0.5, recovery_s=30.0,
                          recorder=FlightRecorder(capacity=8,
                                                  clock=self.clock))
        self.service.attach_watchdog(wd)
        try:
            self.t["now"] += 1000.0
            a = self._serve_ids(cfg, id_offset=80_000, count=4)
            self.t["now"] -= 900.0   # backward skew between windows
            b = self._serve_ids(cfg, id_offset=80_100, count=4)
        finally:
            self.service.attach_watchdog(None)
        rec = {
            "name": "clock_skew",
            "injected": "clock stepped back 900 s mid-serving",
            "recovered": True,
            "checks": {
                "no_watchdog_trip": wd.slow == 0 and wd.stuck == 0,
                "no_negative_latency": all(
                    r.latency_s >= 0.0
                    for r in list(a.values()) + list(b.values())
                ),
                "still_gnn": all(r.served_by == "gnn"
                                 for r in b.values()),
            },
        }
        return self._finish(rec)

    # ---- transient I/O + durability drills ---------------------------------

    def run_transient_io(self) -> dict:
        """Transient OSErrors injected at the three durable write sites —
        orbax save, the loop journal, the event log — must be absorbed by
        bounded retry-with-backoff, observable in `mho_io_retries_total`."""
        import jax

        from multihop_offload_tpu.loop.promote import PromotionController
        from multihop_offload_tpu.obs.events import RunLog
        from multihop_offload_tpu.obs.registry import registry as obs_registry
        from multihop_offload_tpu.train import checkpoints as ckpt_lib

        cfg = self._drill_cfg("transient_io")
        directory = os.path.join(cfg.model_dir(), "orbax")
        host = jax.tree_util.tree_map(
            np.asarray, self.service.executor.variables
        )
        before = obs_registry().counter("mho_io_retries_total").total()
        plan = faults.FaultPlan(io_fail={
            "ckpt:save": 2, "journal:write": 2, "events:write": 2,
        })
        faults.install(plan)
        try:
            ckpt_lib.save_checkpoint(
                directory, 1, {"params": host["params"]},
                lineage=ckpt_lib.make_lineage("offline"),
            )
            ctl = PromotionController(cfg.model_dir())
            ctl.transition("capturing", cycle=0)
            log = RunLog(os.path.join(self.tmp, "transient_io", "log.jsonl"))
            log.emit("tick", n=1)
            log.close()
        finally:
            faults.clear()
        after = obs_registry().counter("mho_io_retries_total").total()
        resumed = PromotionController.resume(cfg.model_dir())
        rec = {
            "name": "transient_io",
            "injected": "2 consecutive OSErrors at ckpt:save, "
                        "journal:write, events:write",
            "recovered": True,
            "checks": {
                "all_injected_faults_consumed": sum(
                    plan.io_hits.values()) == 6,
                "retries_counted": (after - before) >= 4,
                "save_survived":
                    ckpt_lib.latest_step(directory) == 1,
                "journal_survived": resumed.state == "capturing",
            },
        }
        return self._finish(rec)

    def run_cooldown_restart(self) -> dict:
        """A post-rollback cool-down must survive a process restart: the
        deadline is journaled, so the restarted flywheel keeps refusing new
        cycles until it passes (wall-clock scheduling needs durable
        timers)."""
        from multihop_offload_tpu.loop.promote import PromotionController

        cfg = self._drill_cfg("cooldown")
        ctl = PromotionController(cfg.model_dir(), clock=self.clock,
                                  cooldown_s=120.0)
        ctl.transition("rolled_back", step=3, reason="drill")
        ctl.start_cooldown()
        ctl2 = PromotionController.resume(cfg.model_dir(), clock=self.clock,
                                          cooldown_s=120.0)
        held = ctl2.cooldown_remaining()
        self.t["now"] += 121.0
        rec = {
            "name": "cooldown_restart",
            "injected": "restart 0 s into a 120 s post-rollback cool-down",
            "recovered": True,
            "checks": {
                "cooldown_survived_restart": 0.0 < held <= 120.0,
                "cooldown_expires": ctl2.cooldown_remaining() == 0.0,
                "state_survived": ctl2.state == "rolled_back",
            },
        }
        return self._finish(rec)

    def run_candidate_gc(self) -> dict:
        """Bounded candidate retention: three rejected-candidate
        checkpoints, keep=1 — the two older ones must be deleted with
        typed `gc` events."""
        import jax

        from multihop_offload_tpu import obs
        from multihop_offload_tpu.loop.promote import PromotionController
        from multihop_offload_tpu.obs import events as obs_events
        from multihop_offload_tpu.train import checkpoints as ckpt_lib

        cfg = self._drill_cfg("candidate_gc")
        runlog = obs.start_run(cfg, role="chaos")
        try:
            ctl = PromotionController(cfg.model_dir(), candidate_keep=1)
            host = jax.tree_util.tree_map(
                np.asarray, self.service.executor.variables
            )
            for s in (1, 2, 3):
                ckpt_lib.save_checkpoint(
                    ctl.candidate_dir, s, {"params": host["params"]},
                    lineage=ckpt_lib.make_lineage("refit"),
                )
            removed = ctl.gc_candidates(reason="drill")
            gc_events = [e for e in obs_events.read_events(cfg.obs_log)
                         if e.get("event") == "gc"]
            rec = {
                "name": "candidate_gc",
                "injected": "3 stale candidates, retention keep=1",
                "recovered": True,
                "checks": {
                    "older_deleted": removed == [1, 2],
                    "newest_kept":
                        ckpt_lib.all_steps(ctl.candidate_dir) == [3],
                    "typed_gc_events": len(gc_events) == 2,
                },
            }
        finally:
            obs.finish_run(runlog)
        return self._finish(rec)

    # ---- sharded fleet drills ----------------------------------------------

    def run_device_loss(self) -> dict:
        """Kill-one-device: a sharded service loses a chip between windows;
        the placement planner must re-place every bucket onto the survivors
        (forced — hysteresis cannot hold an invalid plan), conservation and
        golden decisions must hold across the loss, and restoring the chip
        must return it to the fleet.  Skips gracefully (recorded, ok) on a
        1-device host — the CPU proof needs
        XLA_FLAGS=--xla_force_host_platform_device_count=8."""
        import jax

        from multihop_offload_tpu.cli.serve import build_service
        from multihop_offload_tpu.serve.workload import request_stream

        n_dev = len(jax.devices())
        if n_dev < 2:
            rec = {
                "name": "device_loss",
                "injected": None, "recovered": True,
                "skipped": f"needs >= 2 devices, host has {n_dev} "
                           "(XLA_FLAGS=--xla_force_host_platform_"
                           "device_count=8 for the CPU proof)",
                "checks": {"skipped_gracefully": True},
            }
            return self._finish(rec)

        cfg = dataclasses.replace(
            self._drill_cfg("device_loss"),
            serve_mesh=min(4, n_dev), serve_replan_ticks=2,
        )
        svc, _ = build_service(cfg, pool=self.pool, clock=self.clock)

        def window(id_offset: int, count: int = 6) -> dict:
            pending = list(request_stream(
                self.pool, count, seed=cfg.seed + 1 + id_offset,
                arrival_scale=cfg.arrival_scale, ul=cfg.ul_data,
                dl=cfg.dl_data, t_max=float(cfg.T), id_offset=id_offset,
            ))
            pending.reverse()
            out = {}
            while pending or svc.queue_depth:
                while pending:
                    req = pending.pop()
                    if not svc.submit(req):
                        pending.append(req)
                        break
                for r in svc.tick():
                    out[r.request_id] = r
            return out

        golden = window(id_offset=100_000)
        multi_before = svc.executor.last_devices_used
        victim = svc.executor.devices_for(0)[-1]
        fleet_before = len(svc.planner.devices)
        svc.lose_device(victim)
        plan_after_loss = svc.planner.plan
        # the SAME request ids re-served on the shrunken fleet: decisions
        # are PRNG-keyed by request id, so bit-parity must survive the move
        after = window(id_offset=100_000)
        survived = {
            rid: (np.array_equal(r.dst, golden[rid].dst)
                  and np.array_equal(r.is_local, golden[rid].is_local))
            or r.served_by == "baseline"
            for rid, r in after.items()
        }
        svc.restore_device(victim)
        # drive enough windows for the rate-driven re-plan cadence to see
        # the restored chip
        recovered_win = window(id_offset=100_200)
        rec = {
            "name": "device_loss",
            "injected": f"device {getattr(victim, 'id', victim)} dropped "
                        f"from a {fleet_before}-chip fleet mid-serving",
            "recovered": True,
            "checks": {
                "multi_device_before_loss": multi_before > 1,
                "plan_excludes_lost_device": not plan_after_loss.uses(victim),
                "replaced_onto_survivors": all(
                    len(devs) >= 1 for devs in plan_after_loss.assignments
                ),
                "decisions_never_wrong": bool(survived)
                and all(survived.values()),
                "conservation": (
                    svc.stats.admitted == svc.stats.served
                    and svc.queue_depth == 0
                ),
                "fleet_restored":
                    len(svc.planner.devices) == fleet_before,
                "served_after_restore": len(recovered_win) == 6,
            },
        }
        return self._finish(rec)

    def run_host_loss(self) -> dict:
        """Kill-a-whole-host: the local fleet is split into two pseudo-hosts
        and buckets laid over them by the two-level DCN-aware planner
        (`multihost.plan`); losing a host must force a re-plan that moves
        every one of its buckets onto the survivor's chips WITHOUT crossing
        the host split, decisions must stay bit-identical-or-honestly-
        baseline, conservation must hold, and the takeover compiles must be
        expected rebuilds (zero unexpected retraces).  The cross-PROCESS
        version of this drill is `mho-mesh --smoke`; this in-process twin
        keeps the planner/executor contract in the chaos matrix.  Skips
        gracefully below 4 devices (2 hosts x 2 chips)."""
        import jax

        from multihop_offload_tpu.cli.serve import build_service
        from multihop_offload_tpu.multihost.plan import (
            TwoLevelPlanner, validate_plan,
        )
        from multihop_offload_tpu.obs import jaxhooks
        from multihop_offload_tpu.serve.placement import PlacementPlan
        from multihop_offload_tpu.serve.workload import request_stream

        n_dev = len(jax.devices())
        if n_dev < 4:
            rec = {
                "name": "host_loss",
                "injected": None, "recovered": True,
                "skipped": f"needs >= 4 devices (2 hosts x 2 chips), host "
                           f"has {n_dev} (XLA_FLAGS=--xla_force_host_"
                           "platform_device_count=8 for the CPU proof)",
                "checks": {"skipped_gracefully": True},
            }
            return self._finish(rec)

        cfg = dataclasses.replace(
            self._drill_cfg("host_loss"),
            # two buckets so level 1 has something to spread across hosts
            serve_sizes="10,14", serve_buckets=2,
            serve_mesh=4, serve_replan_ticks=10**9,  # placement injected
        )
        svc, pool = build_service(cfg, clock=self.clock)
        devs = list(jax.devices())[:4]
        hosts = {"hostA": devs[:2], "hostB": devs[2:]}
        n_buckets = len(svc.buckets.pads)
        planner = TwoLevelPlanner(n_buckets, hosts, slots=svc.executor.slots)
        planner.observe([3.0, 2.0][:n_buckets] or [3.0])
        plan = planner.replan()
        validate_plan(plan, hosts)   # DCN invariant before anything compiles
        svc.executor.set_placement(PlacementPlan(plan.devices))

        def window(id_offset: int, count: int = 6) -> dict:
            pending = list(request_stream(
                pool, count, seed=cfg.seed + 1 + id_offset,
                arrival_scale=cfg.arrival_scale, ul=cfg.ul_data,
                dl=cfg.dl_data, t_max=float(cfg.T), id_offset=id_offset,
            ))
            pending.reverse()
            out = {}
            while pending or svc.queue_depth:
                while pending:
                    req = pending.pop()
                    if not svc.submit(req):
                        pending.append(req)
                        break
                for r in svc.tick():
                    out[r.request_id] = r
            return out

        golden = window(id_offset=110_000)
        spans_hosts = len(set(plan.hosts)) > 1
        jaxhooks.install()
        retraces_before = jaxhooks.unexpected_retraces()
        jaxhooks.mark_steady()
        try:
            plan2 = planner.remove_host("hostB")   # forced: invalid plan
            lost_chips = set(hosts["hostB"])
            svc.executor.set_placement(PlacementPlan(plan2.devices))
            after = window(id_offset=110_000)      # same ids, survivor only
            retraces = jaxhooks.unexpected_retraces() - retraces_before
        finally:
            jaxhooks.clear_steady()
        survived = {
            rid: (np.array_equal(r.dst, golden[rid].dst)
                  and np.array_equal(r.is_local, golden[rid].is_local))
            or r.served_by == "baseline"
            for rid, r in after.items()
        }
        plan3 = planner.add_host("hostB", hosts["hostB"])
        rec = {
            "name": "host_loss",
            "injected": "pseudo-host hostB (2 chips) dropped from a "
                        "2-host fleet mid-serving",
            "recovered": True,
            "checks": {
                "plan_spans_hosts_before_loss": spans_hosts,
                "forced_replan_excludes_victim": all(
                    h == "hostA" for h in plan2.hosts
                ) and not any(
                    d in lost_chips for ds in plan2.devices for d in ds
                ),
                "decisions_never_wrong": bool(survived)
                and all(survived.values()),
                "conservation": (
                    svc.stats.admitted == svc.stats.served
                    and svc.queue_depth == 0
                ),
                "zero_unexpected_retraces": retraces == 0,
                "host_restored": "hostB" in planner.hosts
                and validate_plan(plan3, planner.hosts) is None,
            },
        }
        return self._finish(rec)

    # ---- retrace discipline ------------------------------------------------

    def run_no_retrace_after_recovery(self) -> dict:
        """After the whole drill matrix — crashes, quarantines, watchdog
        degrades — serving one more window must trace nothing new: recovery
        swaps weights, never programs."""
        from multihop_offload_tpu.obs import jaxhooks

        cfg = self._drill_cfg("no_retrace")
        self._bootstrap_dir(cfg)
        jaxhooks.install()
        jaxhooks.mark_steady()
        try:
            served = self._serve_ids(cfg, id_offset=90_000, count=6)
            retraces = jaxhooks.unexpected_retraces()
        finally:
            jaxhooks.clear_steady()
        rec = {
            "name": "no_retrace_after_recovery",
            "injected": None,
            "recovered": True,
            "checks": {
                "served": len(served) == 6,
                "zero_unexpected_retraces": retraces == 0,
            },
        }
        return self._finish(rec)

    # ---- the matrix --------------------------------------------------------

    def _finish(self, rec: dict) -> dict:
        rec["ok"] = all(rec["checks"].values())
        self.drills.append(rec)
        return rec

    def run_all(self) -> dict:
        from multihop_offload_tpu.obs.registry import registry as obs_registry

        self.run_baseline()
        # kill-and-restart at a representative site per phase; the full
        # 10-site matrix is pinned by tests/test_chaos.py
        for site in ("refit:mid", "promote:post_save", "rollback:pre_save"):
            self.run_kill(site)
        self.run_ckpt_truncation()
        self.run_ckpt_bitflip()
        self.run_weight_poison_hot_reload()
        self.run_weight_poison_promotion()
        self.run_log_torn_record()
        self.run_log_missing_segment()
        self.run_stuck_tick()
        self.run_clock_skew()
        self.run_transient_io()
        self.run_cooldown_restart()
        self.run_candidate_gc()
        self.run_device_loss()
        self.run_host_loss()
        self.run_no_retrace_after_recovery()
        reg = obs_registry()
        record = {
            "drills": self.drills,
            "counters": {
                "quarantined": int(reg.counter(
                    "mho_ckpt_quarantined_total").total()),
                "canary_rejections": int(reg.counter(
                    "mho_canary_rejections_total").total()),
                "io_retries": int(reg.counter(
                    "mho_io_retries_total").total()),
                "watchdog_slow": int(reg.counter(
                    "mho_watchdog_slow_total").total()),
                "watchdog_stuck": int(reg.counter(
                    "mho_watchdog_stuck_total").total()),
                "loop_resumes": int(reg.counter(
                    "mho_loop_resumes_total").total()),
                "ckpt_gc": int(reg.counter("mho_ckpt_gc_total").total()),
            },
            "checks": {
                "all_drills_ok": all(d["ok"] for d in self.drills),
                "drill_count": len(self.drills),
                "fault_classes_covered": len(self.drills) - 2 >= 8,
            },
        }
        record["ok"] = bool(record["checks"]["all_drills_ok"]
                            and record["checks"]["fault_classes_covered"])
        return record


def run_smoke(cfg: Config) -> dict:
    """The full drill matrix in one temp tree; asserts every drill's
    recovery observed.  The committed record is `benchmarks/chaos_smoke.json`."""
    import tempfile

    with tempfile.TemporaryDirectory(prefix="mho_chaos_smoke_") as tmp:
        harness = ChaosSmoke(cfg, tmp)
        record = harness.run_all()
    failed = [d["name"] for d in record["drills"] if not d["ok"]]
    assert record["ok"], f"chaos smoke failed: {failed or record['checks']}"
    return record

"""Deterministic fault-injection harness for the serve→loop→promote stack.

`faults` is the only module imported here: the production code paths call
its near-zero-cost `crashpoint()` / `io_gate()` hooks, and importing the
drill matrix from package init would create an import cycle
(obs/serve → chaos → drills → serve).  `mho-chaos` imports
`chaos.drills` directly.
"""

from multihop_offload_tpu.chaos.faults import (  # noqa: F401
    FaultPlan,
    SimulatedCrash,
    TransientIOError,
    active_plan,
    clear,
    crashpoint,
    install,
    io_gate,
)

"""Pallas TPU kernel for min-plus all-pairs shortest paths.

The APSP squaring in `env.apsp` asks XLA to reduce a broadcast (N, N, N) sum
— correct, but the kernel here keeps the whole computation in VMEM with zero
HBM intermediates: the distance block lives on-chip and every squaring is an
in-register fori-loop of outer (min, +) updates.

Exploits symmetry: our one-hop weight matrices are symmetric (undirected
links, symmetric per-link delays), and min-plus powers of symmetric matrices
stay symmetric, so the squaring step

    out[i, j] = min_k d[i, k] + d[k, j] = min_k d[k, i] + d[k, j]

is an outer min-plus of row k with itself — only sublane-dimension slices,
never an (expensive) lane-dimension gather.

Grid = batch; each program handles one (N, N) matrix, N padded to the 128
lane width.  A padded-with-inf border is inert under (min, +).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128


def _apsp_kernel(d_ref, o_ref, *, n: int, iters: int):
    d = d_ref[0]
    # row index as an iota comparison: Mosaic has no dynamic_slice on a value
    # held in registers, so row k is extracted with a masked min-reduce
    # (inert +inf elsewhere) — static ops only, same O(N^2) as the update
    row_ids = lax.broadcasted_iota(jnp.int32, (n, 1), 0)

    def squaring(_, dist):
        def body(k, acc):
            masked = jnp.where(row_ids == k, dist, jnp.inf)
            row = jnp.min(masked, axis=0, keepdims=True)     # (1, N) = dist[k]
            return jnp.minimum(acc, row.T + row)

        return lax.fori_loop(0, n, body, dist)

    o_ref[0] = lax.fori_loop(0, iters, squaring, d)


def minplus_power_kernel_call(
    d: jnp.ndarray, iters: int, interpret: bool = False
) -> jnp.ndarray:
    """d: (B, N, N) symmetric with zero diagonal, N a multiple of 128."""
    b, n, _ = d.shape
    kernel = functools.partial(_apsp_kernel, n=n, iters=iters)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n, n), d.dtype),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n, n), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(d)


_MAX_KERNEL_N = 256  # largest padded size with validated Mosaic compiles;
#                      above this the per-row fori body makes compile time
#                      blow up (observed: (1,1024,1024) wedges the compiler
#                      for >10 min), and the whole-matrix-in-VMEM premise
#                      stops paying off anyway — fall back to XLA / the
#                      ring-sharded APSP (`parallel.ring`) instead.


def apsp_minplus_pallas(
    weights: jnp.ndarray,
    num_iters: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in replacement for `env.apsp.apsp_minplus` (symmetric weights).

    Accepts (N, N) or batched (B, N, N); pads N up to the 128-lane width with
    +inf (inert) and zero-diagonals the result region.  Sizes beyond the
    validated kernel range delegate to the XLA squaring.
    """
    squeeze = weights.ndim == 2
    w = weights[None] if squeeze else weights
    b, n, _ = w.shape
    n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
    if n_pad > _MAX_KERNEL_N and not interpret:
        from multihop_offload_tpu.env.apsp import apsp_minplus

        out = jax.vmap(lambda m: apsp_minplus(m, num_iters))(w)
        return out[0] if squeeze else out
    iters = num_iters if num_iters is not None else max(1, math.ceil(math.log2(max(n - 1, 2))))

    eye = jnp.eye(n, dtype=bool)
    w = jnp.where(eye, jnp.zeros_like(w), w)
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n), (0, n_pad - n))
        w = jnp.pad(w, pad, constant_values=jnp.inf)
    out = minplus_power_kernel_call(w, iters, interpret=interpret)
    out = out[:, :n, :n]
    return out[0] if squeeze else out

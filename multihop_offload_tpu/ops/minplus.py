"""Pallas TPU kernels for min-plus all-pairs shortest paths.

Three regimes, replacing the reference's per-graph Dijkstra loop
(`util.py:101-110`, its hottest non-TF routine):

* **Whole-matrix squaring** (padded N <= 256): the distance matrix lives in
  VMEM and every squaring is an in-register fori-loop of outer (min, +)
  updates.  Exploits symmetry — our one-hop weight matrices are symmetric
  (undirected links, symmetric per-link delays) and min-plus powers of
  symmetric matrices stay symmetric, so

      out[i, j] = min_k d[i, k] + d[k, j] = min_k d[k, i] + d[k, j]

  is an outer min-plus of row k with itself: only sublane-dimension slices,
  never an (expensive) lane-dimension gather.

* **Blocked Floyd-Warshall** (larger N): the classic three-phase tiling
  (pivot close / row+col panels / outer update) with 128x128 VMEM tiles and
  the distance matrix in HBM.  The pivot index `kk` is a scalar-prefetch
  input, so each phase is ONE compiled kernel re-invoked from a
  `fori_loop` — compile cost is independent of N (the round-1 whole-matrix
  kernel wedged Mosaic beyond N=256).  One FW sweep is O(N^3) total versus
  the squaring's O(N^3 log N), and each phase writes only its blocks
  in-place (`input_output_aliases`), so HBM traffic per pivot is O(N^2).

* **COO-fed squaring** (`apsp_minplus_coo`, padded N <= 256): the sparse
  layout's regime — W is rebuilt in registers straight from the padded
  link list (no dense (N, N) scatter in HBM) and handed to the same
  chunked squaring, bit-identical to the scatter+XLA reference chain.

A padded-with-inf border is inert under (min, +) for all paths.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


_ROW_CHUNK = 8  # f32 sublane count: rows extracted one sublane group at a time


def _chunked_squaring(d: jnp.ndarray, n: int, iters: int) -> jnp.ndarray:
    """`iters` min-plus squarings of a symmetric (N, N) register value.

    Mosaic has no dynamic_slice on a value held in registers, so pivot rows
    are extracted with masked min-reduces (inert +inf elsewhere).  Doing
    that per pivot costs O(N^2) VPU work per row — as much as the update
    itself (round-3 verdict: the kernel lost to XLA below N=512 mostly on
    this).  Min-plus SQUARING has independent pivots (unlike FW), so rows
    are pulled a SUBLANE GROUP at a time: one masked reduce yields 8 rows
    (O(N^2) per chunk, O(N^3/8) total), then a static 8-way unroll of
    cheap register slices does the outer updates.  Shared by the dense-fed
    (`_apsp_kernel`) and COO-fed (`_coo_apsp_kernel`) entry points."""
    c = _ROW_CHUNK
    nchunks = n // c
    chunk_ids = lax.broadcasted_iota(jnp.int32, (nchunks, 1, 1), 0)

    def squaring(_, dist):
        dist_r = dist.reshape(nchunks, c, n)

        def chunk_body(q, acc):
            rows = jnp.min(
                jnp.where(chunk_ids == q, dist_r, jnp.inf), axis=0
            )                                   # (c, N) = dist[qc:(q+1)c]
            cols = rows.T                       # (N, c): symmetric matrix
            for j in range(c):                  # static unroll, register slices
                acc = jnp.minimum(acc, cols[:, j:j + 1] + rows[j:j + 1, :])
            return acc

        return lax.fori_loop(0, nchunks, chunk_body, dist)

    return lax.fori_loop(0, iters, squaring, d)


def _apsp_kernel(d_ref, o_ref, *, n: int, iters: int):
    o_ref[0] = _chunked_squaring(d_ref[0], n, iters)


def minplus_power_kernel_call(
    d: jnp.ndarray, iters: int, interpret: bool = False
) -> jnp.ndarray:
    """d: (B, N, N) symmetric with zero diagonal, N a multiple of 128."""
    b, n, _ = d.shape
    kernel = functools.partial(_apsp_kernel, n=n, iters=iters)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n, n), d.dtype),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n, n), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(d)


_MAX_SQUARING_N = 256  # largest padded size where the whole-matrix VMEM
#                        squaring kernel is the right shape (validated Mosaic
#                        compiles; beyond this the blocked FW takes over —
#                        the round-1 whole-matrix kernel at (1,1024,1024)
#                        wedged the compiler for >10 min).
_MAX_BLOCKED_N = 2048  # blocked-FW ceiling: above this the (B, N, N) HBM
#                        residency and per-call latency favor the
#                        ring-sharded APSP (`parallel.ring`) across chips.
_AUTO_PALLAS_MIN_N = 256  # measured crossover on a real v5e chip
#                        (benchmarks/pallas_tpu.json, round-5 re-ladder of
#                        the sublane-chunked squaring rework): XLA wins only
#                        below padded N=256; the chunked squaring kernel
#                        wins at 256 (1.12x), blocked FW from 384 (1.29x),
#                        2.48x at 512, 4.33x at 1024.  The pre-rework kernel
#                        lost 0.62-0.63x at 128-256, hence the old 512 floor.
#                        (4.93x).  `apsp_impl='auto'` dispatches on this;
#                        'pallas' forces the kernel regardless (proof runs).


# --------------------------- blocked Floyd-Warshall ------------------------
#
# Block extractions: Mosaic has no dynamic_slice on register values, so row/
# column k of a VMEM tile is extracted with a masked min-reduce (inert +inf
# elsewhere) — static ops only, same O(T^2) order as the update itself.

def _tile_col(mat: jnp.ndarray, k) -> jnp.ndarray:
    ids = lax.broadcasted_iota(jnp.int32, mat.shape, 1)
    return jnp.min(jnp.where(ids == k, mat, jnp.inf), axis=1, keepdims=True)


def _tile_row(mat: jnp.ndarray, k) -> jnp.ndarray:
    ids = lax.broadcasted_iota(jnp.int32, mat.shape, 0)
    return jnp.min(jnp.where(ids == k, mat, jnp.inf), axis=0, keepdims=True)


def _fw_close(p: jnp.ndarray, t: int) -> jnp.ndarray:
    """Exact Floyd-Warshall closure of one (T, T) tile."""

    def body(k, d):
        return jnp.minimum(d, _tile_col(d, k) + _tile_row(d, k))

    return lax.fori_loop(0, t, body, p)


def _minplus_acc(a: jnp.ndarray, b: jnp.ndarray, c: jnp.ndarray, t: int):
    """min(c, a (+) b) on (T, T) tiles."""

    def body(k, acc):
        return jnp.minimum(acc, _tile_col(a, k) + _tile_row(b, k))

    return lax.fori_loop(0, t, body, c)


def _pivot_kernel(kk_ref, d_ref, o_ref, *, t: int):
    o_ref[0] = _fw_close(d_ref[0], t)


def _panel_kernel(kk_ref, p_ref, d_ref, o_ref, *, t: int, side: str):
    # j == kk would recompute the (already closed) pivot to the same value
    # (P (+) P = P); pass it through instead of burning the fori_loop
    @pl.when(pl.program_id(1) == kk_ref[0])
    def _passthrough():
        o_ref[0] = d_ref[0]

    @pl.when(pl.program_id(1) != kk_ref[0])
    def _update():
        p, blk = p_ref[0], d_ref[0]
        # closed pivot (+) panel == the FW panel update; P's zero diagonal
        # makes the min with the old block implicit
        if side == "row":
            o_ref[0] = _minplus_acc(p, blk, blk, t)
        else:
            o_ref[0] = _minplus_acc(blk, p, blk, t)


def _outer_kernel(kk_ref, a_ref, b_ref, d_ref, o_ref, *, t: int):
    # pivot row/column blocks are already final after the panel phase —
    # recomputing them yields identical values; skip the arithmetic
    kk = kk_ref[0]
    on_pivot = (pl.program_id(1) == kk) | (pl.program_id(2) == kk)

    @pl.when(on_pivot)
    def _passthrough():
        o_ref[0] = d_ref[0]

    @pl.when(jnp.logical_not(on_pivot))
    def _update():
        o_ref[0] = _minplus_acc(a_ref[0], b_ref[0], d_ref[0], t)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def blocked_fw_call(
    d: jnp.ndarray, tile: int = _LANE, interpret: bool = False
) -> jnp.ndarray:
    """Exact APSP of (B, N, N) distance matrices, N a multiple of `tile`.

    Requires zero diagonals and +inf for absent edges; symmetric or not.
    Each phase kernel writes only its blocks of the aliased output, the
    pivot index arrives by scalar prefetch, and the pivot loop is a single
    traced `fori_loop` — 4 Mosaic compiles total regardless of N.
    """
    b, n, _ = d.shape
    t = tile
    nb = n // t
    shape = jax.ShapeDtypeStruct(d.shape, d.dtype)

    pivot = pl.pallas_call(
        functools.partial(_pivot_kernel, t=t),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[pl.BlockSpec((1, t, t), lambda bi, kk: (bi, kk[0], kk[0]))],
            out_specs=pl.BlockSpec((1, t, t), lambda bi, kk: (bi, kk[0], kk[0])),
        ),
        out_shape=shape,
        input_output_aliases={1: 0},
        interpret=interpret,
    )

    def make_panel(side: str):
        blk_map = (
            (lambda bi, j, kk: (bi, kk[0], j)) if side == "row"
            else (lambda bi, j, kk: (bi, j, kk[0]))
        )
        return pl.pallas_call(
            functools.partial(_panel_kernel, t=t, side=side),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, nb),
                in_specs=[
                    pl.BlockSpec((1, t, t), lambda bi, j, kk: (bi, kk[0], kk[0])),
                    pl.BlockSpec((1, t, t), blk_map),
                ],
                out_specs=pl.BlockSpec((1, t, t), blk_map),
            ),
            out_shape=shape,
            input_output_aliases={2: 0},
            interpret=interpret,
        )

    row_panel, col_panel = make_panel("row"), make_panel("col")

    outer = pl.pallas_call(
        functools.partial(_outer_kernel, t=t),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nb, nb),
            in_specs=[
                pl.BlockSpec((1, t, t), lambda bi, i, j, kk: (bi, i, kk[0])),
                pl.BlockSpec((1, t, t), lambda bi, i, j, kk: (bi, kk[0], j)),
                pl.BlockSpec((1, t, t), lambda bi, i, j, kk: (bi, i, j)),
            ],
            out_specs=pl.BlockSpec((1, t, t), lambda bi, i, j, kk: (bi, i, j)),
        ),
        out_shape=shape,
        input_output_aliases={3: 0},
        interpret=interpret,
    )

    def step(kk, dist):
        kks = jnp.full((1,), kk, jnp.int32)
        dist = pivot(kks, dist)
        dist = row_panel(kks, dist, dist)
        dist = col_panel(kks, dist, dist)
        dist = outer(kks, dist, dist, dist)
        return dist

    return lax.fori_loop(0, nb, step, d)


def tpu_backend() -> bool:
    """Mosaic kernels only lower on TPU (incl. the tunneled 'axon' platform);
    elsewhere dispatchers (here and `ops.fixed_point`) must delegate to XLA
    unless interpreting."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # backend init failure: let the XLA path surface it
        return False


_tpu_backend = tpu_backend  # transitional alias


def pallas_apsp_path(n: int, interpret: bool = False) -> str:
    """Which implementation `apsp_minplus_pallas` actually runs for size n:
    'squaring' | 'blocked-fw' | 'xla-fallback'.  Lets callers (e.g.
    `scripts/large_scale_demo.py`) report the executed path honestly."""
    if not interpret and not tpu_backend():
        return "xla-fallback"
    n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
    if n_pad <= _MAX_SQUARING_N:
        return "squaring"
    if n_pad <= _MAX_BLOCKED_N:
        return "blocked-fw"
    return "xla-fallback"


def auto_apsp_path(n: int, interpret: bool = False) -> str:
    """Path `apsp_impl='auto'` takes for size n: the fastest MEASURED
    implementation on real hardware (`benchmarks/pallas_tpu.json`) — XLA
    below the `_AUTO_PALLAS_MIN_N` crossover, Pallas blocked-FW above."""
    n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
    if n_pad < _AUTO_PALLAS_MIN_N:
        return "xla"
    return pallas_apsp_path(n, interpret=interpret)


def apsp_minplus_auto(
    weights: jnp.ndarray,
    num_iters: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Measured-crossover dispatch: delegate to the XLA squaring below
    `_AUTO_PALLAS_MIN_N` (where it beats the kernels on-chip), to
    `apsp_minplus_pallas` above.  Re-resolves per call shape, so bucketed
    mixed-size datasets each get the fastest kernel."""
    if auto_apsp_path(weights.shape[-1], interpret=interpret) == "xla":
        from multihop_offload_tpu.env.apsp import apsp_minplus

        if weights.ndim == 2:
            return apsp_minplus(weights, num_iters)
        return jax.vmap(lambda m: apsp_minplus(m, num_iters))(weights)
    return apsp_minplus_pallas(weights, num_iters, interpret=interpret)


def resolve_apsp(impl: str, n: int, interpret: bool = False):
    """Resolve the config knob `apsp_impl` to an APSP callable.

    Returns ``(apsp_fn, path)``.  ``apsp_fn`` is None for the default XLA
    min-plus squaring (callers treat None as `env.apsp.apsp_minplus`).
    'auto' picks the fastest measured path per call shape
    (`benchmarks/pallas_tpu.json` round-5 re-ladder: XLA below padded
    N=256, chunked squaring at 256, blocked FW from 384);
    'pallas' forces `apsp_minplus_pallas`, which self-dispatches
    (squaring <= 256, blocked FW <= 2048, XLA beyond / off-TPU).  ``path``
    is the resolution REPORT for size ``n`` ('xla' | 'squaring' |
    'blocked-fw' | 'xla-fallback'); other bucket sizes may resolve
    differently.
    """
    if impl not in ("xla", "pallas", "auto"):
        raise ValueError(f"apsp_impl must be xla|pallas|auto, got '{impl}'")
    if impl == "xla":
        return None, "xla"
    if impl == "auto":
        path = auto_apsp_path(n, interpret=interpret)
        if path in ("xla", "xla-fallback"):
            # None is the sentinel for direct XLA execution; huge-N (or
            # off-TPU) 'auto' callers must not take the wrapper->pallas->
            # XLA-fallback indirection.
            return None, path
        return functools.partial(apsp_minplus_auto, interpret=interpret), path
    fn = functools.partial(apsp_minplus_pallas, interpret=interpret)
    return fn, pallas_apsp_path(n, interpret=interpret)


def apsp_minplus_pallas(
    weights: jnp.ndarray,
    num_iters: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in replacement for `env.apsp.apsp_minplus` (symmetric weights).

    Accepts (N, N) or batched (B, N, N); pads N up to the 128-lane width
    with +inf (inert) and zero-diagonals the input region.  Padded N <= 256
    runs the whole-matrix VMEM squaring; larger sizes run the blocked
    Floyd-Warshall; beyond `_MAX_BLOCKED_N` delegates to the XLA squaring
    (use `parallel.ring.sharded_apsp` across chips at that scale).
    """
    squeeze = weights.ndim == 2
    w = weights[None] if squeeze else weights
    b, n, _ = w.shape
    n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
    path = pallas_apsp_path(n, interpret=interpret)
    if path == "blocked-fw" and num_iters is not None:
        # the blocked FW always computes the full closure; an explicit
        # num_iters asks for hop-bounded squaring semantics — delegate
        path = "xla-fallback"
    if path == "xla-fallback":
        from multihop_offload_tpu.env.apsp import apsp_minplus

        out = jax.vmap(lambda m: apsp_minplus(m, num_iters))(w)
        return out[0] if squeeze else out

    eye = jnp.eye(n, dtype=bool)
    w = jnp.where(eye, jnp.zeros_like(w), w)
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n), (0, n_pad - n))
        w = jnp.pad(w, pad, constant_values=jnp.inf)
    if path == "squaring":
        iters = num_iters if num_iters is not None else max(
            1, math.ceil(math.log2(max(n - 1, 2)))
        )
        out = minplus_power_kernel_call(w, iters, interpret=interpret)
    else:
        out = blocked_fw_call(w, tile=_LANE, interpret=interpret)
    out = out[:, :n, :n]
    return out[0] if squeeze else out


# --------------------------- COO-fed squaring -------------------------------
#
# Third regime: `--layout sparse` keeps the graph as a padded link list, but
# until this kernel the APSP leg still scatter-built a dense (N, N) weight
# matrix in XLA and ran the dense squaring on it.  Here the dense matrix
# never exists in HBM: the kernel rebuilds W in registers from the (L,)
# edge list (two masked min-extracts + one symmetric iota hit-mask per
# edge, O(L*N^2) VPU work — small next to the squaring's O(N^3 log N)) and
# then runs the shared sublane-chunked squaring in place.  Every step is an
# exact fp min or the same fp adds as `env.apsp.apsp_minplus_blocked`, and
# min-plus squaring of a bitwise-symmetric matrix stays bitwise symmetric
# (a+b == b+a in IEEE), so the result is BIT-IDENTICAL to the scatter+XLA
# reference — the full ceil(log2) schedule lands on the same fixed point
# the reference's bitwise `nxt == cur` early-stop converges to.


def _coo_apsp_kernel(us_ref, vs_ref, d_ref, o_ref, *, n: int, l: int,
                     iters: int):
    u_row = us_ref[0]                            # (1, Lp) int32
    v_row = vs_ref[0]
    d = d_ref[0]                                 # (1, Lp), +inf on pads
    lane = lax.broadcasted_iota(jnp.int32, d.shape, 1)
    ii = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    jj = lax.broadcasted_iota(jnp.int32, (n, n), 1)
    big = jnp.iinfo(jnp.int32).max

    def edge_body(e, w):
        sel = lane == e                          # scalar extract via masked
        u = jnp.min(jnp.where(sel, u_row, big))  # min-reduce: no dynamic
        v = jnp.min(jnp.where(sel, v_row, big))  # slicing of register values
        de = jnp.min(jnp.where(sel, d, jnp.inf))
        hit = ((ii == u) & (jj == v)) | ((ii == v) & (jj == u))
        return jnp.minimum(w, jnp.where(hit, de, jnp.inf))

    w0 = jnp.where(ii == jj, 0.0, jnp.inf).astype(d.dtype)
    w = lax.fori_loop(0, l, edge_body, w0)
    o_ref[0] = _chunked_squaring(w, n, iters)


def coo_apsp_cost_facts(n: int, l: int, iters: int,
                        dtype_bytes: int = 4) -> dict:
    """Analytic cost facts for the COO-fed kernel (EXECUTED work: the edge
    walk is ~5 (N, N) VPU ops per link, the squaring ~2.25*N^3 per iter
    counting the chunked row extraction) — `obs.prof.register_kernel`
    feeds these to the MFU/HBM gauges, since Mosaic programs never pass
    through XLA cost analysis."""
    flops = 5.0 * l * n * n + iters * 2.25 * n ** 3
    bytes_accessed = float(2 * l * 4 + l * dtype_bytes
                           + n * n * dtype_bytes)
    return {"flops": flops, "bytes_accessed": bytes_accessed,
            "argument_bytes": float(2 * l * 4 + l * dtype_bytes)}


_COO_REGISTERED: set = set()


def _register_coo(n: int, l: int, iters: int, dtype_bytes: int) -> None:
    key = (n, l, iters, dtype_bytes)
    if key in _COO_REGISTERED:
        return
    _COO_REGISTERED.add(key)
    from multihop_offload_tpu.obs.prof import register_kernel

    register_kernel(
        "ops/coo_apsp", **coo_apsp_cost_facts(n, l, iters, dtype_bytes),
        labels={"kind": "pallas", "shape": f"n{n}_l{l}"})


def coo_apsp_path(n: int, interpret: bool = False) -> str:
    """Which implementation `apsp_minplus_coo` actually runs for node count
    n: 'coo-squaring' | 'blocked-fw' | 'xla-fallback'.  Same honesty
    contract as `pallas_apsp_path`; 'blocked-fw' means the dense weight
    matrix is scatter-built on device and handed to the blocked-FW kernel
    (the in-register rebuild only fits whole-matrix VMEM sizes)."""
    if not interpret and not tpu_backend():
        return "xla-fallback"
    n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
    if n_pad <= _MAX_SQUARING_N:
        return "coo-squaring"
    if n_pad <= _MAX_BLOCKED_N:
        return "blocked-fw"
    return "xla-fallback"


def apsp_minplus_coo(
    link_ends: jnp.ndarray,
    link_mask: jnp.ndarray,
    link_delays: jnp.ndarray,
    num_nodes: int,
    num_iters: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """APSP fed straight from the padded COO link list.

    Drop-in for the sparse layout's scatter+dense chain
    (`layouts.sparse.weight_matrix_from_edges` -> `env.apsp.
    apsp_minplus_blocked`) and BIT-IDENTICAL to it: masked links carry
    +inf (inert under min), the in-register W build does the same exact
    min-scatter, and the squaring schedule lands on the reference's
    early-stop fixed point.  Unbatched (L, 2)/(L,) inputs only — batch via
    `jax.vmap` (the Pallas batching rule turns it into a grid axis)."""
    path = coo_apsp_path(num_nodes, interpret=interpret)
    delays = jnp.where(link_mask, link_delays,
                       jnp.asarray(jnp.inf, link_delays.dtype))
    if path != "coo-squaring":
        from multihop_offload_tpu.layouts.sparse import (
            weight_matrix_from_edges,
        )

        w = weight_matrix_from_edges(link_ends, link_mask, link_delays,
                                     num_nodes)
        if path == "blocked-fw":
            return apsp_minplus_pallas(w, num_iters, interpret=interpret)
        from multihop_offload_tpu.env.apsp import apsp_minplus_blocked

        return apsp_minplus_blocked(w, num_iters=num_iters)

    n = num_nodes
    (l, _) = link_ends.shape
    n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
    l_pad = max(_LANE, math.ceil(l / _LANE) * _LANE)
    iters = num_iters if num_iters is not None else max(
        1, math.ceil(math.log2(max(n - 1, 2)))
    )
    _register_coo(n_pad, l_pad, iters, delays.dtype.itemsize)

    us = jnp.zeros((1, 1, l_pad), jnp.int32).at[0, 0, :l].set(
        link_ends[:, 0].astype(jnp.int32))
    vs = jnp.zeros((1, 1, l_pad), jnp.int32).at[0, 0, :l].set(
        link_ends[:, 1].astype(jnp.int32))
    d = jnp.full((1, 1, l_pad), jnp.inf, delays.dtype).at[0, 0, :l].set(
        delays)

    kernel = functools.partial(_coo_apsp_kernel, n=n_pad, l=l, iters=iters)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, n_pad, n_pad), delays.dtype),
        grid=(1,),
        in_specs=[
            pl.BlockSpec((1, 1, l_pad), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, l_pad), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, l_pad), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(us, vs, d)
    return out[0, :n, :n]


def resolve_coo_apsp(impl: str, n: int, interpret: bool = False):
    """Resolve the config knob `apsp_impl` to a COO-fed APSP callable for
    the sparse layout.

    Returns ``(edges_fn, path)``.  ``edges_fn`` is None for the default
    scatter+XLA chain (callers treat None as `weight_matrix_from_edges` +
    `env.apsp.apsp_minplus_blocked`) and otherwise a drop-in
    ``(link_ends, link_mask, link_delays, num_nodes) -> (N, N)`` running
    `apsp_minplus_coo`.  'auto' follows the same measured
    `_AUTO_PALLAS_MIN_N` crossover as `resolve_apsp` — the COO build feeds
    the identical squaring kernel, so the dense-fed ladder
    (`benchmarks/pallas_tpu.json`) is the evidence that transfers; the
    in-step COO gate lives in `benchmarks/bench_matrix.json`
    (`coo_apsp_perf`)."""
    if impl not in ("xla", "pallas", "auto"):
        raise ValueError(f"apsp_impl must be xla|pallas|auto, got '{impl}'")
    if impl == "xla":
        return None, "xla"

    def fn(link_ends, link_mask, link_delays, num_nodes):
        return apsp_minplus_coo(link_ends, link_mask, link_delays,
                                num_nodes, interpret=interpret)

    if impl == "auto":
        n_pad = max(_LANE, math.ceil(n / _LANE) * _LANE)
        if n_pad < _AUTO_PALLAS_MIN_N:
            return None, "xla"
        path = coo_apsp_path(n, interpret=interpret)
        if path == "xla-fallback":
            return None, path
        return fn, path
    return fn, coo_apsp_path(n, interpret=interpret)

"""Sparse (COO) graph propagation via gather + segment-sum.

Dense (E, E) supports are right for the paper-scale graphs (a few hundred
extended slots -> MXU tiles, `models.chebconv`), but at beyond-paper scale
(BASELINE.json config 5) the dense support dominates memory and host->device
transfer: an 8,500-slot extended line graph is a ~290 MB float32 matrix with
~0.2% nonzeros.  This module provides the fixed-shape sparse alternative:
edges as padded (row, col, val) COO triples, propagation as
`segment_sum(vals * x[cols], rows)` — XLA lowers the gather/scatter-add pair
efficiently on TPU, and every op is static-shape (`nnz` is padded, padding
rows point at slot 0 with value 0).

`coo_propagate` plugs into `ChebConv.propagate`, so the same Flax parameters
drive dense, mesh-sharded (`parallel.partition`), or sparse propagation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class COO:
    """Padded COO matrix; padding entries have val == 0 and row = col = 0."""

    rows: jnp.ndarray   # (nnz_pad,) int32
    cols: jnp.ndarray   # (nnz_pad,) int32
    vals: jnp.ndarray   # (nnz_pad,) float
    shape: tuple = struct.field(pytree_node=False)  # static logical (n, n)


def dense_to_coo(mat: np.ndarray, nnz_pad: int | None = None, round_to: int = 128) -> COO:
    """Host-side conversion with padding to a static nonzero count."""
    mat = np.asarray(mat)
    r, c = np.nonzero(mat)
    v = mat[r, c]
    nnz = r.size
    if nnz_pad is None:
        nnz_pad = max(round_to, int(-(-nnz // round_to) * round_to))
    if nnz > nnz_pad:
        raise ValueError(f"{nnz} nonzeros exceed pad {nnz_pad}")
    rows = np.zeros(nnz_pad, np.int32)
    cols = np.zeros(nnz_pad, np.int32)
    vals = np.zeros(nnz_pad, mat.dtype)
    rows[:nnz], cols[:nnz], vals[:nnz] = r, c, v
    return COO(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals), mat.shape)


def coo_matmul(coo: COO, x: jnp.ndarray) -> jnp.ndarray:
    """(n, n) sparse @ (n, F) dense -> (n, F): one gather + one segment-sum."""
    contrib = coo.vals[:, None] * x[coo.cols]            # (nnz, F)
    return jax.ops.segment_sum(contrib, coo.rows, num_segments=coo.shape[0])


def coo_propagate(support, x: jnp.ndarray) -> jnp.ndarray:
    """`ChebConv.propagate`-compatible: `support` is a COO pytree."""
    return coo_matmul(support, x)

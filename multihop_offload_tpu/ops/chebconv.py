"""Fused Pallas ChebConv propagate: gather -> segment-sum in one kernel.

The sparse layout's Chebyshev recurrence (`layouts.sparse.make_sparse_propagate`)
lowers to XLA as a gather of x rows followed by a serialized `segment_sum`
scatter — the exact shape "Fast Training of Sparse GNNs on Dense Hardware"
(PAPERS.md) identifies as leaving dense-hardware throughput on the table.
This module fuses the two into one edge-tiled kernel:

- the grid walks edge blocks; each block builds a (N, Eb) one-hot gather
  matrix from the block's `cols` and pulls `x[cols]` out of VMEM with a
  single MXU matmul (`one_hot(cols).T @ x` is exact — one-hot rows select,
  they never mix values);
- the segment-sum is a second matmul against the scatter one-hot with the
  edge weights folded in (`where(node == rows, vals, 0) @ gathered`),
  accumulated in the >= fp32 island dtype directly in the revisited output
  block — registers/VMEM across the whole edge walk, ONE HBM write per
  node tile when the grid retires;
- block 0 seeds the accumulator with the diagonal term `diag[:, None] * x`.

fp32 adds reassociate, so unlike the COO min-plus APSP (exact min) the fused
tile is NOT bit-identical to `segment_sum`; tests pin values/grads to the
layouts/ 4.5e-7 bar and decisions bit-identical.  The `custom_vjp` recomputes
the backward through the exact `make_sparse_propagate` math, so the trained
path keeps the step-form critic gradient (`agent.train_step`) unchanged.

Honesty contract matches `minplus.pallas_apsp_path`: `chebconv_path`
reports the executed implementation, off-TPU non-interpret delegates to the
XLA reference, and `resolve_chebconv('auto')` stays on XLA until
`benchmarks/bench_matrix.json` carries an on-chip `chebconv_perf` win —
the same stop-at-measured-evidence rule as `fixed_point._AUTO_FP_MAX_L`.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multihop_offload_tpu.ops.minplus import tpu_backend
from multihop_offload_tpu.precision import island_dtype

_LANE = 128      # f32 lane tile (last dim)
_SUBLANE = 8     # f32 sublane tile (second-to-last dim)
_EDGE_BLOCK = 512  # edges walked per grid step (VMEM one-hot: N x 512)

# shapes whose analytic cost facts are already registered (per process) —
# registration happens at trace time, once per distinct kernel shape
_REGISTERED: set = set()


def _xla_propagate(rows, cols, vals, diag, x, acc):
    """The one true reference: `layouts.sparse.make_sparse_propagate` math,
    inlined to avoid an ops<->layouts import cycle.  The VJP recompute must
    pull back through exactly what the rest of the framework runs."""
    contrib = (vals[:, None] * x[cols]).astype(acc)
    agg = jax.ops.segment_sum(contrib, rows, num_segments=x.shape[0])
    agg = agg + diag.astype(acc)[:, None] * x.astype(acc)
    return agg.astype(x.dtype)


def _chebconv_kernel(rows_ref, cols_ref, vals_ref, diag_ref, x_ref, o_ref):
    x = x_ref[...]                       # (N, F) acc dtype
    n = x.shape[0]

    @pl.when(pl.program_id(0) == 0)
    def _seed_diag():
        o_ref[...] = diag_ref[...] * x   # (N, 1) * (N, F)

    rows = rows_ref[...]                 # (1, Eb) int32
    cols = cols_ref[...]
    vals = vals_ref[...]                 # (1, Eb) acc dtype
    node = jax.lax.broadcasted_iota(jnp.int32, (n, rows.shape[1]), 0)
    gather = (node == cols).astype(x.dtype)          # one-hot per edge col
    gathered = jax.lax.dot_general(                  # (Eb, F) == x[cols]
        gather, x, (((0,), (0,)), ((), ())),
        preferred_element_type=x.dtype)
    scatter = jnp.where(node == rows, vals, 0).astype(x.dtype)
    o_ref[...] += jax.lax.dot_general(               # fused segment-sum
        scatter, gathered, (((1,), (0,)), ((), ())),
        preferred_element_type=x.dtype)


def chebconv_cost_facts(n: int, nnz: int, feat: int,
                        dtype_bytes: int = 4) -> dict:
    """Analytic cost facts for the fused tile (EXECUTED work — the one-hot
    formulation runs two (N, Eb) x (Eb, F)-class matmuls per block, which is
    what the MXU actually retires and what an honest MFU divides by)."""
    flops = 4.0 * n * nnz * feat + 2.0 * n * feat   # 2 matmuls + diag seed
    bytes_accessed = (
        2 * nnz * 4                   # rows + cols (int32)
        + nnz * dtype_bytes           # vals
        + n * dtype_bytes             # diag
        + 2 * n * feat * dtype_bytes  # x in + one out write per node tile
    )
    return {"flops": flops, "bytes_accessed": float(bytes_accessed),
            "argument_bytes": float(bytes_accessed - n * feat * dtype_bytes)}


def _register(n: int, nnz: int, feat: int, dtype_bytes: int) -> None:
    key = (n, nnz, feat, dtype_bytes)
    if key in _REGISTERED:
        return
    _REGISTERED.add(key)
    from multihop_offload_tpu.obs.prof import register_kernel

    register_kernel(
        "ops/chebconv", **chebconv_cost_facts(n, nnz, feat, dtype_bytes),
        labels={"kind": "pallas", "shape": f"n{n}_nnz{nnz}_f{feat}"})


def _pad_to(v: int, m: int) -> int:
    return max(m, math.ceil(v / m) * m)


def _forward(rows, cols, vals, diag, x, acc_name, interpret, edge_block):
    acc = jnp.dtype(acc_name)
    if not interpret and not tpu_backend():
        # honesty contract: off-TPU the Mosaic kernel cannot lower; run the
        # reference (chebconv_path reports 'xla-fallback')
        return _xla_propagate(rows, cols, vals, diag, x, acc)

    n, f = x.shape
    (e,) = rows.shape
    n_pad = _pad_to(n, _SUBLANE)
    f_pad = _pad_to(f, _LANE)
    eb = min(edge_block, _pad_to(e, _LANE))
    e_pad = _pad_to(e, eb)
    _register(n_pad, e_pad, f_pad, acc.itemsize)

    # pad edges with (row=0, col=0, val=0): inert — the scatter one-hot
    # column is all zero, so the pad contributes exact +0.0 to row 0,
    # matching the sparse layout's own nnz padding convention
    rows_p = jnp.zeros((1, e_pad), jnp.int32).at[0, :e].set(rows)
    cols_p = jnp.zeros((1, e_pad), jnp.int32).at[0, :e].set(cols)
    vals_p = jnp.zeros((1, e_pad), acc).at[0, :e].set(vals.astype(acc))
    diag_p = jnp.zeros((n_pad, 1), acc).at[:n, 0].set(diag.astype(acc))
    x_p = jnp.zeros((n_pad, f_pad), acc).at[:n, :f].set(x.astype(acc))

    out = pl.pallas_call(
        _chebconv_kernel,
        grid=(e_pad // eb,),
        in_specs=[
            pl.BlockSpec((1, eb), lambda i: (0, i)),      # rows
            pl.BlockSpec((1, eb), lambda i: (0, i)),      # cols
            pl.BlockSpec((1, eb), lambda i: (0, i)),      # vals
            pl.BlockSpec((n_pad, 1), lambda i: (0, 0)),   # diag
            pl.BlockSpec((n_pad, f_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((n_pad, f_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), acc),
        interpret=interpret,
    )(rows_p, cols_p, vals_p, diag_p, x_p)
    return out[:n, :f].astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def chebconv_propagate_pallas(rows, cols, vals, diag, x,
                              acc_name: str = "float32",
                              interpret: bool = False,
                              edge_block: int = _EDGE_BLOCK):
    """Fused gather->segment-sum ChebConv propagate (custom_vjp primal).

    Args are the flattened `SparseSupport` (`rows`/`cols`/`vals` the padded
    COO, `diag` the (N,) diagonal) plus the (N, F) node features.  The
    static tail (`acc_name`/`interpret`/`edge_block`) is nondiff; the
    backward recomputes through `_xla_propagate`, so gradients are exactly
    the reference chain's (step-form critic included) regardless of which
    forward executed."""
    return _forward(rows, cols, vals, diag, x, acc_name, interpret,
                    edge_block)


def _cheb_fwd(rows, cols, vals, diag, x, acc_name, interpret, edge_block):
    out = chebconv_propagate_pallas(rows, cols, vals, diag, x, acc_name,
                                    interpret, edge_block)
    return out, (rows, cols, vals, diag, x)


def _cheb_bwd(acc_name, interpret, edge_block, res, g):
    rows, cols, vals, diag, x = res
    _, vjp = jax.vjp(
        functools.partial(_xla_propagate, acc=jnp.dtype(acc_name)),
        rows, cols, vals, diag, x)
    return vjp(g)  # float0 cotangents for the int rows/cols


chebconv_propagate_pallas.defvjp(_cheb_fwd, _cheb_bwd)


def make_fused_propagate(accum_dtype=None, *, interpret: bool = False,
                         edge_block: int = _EDGE_BLOCK):
    """Drop-in twin of `layouts.sparse.make_sparse_propagate` running the
    fused Pallas tile: `propagate(support, x)` with the same accumulation
    contract (>= fp32 island unless `accum_dtype` pins it)."""

    def propagate(support, x):
        e = support.edges
        acc = jnp.dtype(accum_dtype or island_dtype(x.dtype))
        return chebconv_propagate_pallas(
            e.rows, e.cols, e.vals, support.diag, x, acc.name, interpret,
            edge_block)

    return propagate


# ---- ragged edge count: occupancy-aware serving ---------------------------
#
# A serving bucket at low occupancy packs far fewer live edges than its
# static nnz pad; the dense tile above still walks every padded block.  The
# ragged variant takes the LIVE edge count as a scalar-prefetch argument
# (available before the kernel body runs — `pltpu.PrefetchScalarGridSpec`),
# and skips every edge block past it.  The contract is the sparse layout's
# own padding convention: edges at index >= nnz_live MUST be inert
# (row=0, col=0, val=0), so a skipped block contributes exactly the +0.0 a
# full walk would have — at any live count the ragged kernel's output is
# BIT-IDENTICAL to itself walking the whole capacity (tests pin this).
# Against the dense tile / XLA reference it carries the fused tile's
# existing bar: values at the layouts scaled tolerance, decisions
# bit-parity gated.  Off-TPU (non-interpret) the same honesty contract as
# the dense tile holds: delegate to the masked XLA reference, which IS
# bitwise the reference.


def _chebconv_ragged_kernel(live_ref, rows_ref, cols_ref, vals_ref, diag_ref,
                            x_ref, o_ref):
    x = x_ref[...]                       # (N, F) acc dtype
    n = x.shape[0]
    eb = rows_ref.shape[1]

    @pl.when(pl.program_id(0) == 0)
    def _seed_diag():
        o_ref[...] = diag_ref[...] * x   # (N, 1) * (N, F)

    @pl.when(pl.program_id(0) * eb < live_ref[0])
    def _edge_block():
        # identical math to the dense kernel; a block whose first edge is
        # past the live count is all-inert and skipped outright
        rows = rows_ref[...]             # (1, Eb) int32
        cols = cols_ref[...]
        vals = vals_ref[...]             # (1, Eb) acc dtype
        node = jax.lax.broadcasted_iota(jnp.int32, (n, rows.shape[1]), 0)
        gather = (node == cols).astype(x.dtype)
        gathered = jax.lax.dot_general(
            gather, x, (((0,), (0,)), ((), ())),
            preferred_element_type=x.dtype)
        scatter = jnp.where(node == rows, vals, 0).astype(x.dtype)
        o_ref[...] += jax.lax.dot_general(
            scatter, gathered, (((1,), (0,)), ((), ())),
            preferred_element_type=x.dtype)


def chebconv_ragged_cost_facts(n: int, nnz_live: int, nnz_cap: int,
                               feat: int, dtype_bytes: int = 4,
                               edge_block: int = _EDGE_BLOCK) -> dict:
    """Analytic EXECUTED cost of one ragged call: only `ceil(live / Eb)`
    edge blocks run their two matmuls, so flops/bytes scale with occupancy
    instead of the static pad.  `nnz_cap` is the padded capacity the dense
    tile would have walked — the CPU-proxy cost-reduction gate in the bench
    matrix divides the dense facts by these."""
    eb = min(edge_block, _pad_to(max(nnz_cap, 1), _LANE))
    blocks = math.ceil(max(int(nnz_live), 1) / eb)
    nnz_run = blocks * eb
    flops = 4.0 * n * nnz_run * feat + 2.0 * n * feat
    bytes_accessed = (
        2 * nnz_run * 4
        + nnz_run * dtype_bytes
        + n * dtype_bytes
        + 2 * n * feat * dtype_bytes
    )
    return {"flops": flops, "bytes_accessed": float(bytes_accessed),
            "argument_bytes": float(bytes_accessed - n * feat * dtype_bytes)}


def _register_ragged(n: int, nnz_cap: int, feat: int, dtype_bytes: int) -> None:
    key = ("ragged", n, nnz_cap, feat, dtype_bytes)
    if key in _REGISTERED:
        return
    _REGISTERED.add(key)
    from multihop_offload_tpu.obs.prof import register_kernel

    # registered at CAPACITY (the static shape jit sees); the per-call
    # executed work is occupancy-dependent — chebconv_ragged_cost_facts is
    # the analytic scaler consumers apply
    register_kernel(
        "ops/chebconv_ragged",
        **chebconv_cost_facts(n, nnz_cap, feat, dtype_bytes),
        labels={"kind": "pallas-ragged", "shape": f"n{n}_cap{nnz_cap}_f{feat}"})


def _forward_ragged(rows, cols, vals, diag, x, nnz_live, acc_name, interpret,
                    edge_block):
    acc = jnp.dtype(acc_name)
    if not interpret and not tpu_backend():
        # honesty contract: off-TPU run the masked XLA reference — the inert
        # tail (vals == 0 past nnz_live) makes it bit-identical to the skip
        return _xla_propagate(rows, cols, vals, diag, x, acc)

    n, f = x.shape
    (e,) = rows.shape
    n_pad = _pad_to(n, _SUBLANE)
    f_pad = _pad_to(f, _LANE)
    eb = min(edge_block, _pad_to(e, _LANE))
    e_pad = _pad_to(e, eb)
    _register_ragged(n_pad, e_pad, f_pad, acc.itemsize)

    rows_p = jnp.zeros((1, e_pad), jnp.int32).at[0, :e].set(rows)
    cols_p = jnp.zeros((1, e_pad), jnp.int32).at[0, :e].set(cols)
    vals_p = jnp.zeros((1, e_pad), acc).at[0, :e].set(vals.astype(acc))
    diag_p = jnp.zeros((n_pad, 1), acc).at[:n, 0].set(diag.astype(acc))
    x_p = jnp.zeros((n_pad, f_pad), acc).at[:n, :f].set(x.astype(acc))
    live = jnp.asarray(nnz_live, jnp.int32).reshape((1,))

    out = pl.pallas_call(
        _chebconv_ragged_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(e_pad // eb,),
            in_specs=[
                pl.BlockSpec((1, eb), lambda i, live: (0, i)),      # rows
                pl.BlockSpec((1, eb), lambda i, live: (0, i)),      # cols
                pl.BlockSpec((1, eb), lambda i, live: (0, i)),      # vals
                pl.BlockSpec((n_pad, 1), lambda i, live: (0, 0)),   # diag
                pl.BlockSpec((n_pad, f_pad), lambda i, live: (0, 0)),
            ],
            out_specs=pl.BlockSpec((n_pad, f_pad), lambda i, live: (0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), acc),
        interpret=interpret,
    )(live, rows_p, cols_p, vals_p, diag_p, x_p)
    return out[:n, :f].astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def chebconv_propagate_ragged(rows, cols, vals, diag, x, nnz_live,
                              acc_name: str = "float32",
                              interpret: bool = False,
                              edge_block: int = _EDGE_BLOCK):
    """Ragged-occupancy fused ChebConv propagate (custom_vjp primal).

    Same arguments as `chebconv_propagate_pallas` plus `nnz_live` — the
    LIVE edge count (int32 scalar, may be traced: one compiled program
    serves every occupancy).  Edges past `nnz_live` must be inert padding
    (row=0, col=0, val=0); given that, the output at any live count is
    bit-identical to the same kernel walking the full capacity.  The
    backward recomputes through `_xla_propagate` exactly like the dense
    tile's."""
    return _forward_ragged(rows, cols, vals, diag, x, nnz_live, acc_name,
                           interpret, edge_block)


def _cheb_ragged_fwd(rows, cols, vals, diag, x, nnz_live, acc_name, interpret,
                     edge_block):
    out = chebconv_propagate_ragged(rows, cols, vals, diag, x, nnz_live,
                                    acc_name, interpret, edge_block)
    return out, (rows, cols, vals, diag, x, nnz_live)


def _cheb_ragged_bwd(acc_name, interpret, edge_block, res, g):
    rows, cols, vals, diag, x, nnz_live = res
    _, vjp = jax.vjp(
        functools.partial(_xla_propagate, acc=jnp.dtype(acc_name)),
        rows, cols, vals, diag, x)
    # the live count is integer data, never differentiated: float0, exactly
    # what jax.vjp hands back for the int rows/cols
    zero_live = np.zeros(np.shape(nnz_live), jax.dtypes.float0)
    return (*vjp(g), zero_live)


chebconv_propagate_ragged.defvjp(_cheb_ragged_fwd, _cheb_ragged_bwd)


def make_fused_propagate_ragged(accum_dtype=None, *, interpret: bool = False,
                                edge_block: int = _EDGE_BLOCK):
    """Ragged twin of `make_fused_propagate`: `propagate(support, x,
    nnz_live)` skips edge blocks past the live count (serving buckets pass
    their packed batch's real edge count; the static nnz pad stays the
    compiled shape)."""

    def propagate(support, x, nnz_live):
        e = support.edges
        acc = jnp.dtype(accum_dtype or island_dtype(x.dtype))
        return chebconv_propagate_ragged(
            e.rows, e.cols, e.vals, support.diag, x, nnz_live, acc.name,
            interpret, edge_block)

    return propagate


def chebconv_ragged_path(interpret: bool = False) -> str:
    """Which implementation `chebconv_propagate_ragged` actually runs:
    'pallas' | 'xla-fallback' — the dense tile's honesty contract verbatim
    (off-TPU the masked XLA reference serves, and callers must report it)."""
    return chebconv_path(interpret)


def chebconv_path(interpret: bool = False) -> str:
    """Which implementation `chebconv_propagate_pallas` actually runs:
    'pallas' | 'xla-fallback' — same honesty contract as
    `minplus.pallas_apsp_path` (callers report the executed path)."""
    if interpret:
        return "pallas"
    return "pallas" if tpu_backend() else "xla-fallback"


def resolve_chebconv(impl: str, interpret: bool = False):
    """Resolve the `cheb_impl` knob to a propagate factory.

    Mirrors `minplus.resolve_apsp`: returns ``(make_propagate, path)`` where
    ``make_propagate`` is None for the default XLA segment-sum (callers
    treat None as `layouts.sparse.make_sparse_propagate`) and otherwise a
    ``make_fused_propagate``-shaped factory.  'auto' resolves to XLA
    everywhere until `benchmarks/bench_matrix.json` records an on-chip
    `chebconv_perf` gate win — the fused tile has no measured in-step
    evidence yet, and 'auto' stops at measured evidence (the
    `_AUTO_FP_MAX_L` rule)."""
    if impl not in ("xla", "pallas", "auto"):
        raise ValueError(f"cheb_impl must be xla|pallas|auto, got '{impl}'")
    if impl in ("xla", "auto"):
        return None, "xla"

    def factory(accum_dtype=None):
        return make_fused_propagate(accum_dtype, interpret=interpret)

    return factory, chebconv_path(interpret=interpret)

"""Pallas TPU kernel for the conflict-interference fixed point.

The queueing model's inner loop (`offloading_v3.py:500-506`, reimplemented in
`env.queueing.interference_fixed_point`) iterates 10 rounds of

    busy = clip(lambda / mu, 0, 1);  mu = rate / (1 + A_conflict @ busy)

XLA re-reads the (L, L) conflict adjacency from HBM every round.  This kernel
pins the adjacency block and all per-link vectors in VMEM for the whole
fixed point: one HBM read of A total, ten on-chip matvecs (the adjacency is
symmetric, so `A @ busy` is the row-vector product `busy @ A` — MXU work with
no transposes).

Differentiability: the actor and critic reverse-differentiate through the
unrolled iterations (`gnn_offloading_agent.py:240-244,348-352`).  Pallas
kernels carry no AD rules, so `fixed_point_pallas` wears a `custom_vjp`
whose backward recomputes the scan in XLA and pulls back through it —
forward stays in VMEM, gradients stay exact.

Grid = batch; one program per (L, L) conflict matrix, L padded to the
128-lane width (padding: rate 1, cf_deg 0, lambda 0, zero adjacency rows —
inert: busy=0, mu=1).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128


def _fp_kernel(adj_ref, rates_ref, cf_ref, lam_ref, mu_ref, *, iters: int):
    adj = adj_ref[0]          # (L, L)
    rates = rates_ref[0]      # (1, L)
    cf = cf_ref[0]
    lam = lam_ref[0]
    mu0 = rates / (cf + 1.0)

    def body(_, mu):
        busy = jnp.clip(lam / mu, 0.0, 1.0)
        neighbor = jnp.dot(busy, adj)       # == adj @ busy (A symmetric)
        return rates / (1.0 + neighbor)

    mu_ref[0] = lax.fori_loop(0, iters, body, mu0)


def _pallas_call(adj, rates, cf, lam, iters: int, interpret: bool):
    b, l, _ = adj.shape
    kernel = functools.partial(_fp_kernel, iters=iters)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, l), adj.dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, l, l), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, l), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, l), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, l), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, l), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(adj, rates, cf, lam)


def fixed_point_path(interpret: bool = False) -> str:
    """Which implementation `fixed_point_pallas` actually runs:
    'pallas' | 'xla-fallback' — same honesty contract as
    `minplus.pallas_apsp_path` (callers report the executed path)."""
    if interpret:
        return "pallas"
    from multihop_offload_tpu.ops.minplus import tpu_backend

    return "pallas" if tpu_backend() else "xla-fallback"


# Measured crossover, round-5 evidence set: IN-STEP (the authoritative
# signal — `benchmarks/fp_ab.json`, 200-rep idle-host legs) the kernel wins
# 1.16x at the production padded L=256, and that is the LAST rung with an
# in-step A/B.  The L=384/512 in-step rungs are now campaign legs of the
# matrix runner (`mho-bench --matrix`, gates `fp_rung_384`/`fp_rung_512`
# in `benchmarks/bench_matrix.json` — one chip session runs the whole
# knob cross-product); as of this writing both gates are null — awaiting
# a chip run — and the only 384/512 evidence remains the isolated
# microbench ladder (`pallas_tpu.json` l384/l512: 0.94/1.13x) sitting on
# the tunnel's ~4ms dispatch floor, where the 384 rung is an outright
# loss.  'auto' therefore stops at the measured win (256) rather than
# extrapolating the microbench trend; raise this only when the
# bench_matrix.json rung gate for the shape shows an in-step
# pallas-over-xla > 1.  `fp_impl=pallas` remains the explicit override
# for larger pads.
_AUTO_FP_MAX_L = 256


def auto_fp_path(l: int, interpret: bool = False) -> str:
    """Path `fp_impl='auto'` takes for padded link count l: 'pallas' where the
    kernel's on-chip win is measured, 'xla' elsewhere (incl. off-TPU)."""
    l_pad = max(_LANE, math.ceil(l / _LANE) * _LANE)
    if l_pad > _AUTO_FP_MAX_L:
        return "xla"
    return fixed_point_path(interpret=interpret)


def resolve_fixed_point(impl: str, l: int, interpret: bool = False):
    """Resolve the config knob `fp_impl` to a fixed-point callable.

    Mirrors `minplus.resolve_apsp`: returns ``(fp_fn, path)`` where ``fp_fn``
    is None for the default XLA scan (callers treat None as
    `env.queueing.interference_fixed_point_raw`) and otherwise a drop-in
    ``(adj, rates, cf, lam, num_iters) -> mu`` running the Pallas kernel.
    ``path`` reports the resolution for padded link count ``l``
    ('xla' | 'pallas' | 'xla-fallback').
    """
    if impl not in ("xla", "pallas", "auto"):
        raise ValueError(f"fp_impl must be xla|pallas|auto, got '{impl}'")
    if impl == "xla":
        return None, "xla"

    def fn(adj, rates, cf, lam, num_iters=10):
        return fixed_point_pallas(adj, rates, cf, lam, num_iters, interpret)

    if impl == "auto":
        path = auto_fp_path(l, interpret=interpret)
        if path in ("xla", "xla-fallback"):
            # None sentinel = direct XLA execution, no wrapper indirection
            return None, path
        return fn, path
    return fn, fixed_point_path(interpret=interpret)


def _xla_reference(adj, rates, cf, lam, num_iters):
    # the one true update lives in env.queueing; the VJP recompute must pull
    # back through exactly the math the rest of the framework runs
    from multihop_offload_tpu.env.queueing import interference_fixed_point_raw

    return interference_fixed_point_raw(adj, rates, cf, lam, num_iters)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fixed_point_pallas(
    adj_conflict: jnp.ndarray,
    link_rates: jnp.ndarray,
    cf_degs: jnp.ndarray,
    link_lambda: jnp.ndarray,
    num_iters: int = 10,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in `interference_fixed_point` core: (L, L), (L,), (L,), (L,) ->
    converged mu (L,).  Also accepts a leading batch axis on every operand.
    Off-TPU (and not interpreting) it delegates to the XLA reference — same
    dispatch contract as `minplus.apsp_minplus_pallas`."""
    if not interpret:
        from multihop_offload_tpu.ops.minplus import _tpu_backend

        if not _tpu_backend():
            return _xla_reference(adj_conflict, link_rates, cf_degs,
                                  link_lambda, num_iters)
    squeeze = adj_conflict.ndim == 2
    adj = adj_conflict[None] if squeeze else adj_conflict
    vecs = [x[None] if squeeze else x for x in (link_rates, cf_degs, link_lambda)]
    b, l, _ = adj.shape
    l_pad = max(_LANE, math.ceil(l / _LANE) * _LANE)
    if l_pad != l:
        adj = jnp.pad(adj, ((0, 0), (0, l_pad - l), (0, l_pad - l)))
        rates = jnp.pad(vecs[0], ((0, 0), (0, l_pad - l)), constant_values=1.0)
        cf = jnp.pad(vecs[1], ((0, 0), (0, l_pad - l)))
        lam = jnp.pad(vecs[2], ((0, 0), (0, l_pad - l)))
    else:
        rates, cf, lam = vecs
    mu = _pallas_call(
        adj, rates[:, None, :], cf[:, None, :], lam[:, None, :],
        num_iters, interpret,
    )[:, 0, :l]
    return mu[0] if squeeze else mu


def _fp_fwd(adj, rates, cf, lam, num_iters, interpret):
    mu = fixed_point_pallas(adj, rates, cf, lam, num_iters, interpret)
    return mu, (adj, rates, cf, lam)


def _fp_bwd(num_iters, interpret, res, g):
    adj, rates, cf, lam = res
    # recompute-and-pull-back through the XLA scan: exact, and the forward
    # already paid only one HBM pass
    _, vjp = jax.vjp(
        functools.partial(_xla_reference, num_iters=num_iters),
        adj, rates, cf, lam,
    )
    return vjp(g)


fixed_point_pallas.defvjp(_fp_fwd, _fp_bwd)

from multihop_offload_tpu.ops.minplus import (  # noqa: F401
    apsp_minplus_coo,
    apsp_minplus_pallas,
    coo_apsp_path,
    minplus_power_kernel_call,
    resolve_apsp,
    resolve_coo_apsp,
)
from multihop_offload_tpu.ops.fixed_point import fixed_point_pallas  # noqa: F401
from multihop_offload_tpu.ops.chebconv import (  # noqa: F401
    chebconv_path,
    chebconv_propagate_pallas,
    chebconv_propagate_ragged,
    chebconv_ragged_path,
    make_fused_propagate,
    make_fused_propagate_ragged,
    resolve_chebconv,
)
from multihop_offload_tpu.ops.sparse import (  # noqa: F401
    COO,
    coo_matmul,
    coo_propagate,
    dense_to_coo,
)

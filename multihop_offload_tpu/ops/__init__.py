from multihop_offload_tpu.ops.minplus import (  # noqa: F401
    apsp_minplus_pallas,
    minplus_power_kernel_call,
)

"""Drift-campaign harness: scenario shifts vs the flywheel's detectors.

The drift-gated loop (`mho-loop --loop_drift`) only opens a capture/refit
cycle when `obs.drift.DriftMonitor` trips on the captured-outcome stream.
This module measures that gate against a KNOWN distribution shift: a
`scenarios.shift.ShiftSchedule` renders a synthetic outcome stream with
the world switching at `at_tick`, and `shift_campaign` reports when (and
whether) the detectors notice — detection delay in ticks, and whether any
detector fired before the shift (a false positive against a stationary
from-world).

This is the consumable the ROADMAP's drift-campaign item needs: scenario
switches as injectors, detectors as the system under test.
"""

from __future__ import annotations

from typing import List, Optional


def shift_campaign(schedule, ticks: int, seed: int = 0,
                   min_samples: int = 16) -> dict:
    """Feed `schedule.outcome_events(ticks, seed)` to a fresh
    `DriftMonitor`; returns the detection report.

    `min_samples` is the detectors' warmup length — the schedule's
    `at_tick` must exceed it or the post-shift world leaks into the
    warmup baseline and the measurement is void (reported as
    `warmup_ok: false` rather than raising, so a sweep over schedules
    degrades per-row)."""
    from multihop_offload_tpu.obs.drift import DriftMonitor

    monitor = DriftMonitor(min_samples=min_samples)
    events = schedule.outcome_events(ticks, seed=seed)
    tripped_at: Optional[int] = None
    trips: List[dict] = []
    for tick, ev in enumerate(events):
        new = monitor.update(ev)
        if new and tripped_at is None:
            tripped_at = tick
        trips.extend(new)
    detected = tripped_at is not None and tripped_at >= schedule.at_tick
    return {
        "ticks": int(ticks),
        "at_tick": int(schedule.at_tick),
        "warmup_ok": schedule.at_tick > min_samples,
        "from": schedule.from_spec.name,
        "to": schedule.to_spec.name,
        "tripped_at": tripped_at,
        "detected": detected,
        "detection_delay": (tripped_at - schedule.at_tick) if detected
        else None,
        "false_positive": tripped_at is not None
        and tripped_at < schedule.at_tick,
        "trips": trips,
    }

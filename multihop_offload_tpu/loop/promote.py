"""Promotion controller: the flywheel's state machine, with rollback.

States: idle -> capturing -> refitting -> validating -> {promoted |
rejected} -> monitoring -> {ok -> idle | rolled_back}.  Transitions are
host-side bookkeeping; the two state-changing actions are:

- `promote`: pre-validate the candidate's param signature against the
  LIVE serving tree (`serve.executor.param_signature` — a mismatched tree
  must reject the promotion here, never fail mid-tick), save it into the
  serving orbax tree at a fresh monotone step with its lineage, and swap
  it in through the service's no-retrace hot-reload path.
- `rollback`: re-pin the pre-promotion champion.  Orbax keeps the FIRST
  save of any step id, so rollback never "goes back" to an old step — it
  re-saves the champion snapshot at `latest + 1` (`source="rollback"`
  lineage pointing at the failed candidate) and hot-reloads.  The step
  counter stays monotone, the weights return.

Every transition lands in the run log (`loop_state` events; `promotion` /
`rollback` / `rejection` for the decisions) and the `mho_loop_*` counters,
so `mho-obs` can render a flywheel run and Prometheus can alert on
rollback rate.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import jax
import numpy as np

from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs import trace as obs_trace
from multihop_offload_tpu.obs.registry import registry as obs_registry
from multihop_offload_tpu.serve.executor import param_signature
from multihop_offload_tpu.train import checkpoints as ckpt_lib

STATES = (
    "idle", "capturing", "refitting", "validating",
    "promoted", "rejected", "monitoring", "rolled_back",
)


class PromotionController:
    """Drives candidate weights into (and back out of) the serving tree."""

    def __init__(self, model_dir: str, which: str = "orbax"):
        self.model_dir = model_dir
        self.which = which
        self.directory = os.path.join(model_dir, which)
        self.state = "idle"
        self.history: List[dict] = []

    # ---- state bookkeeping -------------------------------------------------

    def transition(self, state: str, **fields) -> None:
        if state not in STATES:
            raise ValueError(f"unknown loop state '{state}'; one of {STATES}")
        self.state = state
        rec = {"state": state, **fields}
        self.history.append(rec)
        obs_events.emit("loop_state", **rec)
        obs_registry().counter(
            "mho_loop_transitions_total", "flywheel state transitions"
        ).inc(state=state)

    def _next_step(self) -> int:
        return (ckpt_lib.latest_step(self.directory) or 0) + 1

    def drift_triggered(self, trip: dict, cycle: Optional[int] = None) -> None:
        """Enter capture because a drift detector fired (obs.drift): the
        flywheel's third entry path besides schedule and operator.  The
        trip's signal/detector/stat land in the `loop_state` event so a
        capture window is attributable to the shift that opened it."""
        obs_registry().counter(
            "mho_loop_drift_captures_total",
            "capture windows opened by drift detectors",
        ).inc(signal=str(trip.get("signal", "?")))
        fields = {k: trip[k] for k in ("signal", "detector", "stat", "value")
                  if k in trip}
        if cycle is not None:
            fields["cycle"] = cycle
        self.transition("capturing", trigger="drift_triggered", **fields)

    # ---- the two weight-moving actions -------------------------------------

    def promote(
        self,
        service,
        candidate_variables: Any,
        lineage: Optional[dict] = None,
        candidate_step: Optional[int] = None,
        experience_ids: Optional[List[int]] = None,
    ) -> Optional[int]:
        """Validated candidate -> serving tree -> hot-reload.

        Returns the serving step it landed at, or None when the candidate
        was structurally rejected (wrong tree/shape/dtype signature — the
        service keeps serving the champion untouched)."""
        live = service.executor.variables["params"]
        cand = candidate_variables["params"]
        if param_signature(cand) != param_signature(live):
            self.reject("param signature mismatch against live tree",
                        candidate_step=candidate_step)
            return None
        step = self._next_step()
        host = jax.tree_util.tree_map(np.asarray, candidate_variables)
        ckpt_lib.save_checkpoint(
            self.directory, step, {"params": host["params"]},
            lineage=lineage if lineage is not None
            else ckpt_lib.make_lineage("refit", parent_step=candidate_step),
        )
        loaded = service.hot_reload(self.model_dir, which=self.which)
        obs_registry().counter(
            "mho_loop_promotions_total", "candidates promoted to serving"
        ).inc()
        obs_events.emit("promotion", step=step, loaded=loaded,
                        candidate_step=candidate_step)
        if experience_ids:
            # close the trace loop: every captured request that trained this
            # candidate gets a terminal "promotion" hop with its lineage
            obs_trace.hop("promotion", experience_ids, step=step,
                          candidate_step=candidate_step)
        self.transition("promoted", step=step)
        return step

    def reject(self, reason: str, candidate_step: Optional[int] = None) -> None:
        """Candidate refused before touching the serving tree."""
        obs_registry().counter(
            "mho_loop_rejections_total", "candidates refused promotion"
        ).inc()
        obs_events.emit("rejection", reason=reason,
                        candidate_step=candidate_step)
        self.transition("rejected", reason=reason)

    def rollback(self, service, champion_variables: Any, reason: str,
                 failed_step: Optional[int] = None) -> int:
        """Re-pin the champion snapshot at a fresh monotone step."""
        step = self._next_step()
        host = jax.tree_util.tree_map(np.asarray, champion_variables)
        ckpt_lib.save_checkpoint(
            self.directory, step, {"params": host["params"]},
            lineage=ckpt_lib.make_lineage(
                "rollback", parent_step=failed_step,
                parent_dir=self.directory,
                extra={"reason": reason},
            ),
        )
        loaded = service.hot_reload(self.model_dir, which=self.which)
        obs_registry().counter(
            "mho_loop_rollbacks_total", "promotions rolled back"
        ).inc()
        obs_events.emit("rollback", step=step, loaded=loaded,
                        reason=reason, failed_step=failed_step)
        self.transition("rolled_back", step=step, reason=reason)
        return step


def monitor_ok(
    pre_tau: Optional[float],
    post_tau: Optional[float],
    max_ratio: float,
) -> bool:
    """Post-promotion regression check on measured serve tau: the promoted
    policy's measured mean tau may exceed the pre-promotion baseline by at
    most `max_ratio`.  Missing measurements (no traffic in a window) pass —
    absence of evidence must not trigger a rollback."""
    if pre_tau is None or post_tau is None or pre_tau <= 0:
        return True
    return post_tau <= pre_tau * max_ratio

"""Promotion controller: the flywheel's state machine, with rollback.

States: idle -> capturing -> refitting -> validating -> {promoting ->
promoted | rejected} -> monitoring -> {ok -> idle | rolling_back ->
rolled_back}.  Transitions are host-side bookkeeping; the two
state-changing actions are:

- `promote`: pre-validate the candidate's param signature against the
  LIVE serving tree (`serve.executor.param_signature` — a mismatched tree
  must reject the promotion here, never fail mid-tick), save it into the
  serving orbax tree at a fresh monotone step with its lineage, and swap
  it in through the service's no-retrace hot-reload path.
- `rollback`: re-pin the pre-promotion champion.  Orbax keeps the FIRST
  save of any step id, so rollback never "goes back" to an old step — it
  re-saves the champion snapshot at `latest + 1` (`source="rollback"`
  lineage pointing at the failed candidate) and hot-reloads.  The step
  counter stays monotone, the weights return.

Durability: every transition is journaled to an atomically-written
(`tmp`+`fsync`+`rename`) sidecar, `<model_dir>/loop_state.json`, BEFORE
its side effects — `promoting` / `rolling_back` are write-ahead intents
carrying the pinned target step, so a process killed mid-save resumes
idempotently (`PromotionController.resume` + `cli.loop` phase dispatch)
instead of restarting the cycle or double-saving.  Cool-down timers
survive restarts the same way.  `ctx` is the journaled scratchpad: the
fields of every transition merge into it, and `note()` adds
cycle-progress facts (pre-promotion tau, champion step) between
transitions.

Every transition lands in the run log (`loop_state` events; `promotion` /
`rollback` / `rejection` for the decisions) and the `mho_loop_*` counters,
so `mho-obs` can render a flywheel run and Prometheus can alert on
rollback rate.
"""

from __future__ import annotations

import os
import time
from typing import Any, List, Optional

import jax
import numpy as np

from multihop_offload_tpu.chaos import faults
from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs import trace as obs_trace
from multihop_offload_tpu.obs.registry import registry as obs_registry
from multihop_offload_tpu.serve.executor import param_signature
from multihop_offload_tpu.train import checkpoints as ckpt_lib
from multihop_offload_tpu.utils.durable import (
    atomic_write_json,
    load_json,
    with_backoff,
)

JOURNAL_SCHEMA = 1

STATES = (
    "idle", "capturing", "refitting", "validating", "canarying",
    "promoting", "promoted", "rejected", "monitoring",
    "rolling_back", "rolled_back",
)


class PromotionController:
    """Drives candidate weights into (and back out of) the serving tree."""

    def __init__(self, model_dir: str, which: str = "orbax",
                 clock=time.time, candidate_keep: int = 0,
                 cooldown_s: float = 0.0):
        self.model_dir = model_dir
        self.which = which
        self.directory = os.path.join(model_dir, which)
        self.candidate_dir = os.path.join(model_dir, f"{which}_candidate")
        self.journal_path = os.path.join(model_dir, "loop_state.json")
        self.clock = clock
        self.candidate_keep = int(candidate_keep)
        self.cooldown_s = float(cooldown_s)
        self.state = "idle"
        self.seq = 0
        self.cooldown_until = 0.0
        self.ctx: dict = {}
        self.resumed = False
        self.history: List[dict] = []

    # ---- durable journal ---------------------------------------------------

    @classmethod
    def resume(cls, model_dir: str, which: str = "orbax", clock=time.time,
               candidate_keep: int = 0,
               cooldown_s: float = 0.0) -> "PromotionController":
        """Rebuild the controller from the journal sidecar: state, seq,
        cool-down deadline and ctx come back exactly as last journaled, so
        a killed `mho-loop` continues the interrupted cycle from its last
        durable transition.  A missing/unreadable journal (first boot, or
        pre-durability trees) yields a fresh idle controller."""
        ctl = cls(model_dir, which=which, clock=clock,
                  candidate_keep=candidate_keep, cooldown_s=cooldown_s)
        j = load_json(ctl.journal_path)
        if j and j.get("schema") == JOURNAL_SCHEMA and j.get("state") in STATES:
            ctl.state = j["state"]
            ctl.seq = int(j.get("seq", 0))
            ctl.cooldown_until = float(j.get("cooldown_until", 0.0))
            ctl.ctx = dict(j.get("ctx") or {})
            ctl.resumed = ctl.state != "idle"
            if ctl.resumed:
                obs_registry().counter(
                    "mho_loop_resumes_total",
                    "flywheel cycles resumed from the journal",
                ).inc(state=ctl.state)
                obs_events.emit("loop_resume", state=ctl.state, seq=ctl.seq,
                                ctx=dict(ctl.ctx))
        return ctl

    def _journal(self) -> None:
        payload = {
            "schema": JOURNAL_SCHEMA,
            "state": self.state,
            "seq": self.seq,
            "cooldown_until": self.cooldown_until,
            "ctx": self.ctx,
            "history_tail": self.history[-8:],
        }

        def _write() -> None:
            faults.io_gate("journal:write")
            atomic_write_json(self.journal_path, payload,
                              site="journal:write")

        with_backoff(_write, site="journal:write")

    # ---- state bookkeeping -------------------------------------------------

    def transition(self, state: str, **fields) -> None:
        if state not in STATES:
            raise ValueError(f"unknown loop state '{state}'; one of {STATES}")
        self.state = state
        self.seq += 1
        rec = {"state": state, **fields}
        self.history.append(rec)
        self.ctx.update(fields)
        # durable first: the journal is the source of truth a restarted
        # process resumes from, the event stream is an observer
        self._journal()
        obs_events.emit("loop_state", **rec)
        obs_registry().counter(
            "mho_loop_transitions_total", "flywheel state transitions"
        ).inc(state=state)

    def note(self, **fields) -> None:
        """Journal cycle-progress facts without a state change (the pinned
        candidate step, the pre-promotion tau, the champion step) so a
        resume after SIGKILL has them."""
        self.ctx.update(fields)
        self._journal()

    def start_cooldown(self, seconds: Optional[float] = None) -> None:
        s = self.cooldown_s if seconds is None else float(seconds)
        if s <= 0:
            return
        self.cooldown_until = float(self.clock()) + s
        self._journal()
        obs_events.emit("loop_cooldown", until=self.cooldown_until,
                        seconds=s)

    def cooldown_remaining(self) -> float:
        return max(self.cooldown_until - float(self.clock()), 0.0)

    def _next_step(self) -> int:
        return (ckpt_lib.latest_step(self.directory) or 0) + 1

    def drift_triggered(self, trip: dict, cycle: Optional[int] = None) -> None:
        """Enter capture because a drift detector fired (obs.drift): the
        flywheel's third entry path besides schedule and operator.  The
        trip's signal/detector/stat land in the `loop_state` event so a
        capture window is attributable to the shift that opened it."""
        obs_registry().counter(
            "mho_loop_drift_captures_total",
            "capture windows opened by drift detectors",
        ).inc(signal=str(trip.get("signal", "?")))
        fields = {k: trip[k] for k in ("signal", "detector", "stat", "value")
                  if k in trip}
        if cycle is not None:
            fields["cycle"] = cycle
        self.transition("capturing", trigger="drift_triggered", **fields)

    # ---- bounded candidate retention ---------------------------------------

    def gc_candidates(self, reason: str) -> List[int]:
        """Bounded retention in `orbax_candidate/`: rejected/rolled-back
        candidates used to pile up forever; keep the newest K."""
        if self.candidate_keep <= 0:
            return []
        return ckpt_lib.gc_checkpoints(self.candidate_dir,
                                       keep=self.candidate_keep,
                                       reason=reason)

    # ---- the two weight-moving actions -------------------------------------

    def promote(
        self,
        service,
        candidate_variables: Any,
        lineage: Optional[dict] = None,
        candidate_step: Optional[int] = None,
        experience_ids: Optional[List[int]] = None,
        step: Optional[int] = None,
        canary=None,
    ) -> Optional[int]:
        """Validated candidate -> serving tree -> hot-reload.

        Journals a `promoting` intent with the pinned target step before
        touching disk, and skips the save when that step already holds a
        verified checkpoint — so a crash anywhere in here resumes by
        calling `promote` again with `step=ctx["step"]` and lands in the
        same place.  Returns the serving step, or None when the candidate
        was structurally rejected (wrong tree/shape/dtype signature) or
        semantically rejected (`canary`, a `loop.canary.CheckpointCanary`
        — journaled "canarying" state) — either way the service keeps
        serving the champion untouched."""
        live = service.executor.variables["params"]
        cand = candidate_variables["params"]
        if param_signature(cand) != param_signature(live):
            self.reject("param signature mismatch against live tree",
                        candidate_step=candidate_step)
            return None
        if canary is not None:
            # semantic gate BEFORE the write-ahead promoting intent: a
            # refused candidate never pins a serving step
            self.transition("canarying", candidate_step=candidate_step)
            why = canary.check(candidate_variables)
            if why is not None:
                obs_registry().counter(
                    "mho_canary_rejections_total",
                    "candidate weight sets refused by the semantic canary",
                ).inc(stage="promote", reason=why.split(":")[0])
                obs_events.emit("canary_reject", stage="promote", reason=why,
                                candidate_step=candidate_step)
                self.reject(f"canary: {why}", candidate_step=candidate_step)
                return None
        step = int(step) if step is not None else self._next_step()
        self.transition("promoting", step=step, candidate_step=candidate_step)
        faults.crashpoint("promote:pre_save")
        if not ckpt_lib.has_verified(self.directory, step):
            host = jax.tree_util.tree_map(np.asarray, candidate_variables)
            ckpt_lib.save_checkpoint(
                self.directory, step, {"params": host["params"]},
                lineage=lineage if lineage is not None
                else ckpt_lib.make_lineage("refit", parent_step=candidate_step),
            )
        faults.crashpoint("promote:post_save")
        loaded = service.hot_reload(self.model_dir, which=self.which)
        faults.crashpoint("promote:post_reload")
        obs_registry().counter(
            "mho_loop_promotions_total", "candidates promoted to serving"
        ).inc()
        obs_events.emit("promotion", step=step, loaded=loaded,
                        candidate_step=candidate_step)
        if experience_ids:
            # close the trace loop: every captured request that trained this
            # candidate gets a terminal "promotion" hop with its lineage
            obs_trace.hop("promotion", experience_ids, step=step,
                          candidate_step=candidate_step)
        self.transition("promoted", step=step)
        return step

    def reject(self, reason: str, candidate_step: Optional[int] = None) -> None:
        """Candidate refused before touching the serving tree."""
        obs_registry().counter(
            "mho_loop_rejections_total", "candidates refused promotion"
        ).inc()
        obs_events.emit("rejection", reason=reason,
                        candidate_step=candidate_step)
        self.transition("rejected", reason=reason)
        self.gc_candidates(reason="rejected candidate")

    def rollback(self, service, champion_variables: Any, reason: str,
                 failed_step: Optional[int] = None,
                 step: Optional[int] = None) -> int:
        """Re-pin the champion snapshot at a fresh monotone step.  Same
        write-ahead-intent contract as `promote`: the `rolling_back`
        journal entry pins the step, the save is skipped when already
        verified, so a crashed rollback re-runs to the same lineage."""
        step = int(step) if step is not None else self._next_step()
        self.transition("rolling_back", step=step, reason=reason,
                        failed_step=failed_step)
        faults.crashpoint("rollback:pre_save")
        if not ckpt_lib.has_verified(self.directory, step):
            host = jax.tree_util.tree_map(np.asarray, champion_variables)
            ckpt_lib.save_checkpoint(
                self.directory, step, {"params": host["params"]},
                lineage=ckpt_lib.make_lineage(
                    "rollback", parent_step=failed_step,
                    parent_dir=self.directory,
                    extra={"reason": reason},
                ),
            )
        faults.crashpoint("rollback:post_save")
        loaded = service.hot_reload(self.model_dir, which=self.which)
        obs_registry().counter(
            "mho_loop_rollbacks_total", "promotions rolled back"
        ).inc()
        obs_events.emit("rollback", step=step, loaded=loaded,
                        reason=reason, failed_step=failed_step)
        self.transition("rolled_back", step=step, reason=reason)
        self.start_cooldown()
        self.gc_candidates(reason="rolled-back candidate")
        return step


def monitor_ok(
    pre_tau: Optional[float],
    post_tau: Optional[float],
    max_ratio: float,
) -> bool:
    """Post-promotion regression check on measured serve tau: the promoted
    policy's measured mean tau may exceed the pre-promotion baseline by at
    most `max_ratio`.  Missing measurements (no traffic in a window) pass —
    absence of evidence must not trigger a rollback."""
    if pre_tau is None or post_tau is None or pre_tau <= 0:
        return True
    return post_tau <= pre_tau * max_ratio

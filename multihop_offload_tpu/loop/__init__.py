"""Continual-learning flywheel: serve -> train -> serve, closed.

The service logs per-request outcomes (`serve.service` capture ->
`obs.events` "outcome" rows); `experience` turns that stream back into
replay batches; `refit` fine-tunes the policy on them in the background;
`validate` replays a held-out slice of the logged workload through the
packet simulator for champion vs candidate; `promote` drives the
state machine capture -> refit -> validate -> promote-via-hot-reload ->
monitor, with automatic rollback.  Entry point: `cli.loop` (`mho-loop`).

Deliberately import-light: submodules import serve/sim/train/agent pieces
directly, and serve.service imports `loop.experience` — keeping this
package namespace empty avoids the cycle.
"""

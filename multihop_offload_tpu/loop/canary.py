"""Semantic checkpoint canary: golden-probe decisions gate every swap.

`train.checkpoints.tree_checksum` proves a candidate's BYTES are what was
written; `serve.executor.param_signature` proves its SHAPES fit the live
model.  Neither proves the weights *mean* anything — a bf16 refit that
overflowed to NaN, or a scale-poisoned tree, is checksum-valid and
signature-valid and would serve garbage.  The canary closes that hole
semantically: a small frozen probe set (synthetic requests off the serving
pool, packed ONCE into the service's own bucket layouts) is run through any
candidate before it may replace the champion, and the candidate is refused
when

  * any live probe output (delay estimate / empirical score) is NaN/Inf, or
  * its decisions (dst, is_local) agree with the champion's recorded golden
    answers on less than `min_agreement` of probe jobs — the decision-
    collapse signature of weight poisoning that finiteness alone misses.

The probe programs are the executor's ALREADY-COMPILED per-bucket gnn
programs (weights are arguments, shapes are the bucket pads), so a canary
run costs a few dispatches and ZERO retraces.  Wired into `loop.promote`
(journaled "canarying" state, opt-in kwarg) and `serve.executor.hot_reload`
(pre-swap check via `executor.canary`); rejection means the champion simply
keeps serving — it is not corruption, so nothing is quarantined.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from multihop_offload_tpu.serve.bucketing import pack_bucket
from multihop_offload_tpu.serve.workload import request_stream

# probe ids live far above any real traffic so trace/experience streams
# can never collide with a client request id
PROBE_ID_OFFSET = 900_000


class CheckpointCanary:
    """Frozen golden-probe gate bound to one service's compiled programs."""

    def __init__(
        self,
        service,
        pool: Sequence,
        count: int = 8,
        seed: int = 123,
        min_agreement: float = 0.7,
    ):
        self.service = service
        self.min_agreement = float(min_agreement)
        self.golden: Optional[list] = None
        # pack once: per-bucket (batch, keys, live-mask rows) in the exact
        # layout the serving tick uses, so probe decisions and serving
        # decisions are the same compiled math
        self._batches = []
        by_bucket: dict = {}
        for req in request_stream(pool, count, seed=seed,
                                  id_offset=PROBE_ID_OFFSET):
            b = service.buckets.bucket_for(*req.sizes)
            if b is not None and service.layout.sparse:
                b = service._sparse_fit(req, b)
            if b is None:
                continue
            by_bucket.setdefault(b, []).append(req)
        if not by_bucket:
            raise ValueError("no probe request fits any bucket")
        hop_cache: dict = {}
        for b, reqs in sorted(by_bucket.items()):
            reqs = reqs[: service.slots]
            pad = service.buckets[b]
            binst, bjobs = pack_bucket(
                reqs, pad, service.slots, dtype=service.dtype,
                hop_cache=hop_cache, layout=service.layout,
            )
            keys = [service.request_key(r.request_id) for r in reqs]
            while len(keys) < service.slots:
                keys.append(keys[-1])
            keys = np.stack([np.asarray(k) for k in keys])
            # live (slot, job) entries: real request rows, true job counts
            live = np.zeros((service.slots, pad.j), dtype=bool)
            for i, r in enumerate(reqs):
                live[i, : r.num_jobs] = True
            self._batches.append((b, binst, bjobs, keys, live))

    # ---- probe execution -------------------------------------------------

    def _probe(self, variables) -> list:
        """Run every probe batch through the executor's compiled gnn
        programs with `variables`; host (dst, is_local, delay_est,
        job_total, live) per batch."""
        import jax

        ex = self.service.executor
        out_rows = []
        for b, binst, bjobs, keys, live in self._batches:
            gnn, _ = ex._steps[b]
            out, _dev = gnn(variables, binst, bjobs, keys)
            host = tuple(np.asarray(x) for x in jax.device_get(out))
            out_rows.append((*host, live))
        return out_rows

    def record_champion(self) -> None:
        """Snapshot the CURRENT champion's probe answers as the golden set."""
        self.golden = [
            (dst.copy(), is_local.copy())
            for dst, is_local, _d, _t, _live in self._probe(
                self.service.executor.variables)
        ]

    # ---- the gate --------------------------------------------------------

    def check(self, candidate_variables) -> Optional[str]:
        """None iff the candidate passes; else a typed refusal reason."""
        rows = self._probe(candidate_variables)
        for _dst, _is_local, delay_est, job_total, live in rows:
            bad = (~np.isfinite(delay_est) | ~np.isfinite(job_total)) & live
            if bool(bad.any()):
                return "nonfinite_probe_outputs"
        if self.golden is None:
            return None  # no champion recorded yet: finiteness-only gate
        agree = 0
        total = 0
        for (gdst, glocal), (dst, is_local, _d, _t, live) in zip(
                self.golden, rows):
            total += int(live.sum())
            agree += int(((dst == gdst) & (is_local == glocal) & live).sum())
        frac = agree / max(total, 1)
        if frac < self.min_agreement:
            # typed tag first (the counter label), detail after the colon
            return (f"decision_collapse:agreement {frac:.3f} < "
                    f"{self.min_agreement:g}")
        return None

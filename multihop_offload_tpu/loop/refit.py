"""Background re-fit: fine-tune the serving policy on captured experience.

One jitted step: `vmap(agent.train_step.forward_backward)` over a packed
experience batch (the service's own pad layout via
`experience.replay_batches`), mean gradients across the batch, one
optimizer update with the repo's Keras-parity Adam (`agent.replay`) and
the post-update max-norm constraint.  Starting point is the CURRENT
champion's parameters — a refit is a continuation, not a retrain — but
the optimizer state is fresh: the offline run's moments describe a
different data distribution and are not checkpointed into serving trees.

The candidate is written to its own orbax tree (`<model_dir>/orbax_candidate`)
with `source="refit"` lineage; it never touches the serving tree — only
`loop.promote` moves weights there, after the sim gate passes.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multihop_offload_tpu.agent.replay import (
    apply_max_norm_constraint,
    make_optimizer,
)
from multihop_offload_tpu.agent.train_step import forward_backward
from multihop_offload_tpu.chaos import faults
from multihop_offload_tpu.loop.experience import (
    Outcome,
    pad_for_outcomes,
    replay_batches,
)
from multihop_offload_tpu.obs import prof as obs_prof
from multihop_offload_tpu.obs import trace as obs_trace
from multihop_offload_tpu.obs.registry import registry as obs_registry
from multihop_offload_tpu.obs.spans import span
from multihop_offload_tpu.train import checkpoints as ckpt_lib

CANDIDATE_SUBDIR = "orbax_candidate"


def candidate_dir(model_dir: str) -> str:
    return os.path.join(model_dir, CANDIDATE_SUBDIR)


def refit(
    model,
    variables,
    outcomes: Sequence[Outcome],
    cfg,
    steps: Optional[int] = None,
    slots: Optional[int] = None,
    seed: int = 0,
    pad=None,
) -> tuple:
    """Fine-tune `variables` on `outcomes`; returns (candidate_variables,
    info dict).  Pure training — saving/lineage is `refit_and_save`."""
    if not outcomes:
        raise ValueError("refit needs at least one captured outcome")
    steps = cfg.loop_refit_steps if steps is None else steps
    slots = cfg.loop_refit_slots if slots is None else slots
    pad = pad_for_outcomes(outcomes, round_to=cfg.round_to) if pad is None else pad

    hop_cache: dict = {}
    with span("loop/refit_pack", outcomes=len(outcomes)):
        batches = list(replay_batches(
            outcomes, pad, slots, dtype=cfg.jnp_dtype, hop_cache=hop_cache
        ))
        # trace continuity: each captured request's journey records which
        # refit batch its experience trained (obs.trace hop chain)
        for bi in range(0, len(outcomes), slots):
            obs_trace.hop(
                "refit_batch",
                [o.request.request_id for o in outcomes[bi:bi + slots]],
                batch=bi // slots, slots=slots,
            )
    optimizer = make_optimizer(cfg)
    params = variables["params"]
    opt_state = optimizer.init(params)

    prob = cfg.prob

    def step_fn(params, opt_state, binst, bjobs, keys):
        def one(inst, jb, k):
            out = forward_backward(
                model, {"params": params}, inst, jb, k, prob=prob,
            )
            return out.grads["params"], out.loss_critic, out.loss_mse

        grads, lc, lm = jax.vmap(one)(binst, bjobs, keys)
        g = jax.tree_util.tree_map(lambda x: jnp.mean(x, axis=0), grads)
        # non-finite containment: one poisoned batch skips-and-counts the
        # update in-jit — params AND optimizer state pass through untouched
        ok = jnp.isfinite(jnp.mean(lc)) & jnp.isfinite(jnp.mean(lm))
        for leaf in jax.tree_util.tree_leaves(g):
            ok = ok & jnp.all(jnp.isfinite(leaf))
        updates, opt_new = optimizer.update(g, opt_state, params)
        p_new = optax.apply_updates(params, updates)
        p_new = apply_max_norm_constraint(p_new, 1.0)
        params = jax.tree_util.tree_map(
            lambda new, old: jnp.where(ok, new, old), p_new, params)
        opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(ok, new, old), opt_new, opt_state)
        return params, opt_state, jnp.mean(lc), jnp.mean(lm), ok

    # registered per-program cost attribution: the refit step AOT-compiles
    # on its first call and accounts each step's synced wall window
    step_fn = obs_prof.wrap("loop/refit_step", jax.jit(step_fn))

    base_key = jax.random.PRNGKey(seed)
    losses = []
    skipped = 0
    with span("loop/refit", steps=steps, batches=len(batches)):
        for s in range(steps):
            faults.crashpoint("refit:mid")
            binst, bjobs = batches[s % len(batches)]
            keys = jax.random.split(jax.random.fold_in(base_key, s), slots)
            t0 = time.perf_counter()  # nondet-ok(device-time accounting is a measurement)
            params, opt_state, lc, lm, ok = step_fn(
                params, opt_state, binst, bjobs, keys
            )
            losses.append((float(lc), float(lm)))
            # the float() pulls above are this loop's sync boundary; the
            # skip flag rides the same fetch
            skipped += int(not bool(ok))
            step_fn.account(time.perf_counter() - t0)  # nondet-ok(same measurement)
    obs_registry().counter(
        "mho_loop_refit_steps_total", "experience fine-tuning steps run"
    ).inc(steps)
    if skipped:
        obs_registry().counter(
            "mho_refit_skipped_updates_total",
            "optimizer updates skipped on non-finite grads",
        ).inc(skipped, phase="refit")
    info = {
        "steps": steps,
        "batches": len(batches),
        "outcomes": len(outcomes),
        "skipped_updates": skipped,
        "loss_critic_first": losses[0][0],
        "loss_critic_last": losses[-1][0],
        "loss_mse_last": losses[-1][1],
    }
    return {"params": params}, info


def refit_and_save(
    model,
    variables,
    outcomes: Sequence[Outcome],
    cfg,
    parent_step: Optional[int] = None,
    seed: int = 0,
    pad=None,
    step: Optional[int] = None,
) -> tuple:
    """Run `refit` and persist the candidate with `source="refit"` lineage.
    Returns (candidate_variables, candidate_step, info).

    `step` pins the candidate step (crash-resume: the journal recorded the
    intended step before the first attempt, so the redo lands at the same
    id instead of latest+1)."""
    cand_vars, info = refit(
        model, variables, outcomes, cfg, seed=seed, pad=pad
    )
    directory = candidate_dir(cfg.model_dir())
    step = int(step) if step is not None else (
        (ckpt_lib.latest_step(directory) or 0) + 1)
    host = jax.tree_util.tree_map(np.asarray, cand_vars)
    faults.crashpoint("refit:pre_save")
    ckpt_lib.save_checkpoint(
        directory, step, host,
        lineage=ckpt_lib.make_lineage(
            "refit", parent_step=parent_step,
            parent_dir=os.path.join(cfg.model_dir(), "orbax"), cfg=cfg,
            extra={"outcomes": len(outcomes),
                   "refit_steps": info["steps"]},
        ),
    )
    faults.crashpoint("refit:post_save")
    obs_registry().counter(
        "mho_loop_refits_total", "candidate checkpoints produced"
    ).inc()
    return cand_vars, step, info

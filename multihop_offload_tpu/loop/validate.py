"""Sim-gated A/B validation: champion vs candidate on held-out workload.

The held-out slice of the captured experience (`experience.split_holdout`)
is replayed through the packet-level simulator (`sim.runner.FleetSim`) —
NOT through the analytic evaluator the candidate was just fit on — once
under the champion's weights and once under the candidate's.  Same
instances, same arrival randomness (shared PRNG keys), same horizon; the
only difference is the policy deciding offloads each round, so the score
deltas are attributable to the weights alone.

Two `FleetSim`s are built per comparison because `sim.policies.make_policy`
closes over its variables (the compiled program treats them as constants —
that is what makes the per-round policy free of host round-trips).  The
validator is a batch job off the serving path, so the extra compile is
paid where it is cheap; it never calls `mark_steady`.

`apply_gates` is the pure decision rule — configurable absolute
delivered-ratio drop and relative tau (mean packet delay) ratio — kept
free of sim state so tests can drive it on synthetic score pairs.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

from multihop_offload_tpu.graphs.instance import (
    build_instance,
    build_jobset,
    stack_instances,
)
from multihop_offload_tpu.loop.experience import Outcome, pad_for_outcomes
from multihop_offload_tpu.obs.spans import span
from multihop_offload_tpu.sim.policies import make_policy
from multihop_offload_tpu.sim.runner import FleetSim
from multihop_offload_tpu.sim.state import build_sim_params, spec_for


def build_validation_fleet(
    outcomes: Sequence[Outcome],
    pad=None,
    margin: float = 5.0,
    round_to: int = 8,
    dtype=np.float32,
):
    """Stack the held-out requests into one sim fleet.

    Returns (insts, jobss, paramss, init_rates, dts, spec_args) — all lanes
    share one pad shape so champion and candidate each run ONE compiled
    program over the whole slice."""
    pad = pad_for_outcomes(outcomes, round_to=round_to) if pad is None else pad
    insts, jobss, params_list = [], [], []
    for o in outcomes:
        r = o.request
        inst = build_instance(
            r.topo, r.roles, r.proc_bws, r.link_rates, r.t_max, pad,
            dtype=dtype, device=False,
        )
        jobs = build_jobset(
            r.job_src, r.job_rate, pad_jobs=pad.j, ul=r.ul, dl=r.dl,
            dtype=dtype, device=False,
        )
        insts.append(inst)
        jobss.append(jobs)
        params_list.append(build_sim_params(inst, jobs, margin=margin))
    init_rates = np.stack([np.asarray(j.rate) for j in jobss])
    dts = np.asarray([float(p.dt) for p in params_list])
    return (
        stack_instances(insts),
        stack_instances(jobss),
        stack_instances(params_list),
        init_rates,
        dts,
        (insts[0], jobss[0]),
    )


def score_run(state, dts: np.ndarray) -> dict:
    """Summarize one fleet run: delivered ratio + delivered-weighted mean
    packet delay in model time (per-lane dt restores the time unit)."""
    st = jax.tree_util.tree_map(np.asarray, state)
    generated = int(st.generated.sum())
    delivered = int(st.delivered.sum())
    dropped = int(st.dropped.sum())
    # delay_sum is in slots; convert per lane, then pool over the fleet
    lane_delay = (st.delay_sum.sum(axis=1) * dts)
    lane_delivered = st.delivered.sum(axis=1)
    total_delivered = lane_delivered.sum()
    mean_delay = (
        float(lane_delay.sum() / total_delivered) if total_delivered else None
    )
    return {
        "generated": generated,
        "delivered": delivered,
        "dropped": dropped,
        "delivered_ratio": delivered / max(generated, 1),
        "mean_packet_delay": mean_delay,
    }


def ab_compare(
    model,
    champion_variables,
    candidate_variables,
    outcomes: Sequence[Outcome],
    rounds: int = 2,
    slots_per_round: int = 200,
    cap: int = 64,
    margin: float = 5.0,
    seed: int = 0,
    round_to: int = 8,
    precision=None,
    dtype=np.float32,
) -> dict:
    """Replay the held-out workload under both policies; returns
    {"champion": score, "candidate": score, ...}."""
    if not outcomes:
        raise ValueError("validation needs at least one held-out outcome")
    insts, jobss, paramss, init_rates, dts, (inst0, jobs0) = (
        build_validation_fleet(
            outcomes, margin=margin, round_to=round_to, dtype=dtype
        )
    )
    spec = spec_for(inst0, jobs0, cap=cap)
    fleet = len(outcomes)
    keys = jax.random.split(jax.random.PRNGKey(seed), fleet)
    scores = {}
    for name, variables in (
        ("champion", champion_variables), ("candidate", candidate_variables)
    ):
        policy = make_policy(
            "gnn", model=model, variables=variables, precision=precision
        )
        sim = FleetSim(
            spec, policy, rounds=rounds, slots_per_round=slots_per_round
        )
        with span("loop/validate", arm=name, fleet=fleet):
            run = sim.run(insts, jobss, paramss, keys,
                          init_rates=init_rates,
                          request_ids=[o.request.request_id
                                       for o in outcomes],
                          tag=name)
        scores[name] = score_run(run.state, dts)
    scores["fleet"] = fleet
    scores["slots"] = rounds * slots_per_round
    return scores


def apply_gates(
    champion: dict,
    candidate: dict,
    max_delivered_drop: float,
    max_tau_ratio: float,
) -> tuple:
    """(ok, reasons): the promotion decision rule on two score dicts.

    - delivered ratio may drop at most `max_delivered_drop` (absolute);
    - mean packet delay (tau proxy) may grow at most `max_tau_ratio`
      (relative).  A candidate with no delivered packets fails outright;
      a champion with none passes the tau gate vacuously (nothing to
      regress against).
    """
    reasons: List[str] = []
    dr_c = champion.get("delivered_ratio", 0.0)
    dr_n = candidate.get("delivered_ratio", 0.0)
    if dr_n < dr_c - max_delivered_drop:
        reasons.append(
            f"delivered_ratio {dr_n:.4f} < champion {dr_c:.4f} "
            f"- {max_delivered_drop}"
        )
    tau_c: Optional[float] = champion.get("mean_packet_delay")
    tau_n: Optional[float] = candidate.get("mean_packet_delay")
    if tau_n is None and candidate.get("generated", 0) > 0:
        reasons.append("candidate delivered no packets")
    elif tau_c is not None and tau_n is not None and tau_n > tau_c * max_tau_ratio:
        reasons.append(
            f"mean_packet_delay {tau_n:.4f} > champion {tau_c:.4f} "
            f"* {max_tau_ratio}"
        )
    return (not reasons), reasons

"""Experience capture: serve outcomes -> JSONL -> replay batches.

The serving tick emits one "outcome" event per sampled answered request —
the full request (so training can rebuild the exact instance), the
decision taken, and the measured result (tau, wall latency, degradation).
This module owns both directions of that boundary:

- `sampled` + `outcome_record`: what `serve.service` calls at capture
  time.  Sampling is a deterministic hash of the request id, not an RNG —
  whether a request is captured never depends on process history, so a
  replayed workload captures the identical subset.
- `read_outcomes` + `replay_batches`: what `loop.refit` and
  `loop.validate` consume.  An `Outcome` wraps a reconstructed
  `OffloadRequest`, so the replay path reuses `serve.bucketing.pack_bucket`
  verbatim — experience batches are bit-compatible with what the service
  itself would pack.

Everything in a record is JSON-native (lists, not arrays): the run log
serializes unknown types through `str`, which would silently garble numpy
arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from multihop_offload_tpu.graphs.instance import PadSpec
from multihop_offload_tpu.graphs.topology import build_topology
from multihop_offload_tpu.obs.events import read_events
from multihop_offload_tpu.serve.bucketing import pack_bucket
from multihop_offload_tpu.serve.request import OffloadRequest, OffloadResponse


def _hash01(x: int, salt: int = 0) -> float:
    """Deterministic uniform-ish [0, 1) from an integer id (Knuth
    multiplicative + an xor-shift finalizer); `salt` decorrelates
    independent uses (capture sampling vs holdout split)."""
    h = (int(x) * 2654435761 + salt * 40503) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 2246822519) & 0xFFFFFFFF
    h ^= h >> 13
    return h / 2.0**32


def sampled(request_id: int, rate: float) -> bool:
    """Capture decision for one request id at sampling rate `rate`."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return _hash01(request_id, salt=1) < rate


def outcome_record(req: OffloadRequest, resp: OffloadResponse) -> dict:
    """JSON-safe fields of one captured outcome (the "outcome" event body)."""
    from multihop_offload_tpu.obs.spans import current_trace_id

    job_total = np.asarray(resp.job_total, np.float64)
    topo = req.topo
    return {
        "request_id": int(req.request_id),
        # the serving tick's span trace id: links this outcome to the
        # request's trace hops (obs.trace / `mho-obs --trace`)
        "trace_id": current_trace_id(),
        # topology as its edge list: adjacency (and everything derived)
        # rebuilds exactly via build_topology at read time
        "n": int(topo.n),
        "link_ends": np.asarray(topo.link_ends).tolist(),
        "pos": None if topo.pos is None else np.asarray(topo.pos).tolist(),
        "cf_radius": float(topo.cf_radius),
        "roles": np.asarray(req.roles).tolist(),
        "proc_bws": np.asarray(req.proc_bws, np.float64).tolist(),
        "link_rates": np.asarray(req.link_rates, np.float64).tolist(),
        "job_src": np.asarray(req.job_src).tolist(),
        "job_rate": np.asarray(req.job_rate, np.float64).tolist(),
        "ul": float(req.ul),
        "dl": float(req.dl),
        "t_max": float(req.t_max),
        "topo_key": None if req.topo_key is None else str(req.topo_key),
        # the decision and its measured outcome
        "dst": np.asarray(resp.dst).tolist(),
        "is_local": np.asarray(resp.is_local, bool).tolist(),
        "job_total": job_total.tolist(),
        "tau": float(job_total.mean()) if job_total.size else 0.0,
        "latency_s": float(resp.latency_s),
        "served_by": resp.served_by,
        "bucket": int(resp.bucket),
        "degraded": resp.served_by != "gnn",
    }


@dataclasses.dataclass(frozen=True)
class Outcome:
    """One captured (request, decision, measurement) triple, reconstructed."""

    request: OffloadRequest
    dst: np.ndarray          # (j,) int32 chosen compute node per job
    is_local: np.ndarray     # (j,) bool
    job_total: np.ndarray    # (j,) measured/empirical per-job delay
    tau: float               # mean job_total over the request's real jobs
    latency_s: float
    served_by: str
    bucket: int
    degraded: bool


def outcome_from_event(ev: dict) -> Outcome:
    """Rebuild an `Outcome` (including its full `OffloadRequest`) from one
    "outcome" event row."""
    n = int(ev["n"])
    adj = np.zeros((n, n), np.uint8)
    ends = np.asarray(ev["link_ends"], np.int32).reshape(-1, 2)
    adj[ends[:, 0], ends[:, 1]] = 1
    adj[ends[:, 1], ends[:, 0]] = 1
    pos = None if ev.get("pos") is None else np.asarray(ev["pos"], np.float64)
    topo = build_topology(adj, pos=pos, cf_radius=float(ev.get("cf_radius", 0.0)))
    req = OffloadRequest(
        request_id=int(ev["request_id"]),
        topo=topo,
        roles=np.asarray(ev["roles"], np.int32),
        proc_bws=np.asarray(ev["proc_bws"], np.float64),
        link_rates=np.asarray(ev["link_rates"], np.float64),
        job_src=np.asarray(ev["job_src"], np.int32),
        job_rate=np.asarray(ev["job_rate"], np.float64),
        ul=float(ev["ul"]),
        dl=float(ev["dl"]),
        t_max=float(ev["t_max"]),
        topo_key=ev.get("topo_key"),
    )
    return Outcome(
        request=req,
        dst=np.asarray(ev["dst"], np.int32),
        is_local=np.asarray(ev["is_local"], bool),
        job_total=np.asarray(ev["job_total"], np.float64),
        tau=float(ev["tau"]),
        latency_s=float(ev["latency_s"]),
        served_by=str(ev["served_by"]),
        bucket=int(ev["bucket"]),
        degraded=bool(ev["degraded"]),
    )


def read_outcomes(path: str, include_degraded: bool = False) -> List[Outcome]:
    """All captured outcomes in a (possibly rotated) run log.  Degraded
    (baseline-served) outcomes are excluded by default: they carry no
    signal about the GNN policy being refit."""
    out = []
    for ev in read_events(path):
        if ev.get("event") != "outcome":
            continue
        o = outcome_from_event(ev)
        if include_degraded or not o.degraded:
            out.append(o)
    return out


def split_holdout(
    outcomes: Sequence[Outcome], frac: float
) -> Tuple[List[Outcome], List[Outcome]]:
    """(train, holdout) split, deterministic per request id — re-reading a
    grown log never moves a request across the boundary (the validator must
    not score the candidate on its own training data)."""
    train, hold = [], []
    for o in outcomes:
        (hold if _hash01(o.request.request_id, salt=2) < frac else train).append(o)
    return train, hold


def pad_for_outcomes(
    outcomes: Sequence[Outcome], round_to: int = 8
) -> PadSpec:
    """One pad shape covering every captured request (the refit/validate
    fleet is a single bucket: all lanes of one compiled program)."""
    return PadSpec.for_cases(
        [o.request.sizes for o in outcomes], round_to=round_to
    )


def replay_batches(
    outcomes: Sequence[Outcome],
    pad: PadSpec,
    slots: int,
    dtype=np.float32,
    hop_cache: Optional[dict] = None,
) -> Iterator[Tuple]:
    """Yield `(binst, bjobs)` batches of `slots` lanes — the service's own
    packer over the logged requests, so refit trains on exactly the padded
    layout that served them.  The final partial batch pads by repetition
    (pack_bucket's rule), same as a partially filled serving tick."""
    reqs = [o.request for o in outcomes]
    for i in range(0, len(reqs), slots):
        yield pack_bucket(
            reqs[i:i + slots], pad, slots, dtype=dtype, hop_cache=hop_cache
        )

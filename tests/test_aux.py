"""Auxiliary subsystems: mobility, visualization, analysis, profiling."""

import os

import numpy as np
import pandas as pd
import pytest

from multihop_offload_tpu.graphs import generators
from multihop_offload_tpu.graphs.mobility import (
    migrate_link_state,
    random_walk,
    topology_update,
)
from multihop_offload_tpu.graphs.topology import build_topology
from multihop_offload_tpu.train.analysis import (
    overall_table,
    plot_test_figures,
    plot_training_monitor,
    summarize_test,
)
from multihop_offload_tpu.utils.profiling import phase_stats, phase_timer, reset_phases


def test_random_walk_preserves_connectivity(rng):
    adj, pos, _ = generators.connected_poisson_disk(30, seed=9)
    new_pos, new_adj = random_walk(pos, n_moving=5, step_std=0.1, rng=rng)
    assert build_topology(new_adj).connected
    assert new_pos.shape == pos.shape


def test_topology_update_link_migration(rng):
    adj, pos, _ = generators.connected_poisson_disk(30, seed=9)
    old = build_topology(adj)
    new_pos, new_adj = random_walk(pos, n_moving=5, step_std=0.2, rng=rng)
    new, link_map = topology_update(old, new_adj, pos=new_pos)
    # surviving links map back to the same endpoints
    for i, j in enumerate(link_map):
        if j >= 0:
            assert tuple(new.link_ends[i]) == tuple(old.link_ends[j])
    state = np.arange(old.num_links, dtype=np.float64)
    migrated = migrate_link_state(link_map, state, fill=-1.0)
    keep = link_map >= 0
    np.testing.assert_array_equal(migrated[keep], link_map[keep])
    assert (migrated[~keep] == -1).all()


def test_random_walk_degenerate_inputs_are_noops():
    """Empty fleet / zero movers / zero step return the input unchanged
    (a mobility trace must stall, not crash, on a degenerate slot)."""
    p0, a0 = random_walk(np.zeros((0, 2)), rng=np.random.default_rng(2))
    assert p0.shape == (0, 2) and a0.shape == (0, 0)

    pos = np.array([[0.0, 0.0], [0.5, 0.0]])
    for kw in (dict(n_moving=0), dict(step_std=0.0)):
        p, a = random_walk(pos, radius=1.0, rng=np.random.default_rng(3), **kw)
        np.testing.assert_array_equal(p, pos)
        assert build_topology(a).connected
        assert np.isfinite(p).all()


def test_random_walk_exhausted_budget_falls_back_to_no_move():
    """When no connected perturbation exists within the budget, the walk
    returns the unperturbed (connected) graph instead of raising; a walk
    from an already-disconnected graph still raises."""
    pos = np.array([[0.0, 0.0], [0.5, 0.0]])
    # std=100 clipped to (-10, 10): every candidate separates the pair
    new_pos, new_adj = random_walk(
        pos, n_moving=1, step_std=100.0, radius=1.0, bounds=(-10.0, 10.0),
        rng=np.random.default_rng(0), max_tries=5,
    )
    np.testing.assert_array_equal(new_pos, pos)
    assert build_topology(new_adj).connected

    with pytest.raises(RuntimeError, match="no connected perturbation"):
        random_walk(np.array([[0.0, 0.0], [5.0, 0.0]]), n_moving=1,
                    step_std=0.1, radius=1.0, rng=np.random.default_rng(1),
                    max_tries=3)


def test_linkless_topology_update_has_no_nan():
    """A re-wiring step that lands on a linkless graph must not emit NaN
    (np.nanmedian of zero link distances used to warn and poison the
    conflict threshold) and link-state migration must stay shape-correct."""
    import warnings

    old = build_topology(np.array([[0, 1], [1, 0]]))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        new, link_map = topology_update(
            old, np.zeros((2, 2)), pos=np.zeros((2, 2)), cf_radius=1.0,
        )
    assert new.num_links == 0 and link_map.shape == (0,)
    assert new.adj_conflict.shape == (0, 0)
    migrated = migrate_link_state(link_map, np.arange(1, dtype=np.float64))
    assert migrated.shape == (0,)


def _fake_test_csv(tmp_path):
    rows = []
    for n_nodes in [20, 30]:
        for algo in ["baseline", "local", "GNN"]:
            for ni in range(3):
                rows.append({
                    "filename": f"case_{n_nodes}.mat", "seed": 1,
                    "num_nodes": n_nodes, "m": 2, "num_mobile": 10,
                    "num_servers": 3, "num_relays": 1, "num_jobs": 5,
                    "n_instance": ni, "Algo": algo, "runtime": 0.01,
                    "tau": 10.0 + ni, "congest_jobs": ni % 2,
                    "gnn_bl_ratio": 1.0, "gap_2_bl": 0.0,
                })
    p = str(tmp_path / "Adhoc_test_data_fake.csv")
    pd.DataFrame(rows).to_csv(p, index=False)
    return p


def test_analysis_figures(tmp_path):
    p = _fake_test_csv(tmp_path)
    df = pd.read_csv(p)
    s = summarize_test(df)
    assert set(s["Algo"]) == {"baseline", "local", "GNN"}
    t = overall_table(df)
    assert "tau" in t.columns and len(t) == 3
    figs = plot_test_figures(p, out_dir=str(tmp_path / "fig"))
    assert len(figs) == 3 and all(os.path.isfile(f) for f in figs)


def test_training_monitor_plot(tmp_path):
    rows = []
    for fid in range(10):
        for m in ["baseline", "GNN"]:
            rows.append({"fid": fid, "method": m, "tau": 20 - fid,
                         "num_jobs": 4, "congest_jobs": 0})
    p = str(tmp_path / "aco_training_data_fake.csv")
    pd.DataFrame(rows).to_csv(p, index=False)
    out = plot_training_monitor(p, out_dir=str(tmp_path / "fig"))
    assert os.path.isfile(out)


def test_plot_routes_writes_file(tmp_path, small_cases):
    from multihop_offload_tpu.utils.visualization import plot_routes

    rec = small_cases[0]
    out = plot_routes(
        rec.topo, rec.topo.pos, np.flatnonzero(rec.roles == 1),
        rec.mobile_nodes[:3],
        np.random.default_rng(0).uniform(0, 5, rec.topo.num_links),
        np.zeros(rec.topo.n),
        str(tmp_path / "fig" / "routes.png"),
    )
    assert os.path.isfile(out)


def test_layout_positions_cache_roundtrip(tmp_path, small_cases):
    from multihop_offload_tpu.utils.visualization import layout_positions

    rec = small_cases[0]
    cache = str(tmp_path / "pos")
    a = layout_positions(rec.topo, case_name="c0", cache_dir=cache)
    assert a.shape == (rec.topo.n, 2)
    cache_file = os.path.join(cache, "graph_c_pos_c0.npy")
    assert os.path.isfile(cache_file)
    # second call must come from the cache, not a recompute
    np.save(cache_file, a + 7.0)
    b = layout_positions(rec.topo, case_name="c0", cache_dir=cache)
    np.testing.assert_array_equal(b, a + 7.0)
    # explicit array passes through; 'new' bypasses the cache
    np.testing.assert_array_equal(
        layout_positions(rec.topo, pos=a, case_name="c0", cache_dir=cache), a
    )
    fresh = layout_positions(rec.topo, pos="new", case_name="c0", cache_dir=cache)
    assert fresh.shape == (rec.topo.n, 2)
    with pytest.raises(ValueError):
        layout_positions(rec.topo, pos="bogus")


def test_plot_routes_geometry_free(tmp_path, small_cases):
    """BA/ER/WS cases carry no coordinates; pos=None must still render
    (reference node_positions, offloading_v3.py:152-165)."""
    from multihop_offload_tpu.utils.visualization import plot_routes

    rec = small_cases[0]
    out = plot_routes(
        rec.topo, None, np.flatnonzero(rec.roles == 1),
        rec.mobile_nodes[:3],
        np.random.default_rng(0).uniform(0, 5, rec.topo.num_links),
        np.zeros(rec.topo.n),
        str(tmp_path / "fig" / "routes_nopos.png"),
    )
    assert os.path.isfile(out)


def test_route_demo_cli(tmp_path, small_cases):
    from conftest import REFERENCE_DATA

    from multihop_offload_tpu.cli.plot import route_demo

    rec = small_cases[0]
    out = route_demo(
        os.path.join(REFERENCE_DATA, rec.filename),
        str(tmp_path / "fig"), pos_cache=str(tmp_path / "pos"),
    )
    assert os.path.isfile(out)
    assert any(f.endswith(".npy") for f in os.listdir(tmp_path / "pos"))


def test_phase_timers():
    reset_phases()
    with phase_timer("x"):
        pass
    with phase_timer("x"):
        pass
    s = phase_stats()
    assert s["x"]["count"] == 2 and s["x"]["total_s"] >= 0


def test_scalar_logger_writes_event_file(tmp_path):
    from multihop_offload_tpu.train.tb_logging import ScalarLogger

    lg = ScalarLogger(str(tmp_path / "tb"))
    if not lg.active:  # TF unavailable in this environment
        return
    lg.log_scalar("loss", 1.25, 0)
    lg.log_scalar("loss", 0.75, 1)
    lg.flush()
    import glob

    files = glob.glob(str(tmp_path / "tb" / "events.out.tfevents.*"))
    assert files and os.path.getsize(files[0]) > 0


def test_scalar_logger_disabled_is_noop():
    from multihop_offload_tpu.train.tb_logging import ScalarLogger

    lg = ScalarLogger("")
    assert not lg.active
    lg.log_scalar("x", 1.0, 0)  # must not raise
    lg.flush()


def test_validation_docs_derived_from_artifacts():
    """VALIDATION.md / BASELINE.md tables must regenerate bit-identically
    from the committed validation JSONs and the reference CSVs (the docs are
    derived, not transcribed — round-3 drift fix)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir("/root/reference/out"):
        pytest.skip("reference CSVs not available")
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "render_validation.py"),
         "--check"],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr


def test_bench_flop_helpers():
    """The MFU denominator math: the loop correction must add exactly the
    uncharged APSP/fixed-point passes, and the hand count must model K=1
    ChebConv WITHOUT dense support matmuls (benchmarks/flops_reconcile.json:
    the old 2E^2F term overcounted the actor 10x)."""
    import math
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from bench import _hand_flop_count, _loop_corrected_flops

    n, l, e, b = 104, 200, 304, 64
    iters = math.ceil(math.log2(n - 1))
    corrected = _loop_corrected_flops(1.0e9, n, l, b)
    assert corrected == 1.0e9 + (iters - 1) * 2.0 * b * n**3 \
        + 5 * 9 * 2.0 * b * l * l

    hand = _hand_flop_count(n, l, e, b, cheb_k=1)
    # isolate the ChebConv part: K=1 must have NO E^2 support term — it
    # sits far below even one dense support matmul over the batch
    apsp_term = b * 2 * n**3 * iters
    fp_term = b * 5 * 10 * 2 * l**2
    cheb1 = hand - apsp_term - fp_term
    assert 0 < cheb1 < b * 2 * e**2 * 32
    # K=2 adds exactly one support propagation per layer (3x for fwd+bwd)
    hand2 = _hand_flop_count(n, l, e, b, cheb_k=2)
    widths = [4, 32, 32, 32, 32]
    support = sum(2 * e**2 * f for f in widths)
    feature = sum(
        2 * e * fin * fout
        for fin, fout in zip([4, 32, 32, 32, 32], [32, 32, 32, 32, 1])
    )
    assert hand2 - hand == b * 3 * (support + feature)

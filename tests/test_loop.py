"""loop/: continual-learning flywheel — capture round-trip, gates, promotion.

The flywheel's correctness rests on four properties, each tested in
isolation here (the end-to-end path is `mho-loop --smoke`):

- experience round-trip: an "outcome" event written by the serving tick
  reconstructs the EXACT request, and the replay packer produces batches
  bit-identical to packing the original requests;
- the gate rule (`validate.apply_gates`) promotes/rejects correctly on
  synthetic score pairs, including the degenerate no-packets cases;
- the promotion state machine promotes through the no-retrace hot-reload
  path, structurally rejects a mismatched tree BEFORE touching the serving
  checkpoint dir, and rolls back to the champion at a fresh monotone step;
- run-log segment rotation keeps every row readable across the chain,
  including a truncated final line.
"""

import json
import os

import jax
import numpy as np
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.loop import experience
from multihop_offload_tpu.loop.promote import (
    PromotionController,
    monitor_ok,
)
from multihop_offload_tpu.loop.validate import apply_gates
from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs.registry import registry as obs_registry
from multihop_offload_tpu.serve.bucketing import pack_bucket
from multihop_offload_tpu.serve.workload import case_pool, request_stream
from multihop_offload_tpu.train import checkpoints as ckpt_lib

SIZES = [10, 16]


def _make_service(**cfg_kw):
    from multihop_offload_tpu.cli.serve import build_service

    cfg = Config(seed=7, dtype="float32", serve_slots=2, serve_queue_cap=16,
                 serve_deadline_s=60.0, serve_buckets=2,
                 model_root="/nonexistent-model-root", **cfg_kw)
    pool = case_pool(SIZES, per_size=1, seed=cfg.seed)
    return build_service(cfg, pool=pool)


# ---- log-segment rotation --------------------------------------------------


def test_log_rotation_and_spanning_reader(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = obs_events.RunLog(path, manifest={"event": "manifest", "ts": 0.0},
                            max_bytes=400)
    for i in range(40):
        log.emit("tick", i=i, pad="x" * 40)
    log.close()
    segs = obs_events.segment_paths(path)
    assert len(segs) >= 2, "log never rotated"
    assert segs[-1] == path  # active segment is last (newest)
    evs = list(obs_events.read_events(path))
    ticks = [e for e in evs if e["event"] == "tick"]
    assert [e["i"] for e in ticks] == list(range(40))  # nothing lost, in order
    # every rotated segment opens with a chain header
    headers = [e for e in evs if e["event"] == "segment"]
    assert len(headers) == len(segs) - 1
    assert [h["seq"] for h in headers] == sorted(h["seq"] for h in headers)
    # a crash can truncate ANY segment mid-line; the reader must survive
    with open(path, "a") as f:
        f.write('{"event": "tick", "i": 99, "trunc')
    ticks2 = [e for e in obs_events.read_events(path) if e["event"] == "tick"]
    assert [e["i"] for e in ticks2] == list(range(40))


def test_capture_sampling_is_deterministic_per_id():
    assert all(experience.sampled(i, 1.0) for i in range(50))
    assert not any(experience.sampled(i, 0.0) for i in range(50))
    picked = {i for i in range(2000) if experience.sampled(i, 0.5)}
    assert picked == {i for i in range(2000) if experience.sampled(i, 0.5)}
    assert 0.4 < len(picked) / 2000 < 0.6


# ---- experience round-trip -------------------------------------------------


@pytest.fixture(scope="module")
def captured(tmp_path_factory):
    """A small service with 100% capture draining 6 requests into a run log."""
    path = str(tmp_path_factory.mktemp("loop") / "run.jsonl")
    log = obs_events.RunLog(path, manifest={"event": "manifest", "ts": 0.0})
    obs_events.set_run_log(log)
    try:
        service, pool = _make_service(loop_capture_sample=1.0)
        reqs = list(request_stream(pool, 6, seed=11))
        for r in reqs:
            assert service.submit(r)
        responses = service.drain()
    finally:
        obs_events.set_run_log(None)
        log.close()
    return service, reqs, responses, path


def test_outcome_events_round_trip(captured):
    service, reqs, responses, path = captured
    outcomes = experience.read_outcomes(path)
    assert len(outcomes) == len(reqs)  # sample=1.0, nothing degraded
    by_id = {o.request.request_id: o for o in outcomes}
    resp_by_id = {r.request_id: r for r in responses}
    for req in reqs:
        o = by_id[req.request_id]
        r = resp_by_id[req.request_id]
        # the request rebuilds exactly: graph, roles, rates, job set
        np.testing.assert_array_equal(o.request.topo.adj, req.topo.adj)
        np.testing.assert_array_equal(o.request.roles, req.roles)
        np.testing.assert_allclose(o.request.proc_bws, req.proc_bws)
        np.testing.assert_allclose(o.request.link_rates, req.link_rates)
        np.testing.assert_array_equal(o.request.job_src, req.job_src)
        np.testing.assert_allclose(o.request.job_rate, req.job_rate)
        assert (o.request.ul, o.request.dl, o.request.t_max) == (
            req.ul, req.dl, req.t_max)
        # the decision and measurement ride along
        np.testing.assert_array_equal(o.dst, r.dst)
        np.testing.assert_array_equal(o.is_local, r.is_local)
        np.testing.assert_allclose(o.job_total, r.job_total, rtol=1e-6)
        assert o.served_by == "gnn" and not o.degraded
        assert o.tau == pytest.approx(float(np.mean(o.job_total)))


def test_replay_batches_bit_match_service_packing(captured):
    """The refit trainer must see exactly the padded layout that served the
    request: pack_bucket(reconstructed) == pack_bucket(original)."""
    service, reqs, _, path = captured
    outcomes = experience.read_outcomes(path)
    pad = experience.pad_for_outcomes(outcomes, round_to=8)
    by_id = {o.request.request_id: o for o in outcomes}
    for req in reqs:
        got = pack_bucket([by_id[req.request_id].request], pad, 1,
                          dtype=np.float32)
        want = pack_bucket([req], pad, 1, dtype=np.float32)
        for g, w in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    # batching: ceil(6 / 4) slots-sized batches, every leaf at slot width
    batches = list(experience.replay_batches(outcomes, pad, slots=4))
    assert len(batches) == 2
    for binst, bjobs in batches:
        for leaf in jax.tree_util.tree_leaves((binst, bjobs)):
            assert np.asarray(leaf).shape[0] == 4


def test_holdout_split_is_a_stable_partition(captured):
    _, _, _, path = captured
    outcomes = experience.read_outcomes(path)
    train, hold = experience.split_holdout(outcomes, 0.5)
    assert len(train) + len(hold) == len(outcomes)
    train2, hold2 = experience.split_holdout(list(reversed(outcomes)), 0.5)
    assert {o.request.request_id for o in hold} == {
        o.request.request_id for o in hold2}
    # frac=0 holds nothing out; frac=1 holds everything out
    assert experience.split_holdout(outcomes, 0.0)[1] == []
    assert experience.split_holdout(outcomes, 1.0)[0] == []


# ---- gate rule -------------------------------------------------------------


def _score(ratio, tau, generated=100):
    return {"generated": generated, "delivered": int(ratio * generated),
            "delivered_ratio": ratio, "mean_packet_delay": tau}


def test_gates_pass_within_budgets():
    ok, reasons = apply_gates(_score(0.95, 1.0), _score(0.94, 1.05),
                              max_delivered_drop=0.02, max_tau_ratio=1.10)
    assert ok and reasons == []


def test_gates_fail_on_delivered_drop():
    ok, reasons = apply_gates(_score(0.95, 1.0), _score(0.90, 1.0),
                              max_delivered_drop=0.02, max_tau_ratio=1.10)
    assert not ok and any("delivered_ratio" in r for r in reasons)


def test_gates_fail_on_tau_regression():
    ok, reasons = apply_gates(_score(0.95, 1.0), _score(0.95, 1.2),
                              max_delivered_drop=0.02, max_tau_ratio=1.10)
    assert not ok and any("mean_packet_delay" in r for r in reasons)


def test_gates_degenerate_packet_counts():
    # candidate delivered nothing at all -> hard fail
    dead = {"generated": 100, "delivered": 0, "delivered_ratio": 0.0,
            "mean_packet_delay": None}
    ok, reasons = apply_gates(_score(0.95, 1.0), dead,
                              max_delivered_drop=0.02, max_tau_ratio=1.10)
    assert not ok and any("no packets" in r for r in reasons)
    # champion delivered nothing but the candidate does -> tau gate passes
    # vacuously (nothing to regress against)
    ok, _ = apply_gates(dead, _score(0.5, 3.0),
                        max_delivered_drop=0.02, max_tau_ratio=1.10)
    assert ok


def test_monitor_rule():
    assert monitor_ok(None, 5.0, 1.5)        # no baseline: never roll back
    assert monitor_ok(1.0, None, 1.5)        # no post traffic: never roll back
    assert monitor_ok(1.0, 1.49, 1.5)
    assert not monitor_ok(1.0, 1.51, 1.5)


# ---- promotion state machine -----------------------------------------------


def test_promotion_state_machine(tmp_path):
    obs_registry().reset()
    service, _ = _make_service()
    model_dir = str(tmp_path / "model")
    ctl = PromotionController(model_dir)
    assert ctl.state == "idle"
    with pytest.raises(ValueError, match="unknown loop state"):
        ctl.transition("launched")

    # bootstrap a champion at step 1 and serve it
    champion = jax.tree_util.tree_map(np.asarray,
                                      service.executor.variables["params"])
    ckpt_lib.save_checkpoint(
        os.path.join(model_dir, "orbax"), 1, {"params": champion},
        lineage=ckpt_lib.make_lineage("offline"),
    )
    assert service.hot_reload(model_dir) == 1

    # a structurally wrong candidate is rejected BEFORE any save
    bad = {"params": {"oops": np.zeros((2, 2), np.float32)}}
    assert ctl.promote(service, bad, candidate_step=7) is None
    assert ctl.state == "rejected"
    assert service.executor.loaded_step == 1  # serving tree untouched
    assert ckpt_lib.latest_step(ctl.directory) == 1

    # a matching candidate promotes through hot-reload at a fresh step
    cand = jax.tree_util.tree_map(lambda x: np.asarray(x) + 0.5, champion)
    step = ctl.promote(service, {"params": cand}, candidate_step=7)
    assert step == 2 and ctl.state == "promoted"
    assert service.executor.loaded_step == 2
    assert service.executor.loaded_lineage["source"] == "refit"
    assert service.executor.loaded_lineage["parent_step"] == 7
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(
            service.executor.variables["params"])[0]),
        np.asarray(jax.tree_util.tree_leaves(cand)[0]),
    )

    # rollback re-pins the champion at the NEXT monotone step (orbax keeps
    # the first save per step id, so going "back" must go forward)
    rb = ctl.rollback(service, {"params": champion}, "measured regression",
                      failed_step=step)
    assert rb == 3 and ctl.state == "rolled_back"
    assert service.executor.loaded_step == 3
    lin = service.executor.loaded_lineage
    assert lin["source"] == "rollback" and lin["parent_step"] == 2
    assert lin["reason"] == "measured regression"
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(
            service.executor.variables["params"])[0]),
        np.asarray(jax.tree_util.tree_leaves(champion)[0]),
    )

    reg = obs_registry()
    assert reg.counter("mho_loop_promotions_total").total() == 1
    assert reg.counter("mho_loop_rejections_total").total() == 1
    assert reg.counter("mho_loop_rollbacks_total").total() == 1
    # intent states ("promoting"/"rolling_back") are journaled BEFORE the
    # save they announce, so a crash between intent and outcome resumes
    states = [h["state"] for h in ctl.history]
    assert states == ["rejected", "promoting", "promoted",
                      "rolling_back", "rolled_back"]


def test_promotion_canary_refuses_poisoned_candidate(tmp_path):
    """A NaN-poisoned candidate is signature-valid (same tree, same
    shapes) and would be checksum-valid once saved — only the canary's
    semantic probe can refuse it.  Refusal happens in the journaled
    'canarying' state BEFORE the write-ahead 'promoting' intent, so no
    poisoned step ever reaches the serving directory."""
    from multihop_offload_tpu.loop.canary import CheckpointCanary

    obs_registry().reset()
    service, pool = _make_service()
    model_dir = str(tmp_path / "model")
    ctl = PromotionController(model_dir)
    champion = jax.tree_util.tree_map(np.asarray,
                                      service.executor.variables["params"])
    ckpt_lib.save_checkpoint(
        os.path.join(model_dir, "orbax"), 1, {"params": champion},
        lineage=ckpt_lib.make_lineage("offline"),
    )
    assert service.hot_reload(model_dir) == 1
    canary = CheckpointCanary(service, pool, count=6, seed=11)
    canary.record_champion()

    poisoned = jax.tree_util.tree_map(
        lambda x: np.full_like(np.asarray(x), np.nan), champion)
    got = ctl.promote(service, {"params": poisoned}, candidate_step=7,
                      canary=canary)
    assert got is None and ctl.state == "rejected"
    assert service.executor.loaded_step == 1  # champion untouched
    assert ckpt_lib.latest_step(ctl.directory) == 1  # nothing saved
    states = [h["state"] for h in ctl.history]
    assert states[:2] == ["canarying", "rejected"]
    reg = obs_registry()
    assert reg.counter("mho_canary_rejections_total").total(
        stage="promote", reason="nonfinite_probe_outputs") == 1

    # the same canary lets a semantically-sane candidate through
    cand = jax.tree_util.tree_map(lambda x: np.asarray(x) + 1e-4, champion)
    step = ctl.promote(service, {"params": cand}, candidate_step=8,
                       canary=canary)
    assert step == 2 and ctl.state == "promoted"
    assert service.executor.loaded_step == 2


def test_canary_decision_collapse_is_deterministic():
    """The finite half of the gate: reversed-flat weights are finite
    everywhere (no nonfinite refusal possible) but scramble the decision
    head, so agreement against the recorded champion drops well below a
    strict threshold — and the champion itself always passes."""
    from multihop_offload_tpu.loop.canary import CheckpointCanary

    service, pool = _make_service()
    canary = CheckpointCanary(service, pool, count=6, seed=13,
                              min_agreement=0.95)
    assert canary.check(service.executor.variables) is None  # finiteness-only
    canary.record_champion()
    assert canary.check(service.executor.variables) is None  # self-agreement

    scrambled = jax.tree_util.tree_map(
        lambda x: np.ascontiguousarray(
            np.asarray(x).reshape(-1)[::-1].reshape(np.shape(x))),
        service.executor.variables,
    )
    why = canary.check(scrambled)
    assert why is not None and why.startswith("decision_collapse:")
    assert "agreement" in why and "< 0.95" in why
    assert canary.check(scrambled) == why  # deterministic probe set


def test_checkpoint_lineage_sidecar_round_trip(tmp_path):
    d = str(tmp_path / "orbax")
    params = {"params": {"w": np.ones((3,), np.float32)}}
    lin = ckpt_lib.make_lineage("offline", cfg=Config(seed=3),
                                extra={"note": "seed run"})
    ckpt_lib.save_checkpoint(d, 4, params, lineage=lin)
    got = ckpt_lib.load_lineage(d)  # defaults to latest step
    assert got["step"] == 4 and got["source"] == "offline"
    assert got["note"] == "seed run"
    assert got["config_hash"]  # hashed from the dataclass
    # the sidecar is plain JSON outside the orbax step dir
    raw = json.load(open(os.path.join(d, "lineage", "4.json")))
    assert raw == got
    assert ckpt_lib.load_lineage(d, step=99) is None

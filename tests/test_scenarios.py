"""scenarios/ subsystem: spec round-trip + hash, preset determinism,
realization axes (heterogeneous mu, correlated failures, mobility),
analytic-vs-sim agreement at low rho for EVERY topology family (one
compiled fleet program, one lane per family), and the shift-injector /
drift-campaign semantics.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multihop_offload_tpu.env.policies import baseline_policy
from multihop_offload_tpu.graphs.instance import PadSpec, stack_instances
from multihop_offload_tpu.loop.drift import shift_campaign
from multihop_offload_tpu.scenarios import (
    NEW_FAMILIES,
    PRESETS,
    ScenarioSpec,
    from_json,
    preset,
    preset_names,
    shift,
    spec_hash,
    to_json,
)
from multihop_offload_tpu.scenarios.build import (
    draw_topology,
    failure_schedules,
    mobility_step,
    realize,
)
from multihop_offload_tpu.sim.fidelity import (
    analytic_link_delay,
    empirical_queue_delays,
    scale_to_util,
)
from multihop_offload_tpu.sim.policies import make_policy
from multihop_offload_tpu.sim.runner import FleetSim
from multihop_offload_tpu.sim.state import build_sim_params, spec_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MATRIX_RECORD = os.path.join(REPO, "benchmarks", "scenario_matrix.json")

# one representative preset per family (the low-rho fidelity fleet)
FAMILY_REPS = {
    "ba": "ba_poisson",
    "ws": "ws_diurnal",
    "er": "er_hetero",
    "grp": "grp_flash",
    "poisson": "poisson_mobility",
    "grid": "grid_poisson",
    "corridor": "corridor_mmpp",
    "two_tier": "two_tier_poisson",
}


def _shared_pad(specs, lanes=1, round_to=8):
    max_n = max(s.n_nodes for s in specs)
    max_j = max(s.num_jobs for s in specs)
    max_l = 0
    for s in specs:
        for i in range(lanes):
            adj, _ = draw_topology(s, lane=i)
            max_l = max(max_l, int(np.triu(adj, 1).sum()))
    rt = round_to
    return PadSpec(n=-(-max_n // rt) * rt, l=-(-max_l // rt) * rt, s=rt,
                   j=max(max_j, rt))


# ---------------------------------------------------------------------------
# spec: JSON round-trip, hash, validation
# ---------------------------------------------------------------------------


def test_every_preset_round_trips_and_hash_is_content_stable():
    for name in preset_names():
        s = preset(name)
        rt = from_json(to_json(s))
        assert rt == s, name
        h = spec_hash(s)
        assert h == spec_hash(rt) == spec_hash(s)  # pure content hash
        assert len(h) == 12 and int(h, 16) >= 0
    # the hash keys on content: any field change moves it, including name
    a = preset("ba_poisson")
    assert spec_hash(dataclasses.replace(a, seed=a.seed + 1)) != spec_hash(a)
    assert spec_hash(dataclasses.replace(a, name="renamed")) != spec_hash(a)


def test_committed_matrix_record_hashes_match_the_registry():
    """The committed record rows carry each spec's content hash — editing a
    preset without re-running `mho-scenarios --matrix` breaks this."""
    with open(MATRIX_RECORD) as f:
        record = json.load(f)
    assert len(record["scenarios"]) >= 12
    for row in record["scenarios"]:
        assert row["hash"] == spec_hash(preset(row["name"])), row["name"]
    assert set(record["new_families_covered"]) == set(NEW_FAMILIES)


def test_spec_validation_rejects_bad_worlds():
    with pytest.raises(ValueError, match="unknown topology family"):
        ScenarioSpec(name="x", family="smallworld")
    with pytest.raises(ValueError, match="util"):
        ScenarioSpec(name="x", util=1.5)
    with pytest.raises(ValueError, match="geometric"):
        # mobility needs coordinates; BA has none
        from multihop_offload_tpu.scenarios import MobilitySpec
        ScenarioSpec(name="x", family="ba", mobility=MobilitySpec())
    with pytest.raises(KeyError, match="unknown scenario preset"):
        preset("nope")


def test_registry_covers_new_families_and_axes():
    fams = {s.family for s in PRESETS.values()}
    assert set(NEW_FAMILIES) <= fams
    assert any(s.mu_spread > 0 for s in PRESETS.values())
    assert any(s.failures for s in PRESETS.values())
    assert any(s.mobility is not None for s in PRESETS.values())
    assert any(not s.objective.is_null for s in PRESETS.values())


# ---------------------------------------------------------------------------
# build: determinism, heterogeneous mu, failure/mobility schedules
# ---------------------------------------------------------------------------


def test_realize_deterministic_per_seed_and_lane():
    s = preset("grid_poisson")
    pad = _shared_pad([s])
    a = realize(s, pad, lane=0)
    b = realize(s, pad, lane=0)
    np.testing.assert_array_equal(a.topo.adj, b.topo.adj)
    np.testing.assert_array_equal(np.asarray(a.inst.link_rates),
                                  np.asarray(b.inst.link_rates))
    np.testing.assert_array_equal(np.asarray(a.jobs.src),
                                  np.asarray(b.jobs.src))
    np.testing.assert_array_equal(a.proc_bws, b.proc_bws)
    # a different lane is a different seeded world (positions jitter even
    # on the lattice families)
    c = realize(s, pad, lane=1)
    assert not np.array_equal(a.pos, c.pos)


def test_heterogeneous_mu_is_a_seeded_spread():
    pad = _shared_pad([preset("er_hetero"), preset("ba_poisson")])
    het = realize(preset("er_hetero"), pad, lane=0)
    servers = set(int(x) for x in het.servers)
    srv = np.array([het.proc_bws[i] for i in servers])
    assert np.unique(np.round(srv, 9)).size == len(servers)  # spread, not nominal
    hom = realize(preset("ba_poisson"), pad, lane=0)
    expect = np.where(np.isin(np.arange(16), hom.servers), 100.0, 8.0)
    np.testing.assert_allclose(hom.proc_bws, expect)


def test_failure_schedules_links_and_blast_semantics():
    total = 400
    s = preset("corridor_links_fail")
    pad = _shared_pad([s, preset("ba_blast")])
    r = realize(s, pad, lane=0)
    fl, fn = failure_schedules(s, r, pad, total, lane=0)
    assert fl.shape == (pad.l,) and fn.shape == (pad.n,)
    assert fl.dtype == np.int32 and fn.dtype == np.int32
    hit = np.flatnonzero(fl >= 0)
    assert hit.size == 2 and (fl[hit] == total // 2).all()
    assert (hit < r.topo.num_links).all()  # padded tail never scheduled
    assert (fn == -1).all()

    b = preset("ba_blast")
    rb = realize(b, pad, lane=0)
    flb, fnb = failure_schedules(b, rb, pad, total, lane=0)
    assert (flb == -1).all()
    killed = set(np.flatnonzero(fnb >= 0).tolist())
    assert killed, "blast killed nobody"
    protected = set(int(x) for x in rb.servers) | set(
        int(x) for x in np.asarray(rb.jobs.src)[np.asarray(rb.jobs.mask)])
    assert not (killed & protected), "blast hit a protected node"


def test_mobility_step_keeps_pad_and_maps_links():
    s = preset("poisson_mobility")
    pad = _shared_pad([s])
    r = realize(s, pad, lane=0)
    new_r, link_map = mobility_step(s, r, pad)
    assert np.asarray(new_r.inst.link_rates).shape \
        == np.asarray(r.inst.link_rates).shape  # same pad, same programs
    assert not np.array_equal(new_r.pos, r.pos)
    link_map = np.asarray(link_map)
    surviving = link_map[link_map >= 0]
    assert (surviving < r.topo.num_links).all()
    np.testing.assert_array_equal(new_r.proc_bws, r.proc_bws)  # compute stays


# ---------------------------------------------------------------------------
# analytic vs sim at low rho — every family, one compiled program
# ---------------------------------------------------------------------------


def test_low_rho_analytic_vs_sim_agreement_every_family():
    """One lane per topology family through the SAME compiled baseline
    fleet at bottleneck rho ~0.35: per-channel empirical sojourn agrees
    with the analytic 1/(mu - lambda) within 35% traffic-weighted per
    lane (the committed scenario_matrix.json runs longer horizons), and
    packet conservation is exact on every family — including the
    heterogeneous-mu lanes."""
    specs = [preset(FAMILY_REPS[f]) for f in sorted(FAMILY_REPS)]
    pad = _shared_pad(specs)
    bp = jax.jit(baseline_policy)
    reals, outs, paramss = [], [], []
    for i, s in enumerate(specs):
        r = realize(s, pad, lane=0)
        jobs, out = scale_to_util(r.inst, r.jobs, jax.random.PRNGKey(i),
                                  0.35, policy_fn=bp)
        r = dataclasses.replace(r, jobs=jobs)
        reals.append(r)
        outs.append(out)
        paramss.append(build_sim_params(r.inst, r.jobs, margin=6.0))
    spec_sim = spec_for(reals[0].inst, reals[0].jobs, cap=64)
    sim = FleetSim(spec_sim, make_policy("baseline"), rounds=2,
                   slots_per_round=1600)
    keys = jax.random.split(jax.random.PRNGKey(17), len(specs))
    run = sim.run(stack_instances([r.inst for r in reals]),
                  stack_instances([r.jobs for r in reals]),
                  stack_instances(paramss), keys,
                  init_rates=jnp.stack([r.jobs.rate for r in reals]))
    compared = 0
    for lane, s in enumerate(specs):
        st = jax.tree_util.tree_map(lambda x: np.asarray(x)[lane], run.state)
        gen = int(st.generated.sum())
        gap = gen - int(st.delivered.sum()) - int(st.dropped.sum()) \
            - int(st.count[:-1].sum())
        assert gap == 0, f"{s.family}: conservation gap {gap}"
        assert gen > 0 and int(st.delivered.sum()) > 0, s.family
        dt = float(np.asarray(paramss[lane].dt))
        emp_l, _ = empirical_queue_delays(st, spec_sim, dt, min_served=40)
        ana_l = analytic_link_delay(reals[lane].inst, outs[lane])
        lam = np.asarray(outs[lane].delays.link_lambda, np.float64)
        ok = np.isfinite(emp_l) & np.isfinite(ana_l) & (lam > 0)
        assert ok.any(), f"{s.family}: no comparable links at this horizon"
        rel = np.abs(emp_l[ok] - ana_l[ok]) / ana_l[ok]
        w = lam[ok] / lam[ok].sum()
        assert float((rel * w).sum()) < 0.35, s.family
        compared += int(ok.sum())
    assert compared >= 16


# ---------------------------------------------------------------------------
# shift injectors + drift campaign
# ---------------------------------------------------------------------------


def test_shift_tick_semantics():
    a, b = preset("ba_poisson"), preset("grp_flash")
    sched = shift(a, b, 4)
    assert [sched.spec_at(t).name for t in (0, 3, 4, 5)] \
        == ["ba_poisson", "ba_poisson", "grp_flash", "grp_flash"]
    with pytest.raises(ValueError, match="at_tick"):
        shift(a, b, 0)
    events = sched.outcome_events(8, seed=1)
    assert len(events) == 8
    assert [e["shift_side"] for e in events] == ["from"] * 4 + ["to"] * 4
    assert all({"tau", "is_local", "job_rate"} <= set(e) for e in events)
    # deterministic per (schedule, ticks, seed)
    assert events == sched.outcome_events(8, seed=1)
    assert events != sched.outcome_events(8, seed=2)


def test_shift_campaign_detects_after_the_switch_only():
    row = shift_campaign(shift(preset("ba_poisson"), preset("grp_flash"), 32),
                         96)
    assert row["warmup_ok"] and row["detected"]
    assert not row["false_positive"]
    assert row["tripped_at"] >= 32 and row["detection_delay"] >= 0
    assert row["trips"], "no trip records from the detectors"
    # at_tick inside the warmup window voids the measurement, reported
    # honestly rather than raised
    short = shift_campaign(shift(preset("ba_poisson"), preset("grp_flash"),
                                 8), 48)
    assert not short["warmup_ok"]


def test_campaign_report_bookkeeping_is_consistent():
    """The report's fields cannot contradict each other, whatever the
    detectors do: detected <=> (tripped_at >= at_tick), false_positive <=>
    (tripped_at < at_tick), and the two are mutually exclusive — checked
    on a stationary from==to schedule where any trip is detector noise."""
    a = preset("ba_poisson")
    row = shift_campaign(shift(a, a, 48), 96)
    assert not (row["detected"] and row["false_positive"])
    if row["tripped_at"] is None:
        assert not row["detected"] and not row["false_positive"]
    elif row["tripped_at"] >= row["at_tick"]:
        assert row["detected"] and row["detection_delay"] \
            == row["tripped_at"] - row["at_tick"]
    else:
        assert row["false_positive"] and row["detection_delay"] is None

"""obs/ subsystem: registry, spans, run log, retrace hooks, report, CLI."""

import json
import os
import threading

import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs import jaxhooks
from multihop_offload_tpu.obs.events import RunLog, read_events, run_manifest
from multihop_offload_tpu.obs.registry import MetricRegistry, registry
from multihop_offload_tpu.obs.spans import (
    current_phase,
    phase_stats,
    reset_phases,
    span,
)


# ---- registry ---------------------------------------------------------------

def test_registry_counter_gauge_histogram_basics():
    reg = MetricRegistry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2.0, route="a")
    assert c.value() == 1.0
    assert c.value(route="a") == 2.0
    assert c.total() == 3.0
    with pytest.raises(ValueError):
        c.inc(-1.0)

    g = reg.gauge("g", "a gauge")
    g.set(5.0)
    g.inc(1.5)
    assert g.value() == 6.5
    assert g.value(missing="x") is None

    h = reg.histogram("h_seconds", "a histogram")
    for v in (0.002, 0.002, 0.3):
        h.observe(v)
    s = h.stats()
    assert s["count"] == 3
    assert s["min_s"] == pytest.approx(0.002)
    assert s["max_s"] == pytest.approx(0.3)
    assert s["total_s"] == pytest.approx(0.304)

    # kind clash fails loudly instead of silently aliasing
    with pytest.raises(TypeError):
        reg.gauge("c_total")


def test_registry_prometheus_exposition_golden():
    reg = MetricRegistry()
    reg.counter("req_total", "requests").inc(3, route="a")
    reg.counter("req_total").inc(1, route="b")
    reg.gauge("depth").set(7)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(2.0)
    assert reg.prometheus_text() == (
        "# TYPE depth gauge\n"
        "depth 7\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1.0"} 2\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_sum 2.55\n"
        "lat_seconds_count 3\n"
        "# HELP req_total requests\n"
        "# TYPE req_total counter\n"
        'req_total{route="a"} 3\n'
        'req_total{route="b"} 1\n'
    )


def test_registry_concurrent_increments_not_lost():
    reg = MetricRegistry()
    n, threads = 2000, 2

    def worker():
        c = reg.counter("shared_total")
        h = reg.histogram("shared_seconds")
        for _ in range(n):
            c.inc()
            h.observe(0.01)

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert reg.counter("shared_total").total() == n * threads
    assert reg.histogram("shared_seconds").stats()["count"] == n * threads


# ---- spans ------------------------------------------------------------------

def test_span_nesting_ids_and_phase_stats():
    reset_phases()
    assert current_phase() == ""
    with span("outer") as outer:
        assert current_phase() == "outer"
        with span("outer/inner") as inner:
            assert current_phase() == "outer/inner"
            assert inner["parent_id"] == outer["span_id"]
            assert inner["trace_id"] == outer["trace_id"]
        assert current_phase() == "outer"
    assert current_phase() == ""
    s = phase_stats()
    assert s["outer"]["count"] == 1 and s["outer/inner"]["count"] == 1
    for rec in s.values():
        assert rec["min_s"] <= rec["mean_s"] <= rec["max_s"]
        assert rec["total_s"] >= 0
    reset_phases()
    assert phase_stats() == {}


def test_legacy_profiling_shim_still_works():
    # utils.profiling deprecated into obs.spans; old call sites keep working
    from multihop_offload_tpu.utils.profiling import (
        phase_stats as ps,
        phase_timer,
        reset_phases as rp,
    )

    rp()
    with phase_timer("legacy"):
        pass
    assert ps()["legacy"]["count"] == 1
    rp()


# ---- run log (JSONL) --------------------------------------------------------

def test_runlog_jsonl_schema_roundtrip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest=run_manifest(role="test"))
    log.step(epoch=0, fid=3, wall_s=0.5, loss=1.25)
    log.tick(n=1, served=4, queue_depth=2)
    log.checkpoint(step=10, kind="best")
    log.summary(phases={"train/step": {"count": 1, "total_s": 0.5}},
                metrics={})
    log.close()

    rows = list(read_events(path))
    assert [r["event"] for r in rows] == [
        "manifest", "step", "tick", "checkpoint", "summary",
    ]
    man = rows[0]
    assert man["role"] == "test" and man["schema_version"] == 1
    assert "jax_version" in man and "platform" in man
    assert rows[1]["fid"] == 3 and rows[1]["loss"] == 1.25
    assert rows[2]["queue_depth"] == 2
    assert rows[4]["phases"]["train/step"]["count"] == 1
    assert all("ts" in r for r in rows)


def test_runlog_rotation_exact_boundary_never_splits_a_segment(tmp_path):
    """Regression guard for the rotation edge: a row landing EXACTLY on
    `max_bytes` must complete the current segment (rotation is strictly
    greater-than), and a ``segment`` header must only ever be a segment's
    first row — never interleaved mid-file by a write racing the boundary."""
    from multihop_offload_tpu.obs.events import segment_paths

    path = str(tmp_path / "run.jsonl")
    manifest = {"event": "manifest", "ts": 0}
    len_m = len(json.dumps(manifest) + "\n")
    # n stays single-digit so every tick row has identical length
    len_t = len(json.dumps({"event": "tick", "ts": 1, "n": 1}) + "\n")
    log = RunLog(path, manifest=manifest, max_bytes=len_m + 2 * len_t)
    for n in range(1, 8):
        log._write({"event": "tick", "ts": 1, "n": n})
    log.close()

    segs = segment_paths(path)
    assert len(segs) >= 2
    # the first segment holds the manifest plus BOTH ticks: the second tick
    # ends exactly at max_bytes and must not have triggered a rotation
    with open(segs[0]) as f:
        first = [json.loads(ln) for ln in f if ln.strip()]
    assert [r["event"] for r in first] == ["manifest", "tick", "tick"]
    # headers only ever lead a segment
    for seg in segs:
        with open(seg) as f:
            rows = [json.loads(ln) for ln in f if ln.strip()]
        assert rows, f"empty segment {seg}"
        for i, r in enumerate(rows):
            if r["event"] == "segment":
                assert i == 0, f"mid-segment header in {seg}"
    # nothing lost or reordered across the chain
    ns = [r["n"] for r in read_events(path) if r["event"] == "tick"]
    assert ns == list(range(1, 8))


def test_read_events_tolerates_truncated_tail(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "manifest", "ts": 0}) + "\n")
        f.write('{"event": "step", "truncat')  # crashed mid-write
    rows = list(read_events(path))
    assert len(rows) == 1 and rows[0]["event"] == "manifest"


def test_read_events_survives_torn_bytes_at_rotation_boundary(tmp_path):
    """Regression: a crash can tear the FINAL record of a segment that had
    already rotated — a partial JSON line cut mid-UTF-8-sequence, no
    newline.  Text-mode iteration used to raise UnicodeDecodeError on the
    invalid bytes, killing the reader generator so every LATER segment
    silently vanished: a torn mid-chain record looked like end-of-log."""
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest={"event": "manifest", "ts": 0.0},
                 max_bytes=300)
    for i in range(30):
        log.emit("tick", n=i, pad="x" * 32)
    log.close()
    segs = obs_events.segment_paths(path)
    assert len(segs) >= 3, "chain too short to put the tear mid-chain"
    # tear the end of a MID-chain segment: truncate its last record and
    # append bytes that are not valid UTF-8 (a real torn write is byte-,
    # not character-, aligned)
    with open(segs[1], "r+b") as f:
        f.truncate(os.path.getsize(segs[1]) - 7)
        f.seek(0, os.SEEK_END)
        f.write(b'{"event": "tick", "ts\xff\xfe')
    ns = [r["n"] for r in read_events(path) if r["event"] == "tick"]
    # one record lost to the tear; everything in LATER segments survives
    assert ns[-1] == 29
    assert len(ns) >= 28
    assert ns == sorted(ns)
    # and a whole segment going missing doesn't hide the rest either
    os.remove(segs[1])
    ns2 = [r["n"] for r in read_events(path) if r["event"] == "tick"]
    assert ns2[-1] == 29


def test_runlog_restart_rotates_previous_segment_aside(tmp_path):
    """Crash-restart semantics: re-opening a RunLog at a path holding a
    previous (killed) run's events must preserve them as a rotated
    segment, not truncate — durable consumers (crash-resume, the
    flywheel's experience reader) need every outcome already on disk."""
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest={"event": "manifest", "ts": 0.0})
    log.emit("outcome", n=1)
    log.close()
    log2 = RunLog(path, manifest={"event": "manifest", "ts": 1.0})
    log2.emit("outcome", n=2)
    log2.close()
    assert len(obs_events.segment_paths(path)) == 2
    ns = [r["n"] for r in read_events(path) if r["event"] == "outcome"]
    assert ns == [1, 2]  # the killed run's outcome survived the restart


def test_span_emit_writes_event_row(tmp_path):
    log = RunLog(str(tmp_path / "run.jsonl"))
    obs_events.set_run_log(log)
    try:
        with span("coarse", emit=True, detail="x"):
            pass
    finally:
        obs_events.set_run_log(None)
        log.close()
    rows = list(read_events(log.path))
    spans = [r for r in rows if r["event"] == "span"]
    assert len(spans) == 1
    assert spans[0]["name"] == "coarse" and spans[0]["detail"] == "x"
    assert spans[0]["duration_s"] >= 0


# ---- jax hooks: retrace / compile tracking ----------------------------------

def test_retrace_counter_catches_injected_shape_change():
    jaxhooks.install()
    jaxhooks.clear_steady()

    @jax.jit
    def f(x):
        return (x * 2.0).sum()

    reg = registry()

    with span("obs-test/warm"):
        f(jnp.zeros(8)).block_until_ready()
        f(jnp.ones(8)).block_until_ready()  # cache hit: no new trace
    warm = reg.counter("jax_retraces_total").value(phase="obs-test/warm")
    assert warm >= 1  # first call traced (>=1: nested pjit may multi-fire)

    jaxhooks.mark_steady()
    try:
        before = jaxhooks.unexpected_retraces()
        with span("obs-test/steady"):
            f(jnp.zeros(8)).block_until_ready()  # same shape: still cached
        assert jaxhooks.unexpected_retraces() == before

        with span("obs-test/leak"):
            f(jnp.zeros(16)).block_until_ready()  # injected shape change
        assert jaxhooks.unexpected_retraces() > before
        assert reg.counter("jax_unexpected_retraces_total").value(
            phase="obs-test/leak") >= 1
    finally:
        jaxhooks.clear_steady()


# ---- report + CLI -----------------------------------------------------------

def test_report_renders_phases_and_retraces(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest=run_manifest(role="train"))
    log.step(epoch=0, fid=0, wall_s=0.2)
    log.summary(
        phases={
            "train/build": {"count": 2, "total_s": 0.5, "mean_s": 0.25,
                            "min_s": 0.2, "max_s": 0.3},
            "train/step": {"count": 2, "total_s": 1.5, "mean_s": 0.75,
                           "min_s": 0.7, "max_s": 0.8},
        },
        metrics={
            "jax_retraces_total": {
                "kind": "counter", "help": "",
                "series": {'{phase="train/step"}': 3.0},
            },
            "jax_unexpected_retraces_total": {
                "kind": "counter", "help": "",
                "series": {'{phase="train/step"}': 1.0},
            },
        },
    )
    log.close()

    from multihop_offload_tpu.obs.report import load_run, render_report

    run = load_run(path)
    assert run["manifest"]["role"] == "train"
    text = render_report(path)
    assert "train/build" in text and "train/step" in text
    assert "input-wait" in text
    assert "unexpected" in text and "PERF BUG" in text

    from multihop_offload_tpu.cli.obs import main as obs_main

    assert obs_main([path]) == 0
    assert obs_main([path, "--json"]) == 0


def test_start_finish_run_wiring(tmp_path):
    import types

    from multihop_offload_tpu import obs

    assert obs.start_run(types.SimpleNamespace(obs_log=""), role="x") is None

    cfg = types.SimpleNamespace(
        obs_log=str(tmp_path / "run.jsonl"),
        obs_prom=str(tmp_path / "metrics.prom"),
    )
    log = obs.start_run(cfg, role="smoke")
    assert obs_events.get_run_log() is log
    registry().counter("obs_smoke_total").inc()
    with span("smoke/phase"):
        pass
    obs.finish_run(log)
    assert obs_events.get_run_log() is None

    rows = list(read_events(cfg.obs_log))
    assert rows[0]["event"] == "manifest" and rows[0]["role"] == "smoke"
    assert rows[-1]["event"] == "summary"
    assert "smoke/phase" in rows[-1]["phases"]
    assert "obs_smoke_total" in rows[-1]["metrics"]
    prom = open(cfg.obs_prom).read()
    assert "obs_smoke_total 1" in prom


def test_graceful_drain_latches_and_polls():
    import signal as _signal

    from multihop_offload_tpu.utils.signals import GracefulDrain

    drain = GracefulDrain(signals=(_signal.SIGUSR1,)).install()
    try:
        assert not drain.requested and drain.signum is None
        _signal.raise_signal(_signal.SIGUSR1)
        assert drain.requested and drain.signum == _signal.SIGUSR1
    finally:
        drain.uninstall()
    # programmatic request (embedding loops, tests) takes the same path
    d2 = GracefulDrain()
    d2.request()
    assert d2.requested and d2.signum == _signal.SIGTERM


def test_terminal_close_seals_chain_next_run_needs_no_rotate_aside(tmp_path):
    """The graceful-drain shutdown contract: `close(terminal=True)` seals
    the active segment into the rotated chain, so a restarted process at
    the SAME path opens a fresh segment without the crash rotate-aside —
    and the spanning reader sees both runs, each a clean segment ending in
    its own summary."""
    from multihop_offload_tpu.obs.events import segment_paths

    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest={"event": "manifest", "ts": 0.0, "run": 1})
    log.tick(n=1)
    log.summary(metrics={})
    log.close(terminal=True)
    # sealed: nothing left at `path`; the segment lives in the chain
    assert not os.path.exists(path)
    assert [os.path.basename(p) for p in segment_paths(path)] == [
        "run.jsonl.0000"]

    log2 = RunLog(path, manifest={"event": "manifest", "ts": 1.0, "run": 2})
    log2.tick(n=2)
    log2.summary(metrics={})
    log2.close(terminal=True)
    assert [os.path.basename(p) for p in segment_paths(path)] == [
        "run.jsonl.0000", "run.jsonl.0001"]

    # spanning reader: both runs, in order, nothing duplicated by a
    # rotate-aside (each segment starts with its own manifest)
    rows = list(read_events(path))
    assert [r["n"] for r in rows if r["event"] == "tick"] == [1, 2]
    assert [r["run"] for r in rows if r["event"] == "manifest"] == [1, 2]
    for seg in segment_paths(path):
        seg_rows = [json.loads(line) for line in open(seg)]
        assert seg_rows[0]["event"] == "manifest"
        assert seg_rows[-1]["event"] == "summary"

    # double-close stays idempotent and never invents a new segment
    log2.close(terminal=True)
    assert len(segment_paths(path)) == 2


def test_finish_run_terminal_routes_the_drain_contract(tmp_path):
    """`obs.finish_run(log, terminal=True)` — what mho-serve/mho-loop call
    on an orderly drain — appends the summary and seals the segment."""
    import types

    from multihop_offload_tpu import obs

    cfg = types.SimpleNamespace(obs_log=str(tmp_path / "run.jsonl"))
    log = obs.start_run(cfg, role="drain")
    obs_events.emit("shutdown", reason="signal", signum=15)
    obs.finish_run(log, terminal=True)
    assert not os.path.exists(cfg.obs_log)  # sealed, not left behind
    rows = list(read_events(cfg.obs_log))
    assert rows[-1]["event"] == "summary"
    assert any(r["event"] == "shutdown" and r["signum"] == 15 for r in rows)

"""Graph layer: topology arrays vs NetworkX oracles, padding, .mat IO."""

import warnings

import networkx as nx
import numpy as np
import pytest

from multihop_offload_tpu.graphs import generators
from multihop_offload_tpu.graphs.instance import PadSpec, build_instance, build_jobset
from multihop_offload_tpu.graphs.matio import (
    load_case_mat,
    reference_link_order,
    save_case_mat,
)
from multihop_offload_tpu.graphs.topology import build_topology, sample_link_rates


def _random_topo(seed, n=25, m=2):
    adj, _ = generators.barabasi_albert(n, m=m, seed=seed)
    return build_topology(adj)


@pytest.mark.parametrize("seed", [0, 7])
def test_line_graph_matches_networkx(seed):
    topo = _random_topo(seed)
    g = nx.from_numpy_array(topo.adj)
    lg = nx.line_graph(g)
    # same number of links and conflict edges
    assert topo.num_links == lg.number_of_nodes()
    assert int(topo.adj_lg.sum()) // 2 == lg.number_of_edges()
    # adjacency agrees link-by-link under the canonical indexing
    for (a, b), (c, d) in lg.edges:
        i = topo.link_index[a, b]
        j = topo.link_index[c, d]
        assert topo.adj_lg[i, j] == 1 and topo.adj_lg[j, i] == 1
    # conflict degrees equal line-graph degrees when cf_radius == 0
    for (a, b), deg in lg.degree:
        assert topo.cf_degs[topo.link_index[a, b]] == deg


def test_link_index_symmetric_and_complete():
    topo = _random_topo(3)
    iu, ju = np.nonzero(np.triu(topo.adj, 1))
    for u, v in zip(iu, ju):
        li = topo.link_index[u, v]
        assert li == topo.link_index[v, u] >= 0
        assert tuple(topo.link_ends[li]) == (u, v)
    assert (topo.link_index[topo.adj == 0] == -1).all()


def test_connected_flag_matches_networkx():
    topo = _random_topo(1)
    assert topo.connected == nx.is_connected(nx.from_numpy_array(topo.adj))
    # two disconnected triangles
    adj = np.zeros((6, 6), dtype=np.uint8)
    for a, b in [(0, 1), (1, 2), (0, 2), (3, 4), (4, 5), (3, 5)]:
        adj[a, b] = adj[b, a] = 1
    assert not build_topology(adj).connected


def test_cf_radius_adds_conflicts():
    adj, pos = generators.poisson_disk(30, nb=5, seed=5)
    t0 = build_topology(adj, pos=pos, cf_radius=0.0)
    t2 = build_topology(adj, pos=pos, cf_radius=2.0)
    assert t2.adj_conflict.sum() >= t0.adj_conflict.sum()
    assert (t2.adj_conflict >= t2.adj_lg).all()
    assert (np.diag(t2.adj_conflict) == 0).all()
    assert (t2.adj_conflict == t2.adj_conflict.T).all()


def test_sample_link_rates_bounds(rng):
    topo = _random_topo(2)
    base = rng.uniform(30, 70, topo.num_links)
    rates = sample_link_rates(topo, base, std=2.0, rng=rng)
    assert rates.shape == (topo.num_links,)
    assert (rates >= 0).all() and (rates <= base + 6).all()
    assert (rates == np.round(rates)).all()


def test_instance_padding_and_ext_layout(rng):
    topo = _random_topo(4, n=20)
    n, l = topo.n, topo.num_links
    roles = np.zeros(n, dtype=np.int32)
    roles[[1, 5]] = 1  # servers
    roles[[2]] = 2     # relay
    bws = np.where(roles == 1, 100.0, np.where(roles == 2, 0.0, 8.0))
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=48, s=4, j=16)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=np.float64)

    assert inst.adj.shape == (24, 24) and inst.adj_ext.shape == (72, 72)
    assert inst.node_mask.sum() == n and inst.link_mask.sum() == l
    # servers ascending with mask
    assert list(inst.servers[:2]) == [1, 5] and inst.server_mask.sum() == 2
    # pseudo-link slots: rate = proc_bw, flags aligned
    assert np.allclose(inst.ext_rate[pad.l : pad.l + n], bws)
    assert inst.ext_self_loop[pad.l + 2] == 0  # relay has no pseudo-link
    assert inst.ext_as_server[pad.l + 1] == 1
    assert inst.ext_mask.sum() == l + (n - 1)  # one relay
    # ext adjacency: real link slot <-> pseudo slot of its endpoints (non-relay)
    u, v = topo.link_ends[0]
    assert inst.adj_ext[0, pad.l + u] == (1.0 if roles[u] != 2 else 0.0)
    # pad link rows are inert
    assert (inst.adj_conflict[l:, :] == 0).all()
    assert (inst.link_rates[l:] == 1.0).all()


def test_jobset_padding():
    js = build_jobset([3, 4], [0.1, 0.2], pad_jobs=8, dtype=np.float64)
    assert js.mask.sum() == 2 and js.rate[2:].sum() == 0
    assert js.ul[0] == 100.0 and js.dl[0] == 1.0


def test_mat_roundtrip(tmp_path, rng):
    adj, pos = generators.barabasi_albert(20, seed=11)
    pos = generators.spring_positions(adj, seed=0)
    topo = build_topology(adj)
    rates = rng.uniform(30, 70, topo.num_links)
    nodes_info = np.zeros((20, 2), dtype=np.int64)
    nodes_info[:, 1] = 8
    nodes_info[0] = [1, 200]
    p = str(tmp_path / "case.mat")
    save_case_mat(p, adj, rates, nodes_info, pos, seed=11, m=2, gtype="ba")
    rec = load_case_mat(p)
    assert rec.topo.n == 20 and rec.seed == 11
    assert np.allclose(rec.link_rates, rates)  # canonical order round-trips
    assert rec.num_servers == 1 and (rec.roles == nodes_info[:, 0]).all()


def test_load_reference_cases(small_cases):
    for rec in small_cases:
        assert rec.topo.connected
        assert rec.link_rates.shape[0] == rec.topo.num_links
        assert (rec.link_rates >= 30 - 1e-9).all() and (rec.link_rates <= 70 + 1e-9).all()
        assert rec.num_servers > 0 and rec.mobile_nodes.size > 0
        # reference order permutation is a bijection
        perm = reference_link_order(rec.topo.adj)
        assert np.sort(perm).tolist() == list(range(rec.topo.num_links))


def test_generators_shapes():
    for name in ["ba", "grp", "ws", "er", "poisson"]:
        adj, pos = generators.generate(name, 30, seed=2)
        assert adj.shape == (30, 30)
        assert (adj == adj.T).all() and (np.diag(adj) == 0).all()
    adj, pos, nb = generators.connected_poisson_disk(25, seed=3)
    assert nx.is_connected(nx.from_numpy_array(adj))


@pytest.mark.parametrize("name", ["grid", "corridor", "two_tier"])
@pytest.mark.parametrize("seed", [0, 11])
def test_new_families_connected_deterministic_and_contract(name, seed):
    """The scenario matrix's planned-deployment families: connected by
    construction, deterministic per seed, and honoring the (adj, pos)
    shape/dtype contract every family shares."""
    n = 18
    adj, pos = generators.generate(name, n, seed=seed)
    assert adj.shape == (n, n) and adj.dtype == np.uint8
    assert (adj == adj.T).all() and (np.diag(adj) == 0).all()
    assert set(np.unique(adj)) <= {0, 1}
    assert pos is not None and pos.shape == (n, 2)
    assert np.issubdtype(pos.dtype, np.floating)
    assert nx.is_connected(nx.from_numpy_array(adj))
    adj2, pos2 = generators.generate(name, n, seed=seed)
    np.testing.assert_array_equal(adj, adj2)
    np.testing.assert_array_equal(pos, pos2)
    adj3, _ = generators.generate(name, n, seed=seed + 1)
    if name == "two_tier":  # lattices are seed-independent in adjacency
        assert not np.array_equal(adj, adj3)


def test_corridor_and_grid_shape_knobs():
    adj_c, _ = generators.generate("corridor", 16, seed=0, width=2)
    adj_g, _ = generators.generate("grid", 16, seed=0)
    g_c = nx.from_numpy_array(adj_c)
    g_g = nx.from_numpy_array(adj_g)
    # a 2-wide corridor is strictly longer end to end than a square grid
    assert nx.diameter(g_c) > nx.diameter(g_g)


def test_two_tier_cluster_heads_are_highest_degree():
    """Degree-ranked placement (the scenario builder's rule) must land on
    the cluster heads — the edge gateways every cluster multihops through
    (nodes core..core+clusters-1 by construction)."""
    core, clusters = 2, 3
    adj, _ = generators.generate("two_tier", 17, seed=3, core=core,
                                 clusters=clusters)
    deg = adj.sum(axis=1)
    ranked = np.argsort(-deg, kind="stable")[:clusters]
    assert set(int(r) for r in ranked) == set(range(core, core + clusters))


def test_er_grp_retry_to_connected_with_typed_warning():
    """Sparse nominal parameters force the densify-retry: the draw still
    comes back connected and the typed warning marks the fallback."""
    for fam, kwargs in [("er", {"degree": 1.2}), ("grp", {"p_in": 0.05,
                                                          "p_out": 0.01})]:
        hit = False
        for seed in range(20):
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                adj, _ = generators.generate(fam, 24, seed=seed, **kwargs)
            assert nx.is_connected(nx.from_numpy_array(adj)), (fam, seed)
            if any(issubclass(x.category,
                              generators.DisconnectedGraphWarning)
                   for x in w):
                hit = True
                break
        assert hit, f"{fam}: no draw engaged the retry fallback in 20 seeds"


def test_generate_rejects_unknown_family_and_dishonest_kwargs():
    with pytest.raises(ValueError, match="unsupported graph model"):
        generators.generate("smallworld", 16, seed=0)
    # the legacy density shorthand only maps onto ba/poisson
    with pytest.raises(ValueError, match="does not take the density"):
        generators.generate("ws", 16, seed=0, m=3)
    with pytest.raises(ValueError, match="unknown parameter"):
        generators.generate("grid", 16, seed=0, width=2)
    adj, _ = generators.generate("ba", 16, seed=0, m=3)
    assert adj.sum() // 2 == (16 - 3) * 3  # m threads through for ba


def test_spring_positions_cache(tmp_path):
    """Layout caching (reference pickles under ../pos/,
    `offloading_v3.py:152-163`): second call hits the cache; `fresh=True`
    recomputes."""
    from multihop_offload_tpu.graphs.generators import barabasi_albert, spring_positions

    adj, _ = barabasi_albert(12, seed=4)
    p1 = spring_positions(adj, seed=1, cache_dir=str(tmp_path), name="case12")
    assert (tmp_path / "case12.npy").is_file()
    p2 = spring_positions(adj, seed=999, cache_dir=str(tmp_path), name="case12")
    np.testing.assert_array_equal(p1, p2)  # cache hit ignores the new seed
    p3 = spring_positions(adj, seed=999, cache_dir=str(tmp_path), name="case12",
                          fresh=True)
    assert not np.array_equal(p1, p3)


def test_init_distributed_single_process_noop(monkeypatch):
    """With no cluster context in the environment the helper must return 0
    without touching jax.distributed (this host exports axon's
    TPU_WORKER_HOSTNAMES, which must be cleared to simulate a plain box)."""
    from multihop_offload_tpu.parallel.mesh import init_distributed

    for hint in (
        "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_ID",
        "OMPI_COMM_WORLD_SIZE", "TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID",
    ):
        monkeypatch.delenv(hint, raising=False)
    assert init_distributed() == 0

"""Learning-dynamics smoke tests: the full actor/critic/replay loop moves the
policy in the right direction, and the real (K>=2) spectral GNN trains too."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.agent import (
    forward_backward,
    forward_env,
    make_optimizer,
    replay_apply,
    replay_init,
    replay_remember,
)
from multihop_offload_tpu.models import ChebNet, chebyshev_support

import __graft_entry__ as graft

_JAX_VERSION = tuple(int(x) for x in jax.__version__.split(".")[:2])


@pytest.fixture(scope="module")
def world():
    binst, bjobs, pad = graft._make_batch(
        num_cases=6, n_nodes=24, pad_round=8, dtype=np.float64, seed=11
    )
    return binst, bjobs, pad


def _mean_tau(model, variables, binst, bjobs, key, support_fn=None):
    def one(i, jb, k):
        support = support_fn(i) if support_fn else None
        out, _ = forward_env(model, variables, i, jb, k, support=support)
        tot = out.delays.job_total
        return jnp.sum(jnp.where(jb.mask, tot, 0.0)) / jnp.maximum(jb.mask.sum(), 1)

    keys = jax.random.split(key, bjobs.src.shape[0])
    return float(jnp.mean(jax.vmap(one)(binst, bjobs, keys)))


def test_mse_supervision_descends(world):
    """With the policy-sensitivity term off (critic_weight=0), the training
    step is supervised regression of the predicted unit-delay matrix onto the
    empirical one — repeated updates on a fixed workload must reduce the MSE."""
    binst, bjobs, pad = world
    model = ChebNet(num_layer=3, hidden=16, param_dtype=jnp.float64)
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((pad.e, 4), jnp.float64),
        jnp.zeros((pad.e, pad.e), jnp.float64),
    )
    import optax

    opt = optax.adam(3e-3)
    opt_state = opt.init(variables["params"])
    i0 = jax.tree_util.tree_map(lambda x: x[0], binst)
    jb0 = jax.tree_util.tree_map(lambda x: x[0], bjobs)
    step = jax.jit(
        lambda v, k: forward_backward(
            model, v, i0, jb0, k, explore=0.0, mse_weight=1.0, critic_weight=0.0
        )
    )
    key = jax.random.PRNGKey(5)
    mses = []
    for _ in range(25):
        out = step(variables, key)
        mses.append(float(out.loss_mse))
        updates, opt_state = opt.update(out.grads["params"], opt_state)
        import optax as _o

        variables = {"params": _o.apply_updates(variables["params"], updates)}
    assert np.isfinite(mses).all()
    # the optimizer recovers from the first-step transient and drives the
    # regression loss far below its peak
    assert min(mses[-5:]) < 0.1 * max(mses)


def test_replay_training_loop_runs(world):
    """The full reference-style loop (memorize + sampled sequential replay)
    stays finite and moves the weights (`AdHoc_train.py:187`)."""
    binst, bjobs, pad = world
    model = ChebNet(num_layer=3, hidden=16, param_dtype=jnp.float64)
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((pad.e, 4), jnp.float64),
        jnp.zeros((pad.e, pad.e), jnp.float64),
    )
    cfg = Config(learning_rate=3e-4, batch=8)
    opt = make_optimizer(cfg)
    opt_state = opt.init(variables["params"])
    mem = replay_init(variables["params"], capacity=64)

    step = jax.jit(
        lambda v, i, jb, k: forward_backward(model, v, i, jb, k, explore=0.1)
    )
    key = jax.random.PRNGKey(1)
    p0 = np.asarray(variables["params"]["cheb_0"]["kernel"]).copy()
    losses = []
    count = 0
    for it in range(6):
        keys = jax.random.split(jax.random.PRNGKey(100 + it), 6)
        round_losses = []
        for b in range(6):
            i = jax.tree_util.tree_map(lambda x: x[b], binst)
            jb = jax.tree_util.tree_map(lambda x: x[b], bjobs)
            out = step(variables, i, jb, keys[b])
            mem = replay_remember(mem, out.grads["params"], out.loss_critic,
                                  out.loss_mse)
            count += 1
            round_losses.append(float(out.loss_critic))
        losses.append(np.mean(round_losses))
        if count >= cfg.batch:
            key, k = jax.random.split(key)
            params, opt_state, _, _ = replay_apply(
                mem, variables["params"], opt_state, opt, k, batch=cfg.batch
            )
            variables = {"params": params}
    assert np.isfinite(losses).all()
    assert not np.allclose(p0, np.asarray(variables["params"]["cheb_0"]["kernel"]))


def test_default_support_matches_model_order(world):
    """`support=None` must resolve per model order: raw extended adjacency
    at k=1 (the reference's shipped behavior), rescaled Laplacian at k>=2.
    Round-3 regression: the k>=2 default silently fell back to the raw
    adjacency, leaving the spectral policy so badly scaled that 300
    training visits never changed a single offloading decision."""
    from multihop_offload_tpu.agent.actor import default_support

    binst, bjobs, pad = world
    i0 = jax.tree_util.tree_map(lambda x: x[0], binst)
    jb0 = jax.tree_util.tree_map(lambda x: x[0], bjobs)

    m1 = ChebNet(num_layer=3, hidden=16, k=1, param_dtype=jnp.float64)
    np.testing.assert_array_equal(
        np.asarray(default_support(m1, i0)), np.asarray(i0.adj_ext)
    )
    m2 = ChebNet(num_layer=3, hidden=16, k=2, param_dtype=jnp.float64)
    expect = chebyshev_support(i0.adj_ext, i0.ext_mask)
    np.testing.assert_array_equal(
        np.asarray(default_support(m2, i0)), np.asarray(expect)
    )

    # the default reaches both entry points: support=None == explicit
    variables = m2.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), expect
    )
    _, a_none = forward_env(m2, variables, i0, jb0, jax.random.PRNGKey(3))
    _, a_sup = forward_env(m2, variables, i0, jb0, jax.random.PRNGKey(3),
                           support=expect)
    np.testing.assert_array_equal(np.asarray(a_none.lam), np.asarray(a_sup.lam))
    out_none = forward_backward(m2, variables, i0, jb0, jax.random.PRNGKey(2))
    out_sup = forward_backward(m2, variables, i0, jb0, jax.random.PRNGKey(2),
                               support=expect)
    np.testing.assert_array_equal(
        np.asarray(out_none.loss_critic), np.asarray(out_sup.loss_critic)
    )


def test_k2_spectral_gnn_trains(world):
    """The real ChebConv (K=2, rescaled-Laplacian support) produces finite,
    nonzero, adjacency-dependent gradients through the full pipeline."""
    binst, bjobs, pad = world
    model = ChebNet(num_layer=3, hidden=16, k=2, param_dtype=jnp.float64)
    i0 = jax.tree_util.tree_map(lambda x: x[0], binst)
    jb0 = jax.tree_util.tree_map(lambda x: x[0], bjobs)
    support = chebyshev_support(i0.adj_ext, i0.ext_mask)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), support
    )
    # keep the output ReLU alive at init: raw features reach ~70 and the
    # spectral support amplifies hidden magnitudes, so glorot init can leave
    # every output pre-activation negative (zero gradient).  Shrink kernels
    # so the +1 output bias dominates while all layers still carry gradient.
    params = jax.tree_util.tree_map(lambda p: p * 0.01, variables["params"])
    params["cheb_2"]["bias"] = params["cheb_2"]["bias"] + 1.0
    variables = {"params": params}
    out = forward_backward(
        model, variables, i0, jb0, jax.random.PRNGKey(2), support=support
    )
    flat, _ = jax.flatten_util.ravel_pytree(out.grads)
    assert np.isfinite(np.asarray(flat)).all() and np.abs(np.asarray(flat)).sum() > 0
    # K=2 kernels carry gradient on the T1 (adjacency) term as well
    g1 = np.asarray(out.grads["params"]["cheb_0"]["kernel"])[1]
    assert np.abs(g1).sum() > 0
    # and the support actually changes the prediction (unlike K=1)
    _, actor_a = forward_env(model, variables, i0, jb0, jax.random.PRNGKey(3),
                             support=support)
    _, actor_b = forward_env(model, variables, i0, jb0, jax.random.PRNGKey(3),
                             support=jnp.zeros_like(support))
    lam_diff = np.max(np.abs(np.asarray(actor_a.lam) - np.asarray(actor_b.lam)))
    assert lam_diff > 1e-9  # small-kernel init makes the T1 term small but real
    tau = _mean_tau(model, variables, binst, bjobs, jax.random.PRNGKey(4),
                    support_fn=lambda i: chebyshev_support(i.adj_ext, i.ext_mask))
    assert np.isfinite(tau)


@pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason="replay-loss decline threshold (3% between halves) calibrated for "
    f"the jax>=0.5 PRNG/optimizer stream; jax {jax.__version__} lands at "
    "~2.9% on the identical recipe",
)
def test_midscale_training_improves_heldout_tau(tmp_path, monkeypatch):
    """Mid-scale integration (round-2 verdict #7): ~20 generated networks,
    3 epochs of the reference's critic recipe — replay updates must reduce
    the replay (critic) loss AND the trained model must beat the fresh-init
    model on held-out workloads (same seed -> identical workloads)."""
    import pandas as pd

    from multihop_offload_tpu.cli.datagen import generate_dataset
    from multihop_offload_tpu.train.driver import Evaluator, Trainer

    monkeypatch.chdir(tmp_path)
    data = str(tmp_path / "aco_mid")
    generate_dataset(data, gtype="ba", size=10, seed0=900,
                     graph_sizes=[20, 30], verbose=False)
    kw = dict(datapath=data, T=800, arrival_scale=0.15, dtype="float32",
              num_instances=4, batch=20, memory_size=200, seed=5, mesh_data=1,
              critic_weight=1.0, learning_rate=1e-4, epochs=3)

    cfg = Config(out=str(tmp_path / "out"), model_root=str(tmp_path / "m_tr"),
                 training_set="MID", **kw)
    tr = Trainer(cfg)
    tr.run(verbose=False)

    # replay updates reduce the sampled critic loss.  The decline plateaus
    # quickly (the critic loss is the analytic TOTAL delay, mostly
    # irreducible once the policy is near-optimal), so assert on halves:
    # calibration 249.2 -> 230.7 under the suite's x64 config
    rl = tr.replay_losses
    assert len(rl) >= 20
    half = len(rl) // 2
    assert np.mean(rl[half:]) < 0.97 * np.mean(rl[:half]), (
        f"replay loss did not decline: first half {np.mean(rl[:half]):.1f} "
        f"last half {np.mean(rl[half:]):.1f}"
    )

    # held-out comparison: fresh-init vs trained weights, identical workloads
    def gnn_tau(model_root):
        ev = Evaluator(Config(out=str(tmp_path / f"out_{os.path.basename(model_root)}"),
                              model_root=model_root, training_set="MID", **kw))
        ev.try_restore()
        df = pd.read_csv(ev.run(verbose=False))
        return (float(np.nanmean(df[df.Algo == "GNN"]["tau"])),
                float(np.nanmean(df[df.Algo == "local"]["tau"])))

    tau_fresh, _ = gnn_tau(str(tmp_path / "m_fresh"))
    tau_trained, tau_local = gnn_tau(str(tmp_path / "m_tr"))
    # calibration: fresh 67.7 -> trained 20.5 (= local); margins are wide
    assert tau_trained < 0.7 * tau_fresh, (tau_trained, tau_fresh)
    assert tau_trained < 1.3 * tau_local, (tau_trained, tau_local)

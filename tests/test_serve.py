"""serve/: batched decision service — parity, backpressure, degradation.

The load-bearing property is bit-parity: demux(route(batch(requests)))
must realize the SAME decisions as running each request alone through
`agent.policy.forward_env` at the same pad shape with the same structural
key.  Batching is then purely a throughput transform — it can never change
what the service answers.
"""

import os

import jax
import numpy as np
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.graphs.instance import (
    build_instance,
    build_jobset,
    compute_hop_matrix,
)
from multihop_offload_tpu.serve.bucketing import pack_bucket
from multihop_offload_tpu.serve.workload import case_pool, request_stream

SIZES = [10, 16]


def _make_service(slots=3, queue_cap=16, deadline_s=60.0, clock=None, **cfg_kw):
    """Small 2-bucket service on synthetic traffic; fresh-init weights."""
    from multihop_offload_tpu.cli.serve import build_service
    from multihop_offload_tpu.serve.service import OffloadService

    cfg = Config(seed=7, dtype="float32", serve_slots=slots,
                 serve_queue_cap=queue_cap, serve_deadline_s=deadline_s,
                 serve_buckets=2, model_root="/nonexistent-model-root",
                 **cfg_kw)
    pool = case_pool(SIZES, per_size=1, seed=cfg.seed)
    service, pool = build_service(cfg, pool=pool)
    if clock is not None:
        # injectable time: rebuild with the deterministic clock, same programs
        service = OffloadService(
            service.executor.model, service.executor.variables,
            service.buckets, slots=slots, queue_cap=queue_cap,
            deadline_s=deadline_s, seed=cfg.seed, clock=clock,
        )
    return service, pool


@pytest.fixture(scope="module")
def served():
    """One shared service + a drained mixed-bucket stream: 5 requests land
    round-robin as 3+2 across the 2 buckets, so slots=2 leaves a
    partially-filled final batch in bucket 0 and needs exactly 2 ticks."""
    service, pool = _make_service(slots=2)
    reqs = list(request_stream(pool, 5, seed=11))
    for r in reqs:
        assert service.submit(r)
    responses = service.drain()
    return service, reqs, responses


def test_smoke_two_ticks(served):
    service, reqs, responses = served
    # tick 1 serves 2+2 (one program per bucket), tick 2 the leftover 1
    assert service.stats.ticks == 2
    assert service.executor.dispatch_count == 3
    assert sorted(r.request_id for r in responses) == sorted(
        r.request_id for r in reqs
    )
    by_id = {r.request_id: r for r in responses}
    for req in reqs:
        resp = by_id[req.request_id]
        assert resp.served_by == "gnn"
        assert resp.dst.shape == (req.num_jobs,)
        assert resp.is_local.shape == (req.num_jobs,)
        # every chosen node exists in THIS request's graph (pad rows never
        # leak out of the demux)
        assert (resp.dst >= 0).all() and (resp.dst < req.topo.n).all()
        assert np.isfinite(resp.delay_est).all()
        assert resp.latency_s >= 0.0
    # dispatch amortization: strictly fewer programs than requests
    assert service.executor.dispatch_count < len(reqs)
    s = service.stats.summary(wall_s=1.0)
    assert s["served"] == len(reqs) and s["degraded"] == 0
    assert s["dispatches_per_request"] < 1.0


def test_batched_decisions_bit_identical_to_single_instance(served):
    """The ISSUE's property test: mixed buckets + partially-filled final
    batch, each demuxed decision bit-identical to the single-instance
    `forward_env` at the same pad shape and structural key."""
    from multihop_offload_tpu.agent.policy import forward_env

    service, reqs, responses = served
    model = service.executor.model
    variables = service.executor.variables
    by_id = {r.request_id: r for r in responses}
    buckets_seen = set()
    for req in reqs:
        b = service.buckets.bucket_for(*req.sizes)
        buckets_seen.add(b)
        pad = service.buckets[b]
        inst = build_instance(
            req.topo, req.roles, req.proc_bws, req.link_rates, req.t_max,
            pad, dtype=service.dtype,
            hop=compute_hop_matrix(req.topo, pad.n),
        )
        jobs = build_jobset(
            req.job_src, req.job_rate, pad_jobs=pad.j, ul=req.ul, dl=req.dl,
            dtype=service.dtype,
        )
        outcome, _ = forward_env(
            model, variables, inst, jobs, service.request_key(req.request_id)
        )
        nj = req.num_jobs
        resp = by_id[req.request_id]
        np.testing.assert_array_equal(
            resp.dst, np.asarray(outcome.decision.dst)[:nj]
        )
        np.testing.assert_array_equal(
            resp.is_local, np.asarray(outcome.decision.is_local)[:nj]
        )
        np.testing.assert_allclose(
            resp.delay_est, np.asarray(outcome.decision.delay_est)[:nj],
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            resp.job_total, np.asarray(outcome.job_total)[:nj],
            rtol=1e-5, atol=1e-6,
        )
    assert len(buckets_seen) == 2, "stream did not exercise both buckets"


def test_pack_bucket_pads_by_repeating_last(served):
    service, reqs, _ = served
    b = service.buckets.bucket_for(*reqs[0].sizes)
    pad = service.buckets[b]
    binst, bjobs = pack_bucket([reqs[0]], pad, 3, dtype=service.dtype)
    # filler slots repeat the last real entry: identical leaves, static width
    leaf = jax.tree_util.tree_leaves(binst)[0]
    assert np.asarray(leaf).shape[0] == 3
    for arr in jax.tree_util.tree_leaves(binst):
        a = np.asarray(arr)
        np.testing.assert_array_equal(a[1], a[0])
        np.testing.assert_array_equal(a[2], a[0])


def test_backpressure_bounded_queue():
    service, pool = _make_service(slots=2, queue_cap=3)
    reqs = list(request_stream(pool, 6, seed=21))
    admitted = [service.submit(r) for r in reqs[:3]]
    assert all(admitted)
    assert not service.submit(reqs[3]), "submit beyond queue_cap must refuse"
    assert service.stats.rejected == 1
    service.tick()  # frees capacity
    assert service.submit(reqs[3])
    # an over-sized graph is refused as too_large, never queued
    big = next(iter(request_stream(case_pool([40], per_size=1, seed=5), 1)))
    assert service.buckets.bucket_for(*big.sizes) is None
    assert not service.submit(big)
    assert service.stats.too_large == 1


def test_deadline_degrades_to_baseline():
    """A tick past the deadline budget serves its batch with the analytic
    greedy baseline — same decisions as `env.policies.baseline_policy` run
    alone, flagged `served_by='baseline'`."""
    from multihop_offload_tpu.env.policies import baseline_policy

    t = [100.0]
    service, pool = _make_service(slots=2, deadline_s=0.5, clock=lambda: t[0])
    reqs = list(request_stream(pool, 2, seed=31))
    for r in reqs:
        service.submit(r)
    t[0] += 10.0  # the service fell behind: oldest wait >> deadline
    responses = service.drain()
    assert len(responses) == len(reqs)
    assert all(r.served_by == "baseline" for r in responses)
    assert service.stats.degraded == len(reqs)
    by_id = {r.request_id: r for r in responses}
    for req in reqs:
        b = service.buckets.bucket_for(*req.sizes)
        pad = service.buckets[b]
        inst = build_instance(
            req.topo, req.roles, req.proc_bws, req.link_rates, req.t_max,
            pad, dtype=service.dtype,
            hop=compute_hop_matrix(req.topo, pad.n),
        )
        jobs = build_jobset(
            req.job_src, req.job_rate, pad_jobs=pad.j, ul=req.ul, dl=req.dl,
            dtype=service.dtype,
        )
        o = baseline_policy(inst, jobs, service.request_key(req.request_id))
        nj = req.num_jobs
        np.testing.assert_array_equal(
            by_id[req.request_id].dst, np.asarray(o.decision.dst)[:nj]
        )


def test_hot_reload_swaps_weights_without_retrace():
    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    service, pool = _make_service(slots=2)
    req = next(iter(request_stream(pool, 1, seed=41)))
    service.submit(req)
    r0 = service.drain()[0]
    programs_before = service.executor._steps  # the compiled-step table

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        bumped = jax.tree_util.tree_map(
            lambda x: np.asarray(x) + 0.25, service.executor.variables["params"]
        )
        ckpt_lib.save_checkpoint(os.path.join(d, "orbax"), 5, {"params": bumped})
        assert service.hot_reload(d) == 5
        assert service.executor.loaded_step == 5
        assert service.hot_reload(d) is None  # already current
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(
                service.executor.variables["params"])[0]),
            np.asarray(jax.tree_util.tree_leaves(bumped)[0]),
        )
        # same compiled programs, new weights, (generically) new decisions
        assert service.executor._steps is programs_before
        service.submit(req)
        r1 = service.drain()[0]
        assert r1.dst.shape == r0.dst.shape
        # a wrong-architecture checkpoint must fail loudly at reload time
        wrong = {"params": {"oops": np.zeros((2, 2), np.float32)}}
        ckpt_lib.save_checkpoint(os.path.join(d, "orbax"), 6, wrong)
        with pytest.raises(ValueError, match="do not match"):
            service.hot_reload(d)


def test_repeated_hot_reloads_stay_steady(tmp_path):
    """The flywheel's serving invariant: N successive orbax hot-reloads are
    pure weight swaps — `jax_unexpected_retraces_total` stays at 0 once the
    service declares steady state, and the loaded step is monotone."""
    from multihop_offload_tpu.obs import jaxhooks
    from multihop_offload_tpu.obs.registry import registry as obs_registry
    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    obs_registry().reset()
    service, pool = _make_service(slots=2)
    req = next(iter(request_stream(pool, 1, seed=51)))
    d = str(tmp_path / "model")
    base = jax.tree_util.tree_map(
        np.asarray, service.executor.variables["params"]
    )
    # first save+reload also warms the orbax restore path pre-steady
    ckpt_lib.save_checkpoint(os.path.join(d, "orbax"), 1, {"params": base})
    assert service.hot_reload(d) == 1
    service.submit(req)
    service.drain()  # compiles the bucket's decision program
    jaxhooks.mark_steady()
    try:
        steps = [service.executor.loaded_step]
        for k in range(2, 6):
            bumped = jax.tree_util.tree_map(lambda x: x + 0.01 * k, base)
            ckpt_lib.save_checkpoint(
                os.path.join(d, "orbax"), k, {"params": bumped}
            )
            assert service.hot_reload(d) == k
            steps.append(service.executor.loaded_step)
            service.submit(req)
            service.drain()  # serve THROUGH the swapped weights, post-steady
        assert steps == sorted(steps) == [1, 2, 3, 4, 5]
        assert jaxhooks.unexpected_retraces() == 0, (
            "hot reload retraced after steady state"
        )
    finally:
        jaxhooks.clear_steady()


def test_ragged_occupancy_sweep_bit_identical():
    """The ladder's load-bearing property: at every occupancy rung (1,
    slots/4, slots/2, full) the ragged service realizes decisions
    bit-identical to the dense full-width service — width is purely a
    throughput transform, like batching itself."""
    slots = 4
    dense, pool = _make_service(slots=slots, queue_cap=64)
    ragged, _ = _make_service(slots=slots, queue_cap=64, serve_ragged=True)
    occupancies = [1, max(1, slots // 4), slots // 2, slots]
    # repeat the low rungs so the EWMA actually narrows the ladder before
    # the parity comparison at those occupancies
    schedule = occupancies + [1, 1, slots // 2]
    n_req = sum(schedule)
    reqs_a = list(request_stream(pool, n_req, seed=61))
    reqs_b = list(request_stream(pool, n_req, seed=61))

    def run(service, reqs):
        responses, it = [], iter(reqs)
        for k in schedule:
            for _ in range(k):
                assert service.submit(next(it))
            responses.extend(service.tick())
        responses.extend(service.drain())
        return {r.request_id: r for r in responses}

    by_dense = run(dense, reqs_a)
    by_ragged = run(ragged, reqs_b)
    assert sorted(by_dense) == sorted(by_ragged) and len(by_dense) == n_req
    for rid, r in by_ragged.items():
        d = by_dense[rid]
        np.testing.assert_array_equal(r.dst, d.dst)
        np.testing.assert_array_equal(r.is_local, d.is_local)
        np.testing.assert_allclose(r.delay_est, d.delay_est,
                                   rtol=1e-5, atol=1e-6)
    # the ladder narrowed (rung programs really served) and the occupancy
    # telemetry flowed: histogram series + pad-waste counter are live
    assert ragged.ladder is not None
    assert ragged.ladder.transitions, "sweep never narrowed the ladder"
    assert any(w < slots for (_, w) in ragged.executor._rungs)
    from multihop_offload_tpu.obs.registry import registry as obs_registry

    snap = obs_registry().snapshot()
    assert "mho_serve_bucket_occupancy" in snap
    assert "mho_serve_pad_waste_slots_total" in snap


def test_ladder_merge_split_hysteresis():
    """OccupancyLadder unit rows: immediate widen on a burst, one-rung
    narrowing only after the (hysteresis-inflated) EWMA clears the rung."""
    from multihop_offload_tpu.serve.bucketing import OccupancyLadder

    lad = OccupancyLadder(1, 8, alpha=0.5, hysteresis=0.25)
    assert lad.rungs == [1, 2, 4, 8] and lad.width_of(0) == 8
    # cold trickle: narrowing is gradual (one rung per tick, EWMA-gated)
    widths = []
    for _ in range(6):
        w = lad.select(0, 1)
        widths.append(w)
        lad.observe(0, 1)
    assert widths[0] == 8, "first tick must not narrow below the EWMA"
    assert widths == sorted(widths, reverse=True), "narrowing skipped a rung"
    assert lad.width_of(0) == 2, (
        "live=1 settles at rung 2: ewma->1 never clears 1*(1+hysteresis)"
    )
    # a burst widens in ONE step, no hysteresis — queued work is never
    # clipped below what full slots would take
    assert lad.select(0, 7) == 8
    assert lad.width_of(0) == 8
    # width always covers min(pending, slots)
    for pending in (1, 3, 5, 9):
        assert lad.select(0, pending) >= min(pending, lad.slots)
    # jitter around a rung boundary must not thrash: with the EWMA still
    # burst-inflated, a single cold tick cannot narrow
    lad2 = OccupancyLadder(1, 8, alpha=0.5, hysteresis=0.25)
    lad2.observe(0, 8)
    assert lad2.select(0, 1) == 8
    transitions_before = len(lad2.transitions)
    assert lad2.select(0, 1) == 8  # ewma 8 -> still > 4/(1+h)
    assert len(lad2.transitions) == transitions_before


def test_overlap_conservation_exactly_once():
    """Overlapped ticks answer every admitted request exactly once: the
    responses just arrive one tick later (the final batch on drain)."""
    slots = 2
    service, pool = _make_service(slots=slots, queue_cap=64,
                                  serve_ragged=True, serve_overlap=True)
    reqs = list(request_stream(pool, 9, seed=71))
    seen = []
    it = iter(reqs)
    # interleave submits with ticks, including empty-queue ticks mid-stream
    for k in (2, 0, 3, 1, 0, 2, 1):
        for _ in range(k):
            assert service.submit(next(it))
        seen.extend(service.tick())
    seen.extend(service.drain())
    ids = sorted(r.request_id for r in seen)
    assert ids == sorted(r.request_id for r in reqs)
    assert len(ids) == len(set(ids)) == len(reqs)
    assert not service._pending and service.queue_depth == 0
    s = service.stats.summary(wall_s=1.0)
    assert s["served"] == len(reqs)


def test_width_transitions_zero_unexpected_retraces():
    """Ladder width changes compile rung programs inside expected_rebuild:
    after steady state, narrowing and re-widening must not count a single
    unexpected retrace (the bench-matrix invariant, pinned here)."""
    from multihop_offload_tpu.obs import jaxhooks

    slots = 4
    service, pool = _make_service(slots=slots, queue_cap=64,
                                  serve_ragged=True, serve_overlap=True)
    reqs = list(request_stream(pool, 4 * slots + 12, seed=81))
    it = iter(reqs)
    # warm the full-width programs and the key-fold at full width
    for _ in range(2 * slots):
        service.submit(next(it))
    service.drain()
    before = jaxhooks.unexpected_retraces()
    jaxhooks.mark_steady()
    try:
        # trickle narrows the ladder (new rung programs + key folds), then
        # a burst widens back to the already-built full width
        for k in (1, 1, 1, 1, 1, 1, slots, 1, 1):
            for _ in range(k):
                service.submit(next(it))
            service.tick()
        service.drain()
        assert service.ladder.transitions, "test never exercised the ladder"
        assert jaxhooks.unexpected_retraces() == before, (
            "a ladder width transition retraced outside expected_rebuild"
        )
    finally:
        jaxhooks.clear_steady()


@pytest.mark.slow
def test_loadgen_soak(tmp_path):
    """The committed-record path end to end at reduced scale: both legs,
    internal dispatch/degradation asserts, and the serving.json schema."""
    import json
    import subprocess
    import sys

    out = tmp_path / "serving.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "serve_loadgen.py"),
         "--requests", "60", "--slots", "4", "--queue-cap", "16",
         "--open-loop-requests", "60", "--search-doublings", "3",
         "--search-iters", "3",
         "--out", str(out)],
        capture_output=True, text=True, timeout=900, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    # closed-loop continuity record, nested under `legacy`
    assert rec["legacy"]["dispatch_comparison"]["below_evaluator"] is True
    assert rec["legacy"]["legs"]["gnn"]["served"] == 60
    assert rec["legacy"]["legs"]["degraded"]["degraded"] == 60
    # open-loop headline: a finite sustained rate that met the SLO
    ol = rec["open_loop"]
    assert ol["sustained_rps"] > 0
    assert any(p["ok"] for p in ol["search"]["probes"])
    assert "sustains" in rec["headline"]

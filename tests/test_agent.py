"""Agent: actor head, forward_env golden test, forward_backward math, replay."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.agent import (
    actor_delay_matrix,
    build_ext_features,
    forward_backward,
    forward_env,
    make_optimizer,
    replay_apply,
    replay_init,
    replay_remember,
)
from multihop_offload_tpu.agent.train_step import _critic_loss, _suffix_bias_grad
from multihop_offload_tpu.agent.replay import apply_max_norm_constraint
from multihop_offload_tpu.graphs.instance import PadSpec, build_instance, build_jobset
from multihop_offload_tpu.graphs.topology import sample_link_rates
from multihop_offload_tpu.models import ChebNet, load_reference_checkpoint

from oracle import refenv
from tests.conftest import REFERENCE_CKPT


@pytest.fixture(scope="module")
def setup(small_cases):
    rng = np.random.default_rng(42)
    rec = small_cases[0]
    rates = sample_link_rates(rec.topo, rec.link_rates, rng=rng)
    pad = PadSpec.for_cases([rec.sizes], round_to=8)
    inst = build_instance(
        rec.topo, rec.roles, rec.proc_bws, rates, 1000.0, pad, dtype=np.float64
    )
    ca = refenv.case_arrays(rec, rates)
    mobile = rng.permutation(rec.mobile_nodes)
    nj = max(3, mobile.size // 2)
    srcs, jrates = mobile[:nj], 0.15 * rng.uniform(0.1, 0.5, nj)
    jobs_list = [
        {"src": int(s), "rate": float(r), "ul": 100.0, "dl": 1.0}
        for s, r in zip(srcs, jrates)
    ]
    js = build_jobset(srcs, jrates, pad_jobs=pad.j, dtype=np.float64)
    model = ChebNet(param_dtype=jnp.float64)
    variables = load_reference_checkpoint(REFERENCE_CKPT, dtype=np.float64)
    return rec, ca, inst, js, jobs_list, model, variables, pad


def _oracle_lambda(variables, feats):
    """Numpy forward of the K=1 stack."""
    h = feats
    for i in range(5):
        w = np.asarray(variables["params"][f"cheb_{i}"]["kernel"])[0]
        b = np.asarray(variables["params"][f"cheb_{i}"]["bias"])
        h = h @ w + b
        h = np.maximum(h, 0) if i == 4 else np.where(h > 0, h, 0.2 * h)
    return h[:, 0]


def _oracle_delay_matrix(ca, lam_link, lam_node, T=1000.0):
    """Reference `forward` math in numpy (`gnn_offloading_agent.py:229-274`)."""
    mu = refenv.fixed_point_oracle(
        ca["link_rates"], ca["cf_degs"], ca["adj_conflict"], lam_link
    )
    link_delay = np.where(
        lam_link - mu > 0, T * lam_link / (101 * mu), 1.0 / (mu - lam_link)
    )
    n = ca["proc_bws"].shape[0]
    comp = ca["proc_bws"] > 0
    node_delay = np.full(n, np.inf)
    bw, lamn = ca["proc_bws"][comp], lam_node[comp]
    node_delay[comp] = np.where(
        lamn - bw > 0, T * lamn / (100 * bw), 1.0 / (bw - lamn)
    )
    D = np.full((n, n), np.nan)
    iu, ju = np.nonzero(ca["adj"])
    D[iu, ju] = link_delay[ca["link_index"][iu, ju]]
    np.fill_diagonal(D, node_delay)
    return D, link_delay, node_delay


def test_features_match_reference_layout(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    feats = np.asarray(build_ext_features(inst, js))
    L = pad.l
    nlinks = rec.topo.num_links
    assert (feats[:nlinks, 0] == 0).all() and (feats[:nlinks, 3] == 0).all()
    np.testing.assert_allclose(feats[:nlinks, 1], ca["link_rates"])
    arrivals = np.zeros(rec.topo.n)
    for j in jobs_list:
        arrivals[j["src"]] += j["rate"] * j["ul"]
    np.testing.assert_allclose(feats[L : L + rec.topo.n, 2], arrivals)
    comp = ca["proc_bws"] > 0
    np.testing.assert_allclose(feats[L : L + rec.topo.n, 0], comp.astype(float))


def test_actor_delay_matrix_matches_oracle(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    out = actor_delay_matrix(model, variables, inst, js, inst.adj_ext)
    feats = np.asarray(build_ext_features(inst, js))
    lam = _oracle_lambda(variables, feats)
    lam_link = lam[: rec.topo.num_links]
    lam_node = lam[pad.l : pad.l + rec.topo.n].copy()
    lam_node[ca["proc_bws"] <= 0] = 0.0
    D_or, link_d_or, node_d_or = _oracle_delay_matrix(ca, lam_link, lam_node)
    n = rec.topo.n
    D = np.asarray(out.delay_matrix)[:n, :n]
    mask = ~np.isnan(D_or)
    np.testing.assert_allclose(D[mask], D_or[mask], rtol=1e-9)
    # non-edges are exactly zero off-diagonal in our dense matrix
    offdiag_nonedge = (~mask) & ~np.eye(n, dtype=bool)
    assert (D[offdiag_nonedge] == 0).all()
    np.testing.assert_allclose(
        np.asarray(out.link_delay)[: rec.topo.num_links], link_d_or, rtol=1e-9
    )


def test_forward_env_golden_vs_oracle_pipeline(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    outcome, actor = jax.jit(
        lambda v, i, j, k: forward_env(model, v, i, j, k)
    )(variables, inst, js, jax.random.PRNGKey(0))

    feats = np.asarray(build_ext_features(inst, js))
    lam = _oracle_lambda(variables, feats)
    lam_node = lam[pad.l : pad.l + rec.topo.n].copy()
    lam_node[ca["proc_bws"] <= 0] = 0.0
    D_or, link_d_or, _ = _oracle_delay_matrix(ca, lam[: rec.topo.num_links], lam_node)
    n = rec.topo.n
    w = np.full((n, n), np.inf)
    iu, ju = np.nonzero(ca["adj"])
    w[iu, ju] = link_d_or[ca["link_index"][iu, ju]]
    sp_or = refenv.apsp_oracle(w)
    hop_or = refenv.hop_oracle(ca["adj"])
    dec = refenv.offload_oracle(ca, jobs_list, np.diagonal(D_or), sp_or, hop_or)
    res = refenv.run_oracle(ca, jobs_list, dec, 1000.0)

    nj = len(jobs_list)
    np.testing.assert_allclose(
        np.asarray(outcome.decision.dst[:nj]), [d["dst"] for d in dec]
    )
    np.testing.assert_allclose(
        np.asarray(outcome.delays.job_total[:nj]), res["total"], rtol=1e-9
    )


def test_suffix_bias_grad_matches_bruteforce(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    out = forward_backward(
        model, variables, inst, js, jax.random.PRNGKey(1)
    )
    routes = out.routes
    rng = np.random.default_rng(5)
    grad_routes = jnp.asarray(rng.normal(size=routes.inc_ext.shape))
    got = np.asarray(_suffix_bias_grad(inst, js, routes, grad_routes))

    # brute force from explicit route edge sequences
    expect = np.zeros(pad.e)
    seq = np.asarray(routes.seq_slot)
    act = np.asarray(routes.seq_active)
    gr = np.asarray(grad_routes)
    for j in range(pad.j):
        if not np.asarray(js.mask)[j]:
            continue
        edges = [int(seq[h, j]) for h in range(seq.shape[0]) if act[h, j]]
        edges.append(pad.l + int(np.asarray(routes.dst)[j]))
        c = 0.0
        for e in edges:
            c -= gr[e, j]
            expect[e] += c
    np.testing.assert_allclose(got, expect, rtol=1e-10, atol=1e-12)


def test_critic_loss_matches_numpy(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    out = forward_backward(model, variables, inst, js, jax.random.PRNGKey(1))
    inc = np.asarray(out.routes.inc_ext)
    jmask = np.asarray(js.mask)
    load = inc @ np.where(jmask, np.asarray(js.rate) * np.asarray(js.ul), 0.0)
    lam_link = load[: pad.l][: rec.topo.num_links]
    mu = refenv.fixed_point_oracle(
        ca["link_rates"], ca["cf_degs"], ca["adj_conflict"], lam_link
    )
    link_delay = np.where(
        lam_link - mu > 0, 1000.0 * lam_link / (101 * mu), 1.0 / (mu - lam_link)
    )
    comp = ca["proc_bws"] > 0
    lam_node = load[pad.l : pad.l + rec.topo.n] * comp
    node_delay = np.zeros(rec.topo.n)
    node_delay[comp] = np.where(
        lam_node[comp] - ca["proc_bws"][comp] > 0,
        1000.0 * lam_node[comp] / (100 * ca["proc_bws"][comp]),
        1.0 / (ca["proc_bws"][comp] - lam_node[comp]),
    )
    unit = np.zeros(pad.e)
    unit[: rec.topo.num_links] = link_delay
    unit[pad.l : pad.l + rec.topo.n] = node_delay
    data = np.asarray(js.ul) + np.asarray(js.dl)
    dje = np.maximum(data[None, :] * np.where(inc > 0, unit[:, None] * inc, 0.0), inc)
    np.testing.assert_allclose(float(out.loss_critic), dje.sum(), rtol=1e-9)


def test_forward_backward_grads_finite_and_vjp_consistent(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    out = jax.jit(
        lambda v, i, j, k: forward_backward(model, v, i, j, k)
    )(variables, inst, js, jax.random.PRNGKey(3))
    flat, _ = jax.flatten_util.ravel_pytree(out.grads)
    assert np.isfinite(np.asarray(flat)).all()
    assert float(jnp.abs(flat).sum()) > 0
    assert np.isfinite(float(out.loss_critic)) and np.isfinite(float(out.loss_mse))

    # vjp composition == grad of the linear surrogate <grad_dist, D(theta)>
    from multihop_offload_tpu.agent.train_step import (
        _grad_edge_to_distance,
        _suffix_bias_grad,
    )

    grad_routes = jax.grad(lambda r: _critic_loss(inst, js, r)[0])(out.routes.inc_ext)
    grad_edge = _suffix_bias_grad(inst, js, out.routes, grad_routes)
    gd = _grad_edge_to_distance(inst, grad_edge)
    emp = out.delays.unit_matrix
    mask = out.delays.unit_mask & jnp.isfinite(emp)
    gd = gd + 0.001 * jnp.where(mask, out.actor.delay_matrix - emp, 0.0)
    gd = jax.lax.stop_gradient(gd)

    def surrogate(v):
        a = actor_delay_matrix(model, v, inst, js, inst.adj_ext)
        contrib = jnp.where(jnp.isfinite(a.delay_matrix), gd * a.delay_matrix, 0.0)
        return jnp.sum(contrib)

    g2 = jax.grad(surrogate)(variables)
    flat2, _ = jax.flatten_util.ravel_pytree(g2)
    np.testing.assert_allclose(np.asarray(flat), np.asarray(flat2), rtol=1e-8, atol=1e-12)


def test_replay_buffer_and_optimizer(setup):
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    cfg = Config(learning_rate=1e-3, dtype="float64")
    params = variables["params"]
    opt = make_optimizer(cfg)
    opt_state = opt.init(params)
    mem = replay_init(params, capacity=8)

    out = forward_backward(model, variables, inst, js, jax.random.PRNGKey(7))
    for i in range(10):  # overfill to exercise the ring
        mem = replay_remember(mem, out.grads["params"], out.loss_critic + i, out.loss_mse)
    assert int(mem.count) == 8 and int(mem.ptr) == 2

    p2, s2, loss, skipped = replay_apply(mem, params, opt_state, opt, jax.random.PRNGKey(0), batch=4)
    assert np.isfinite(float(loss))
    assert int(skipped) == 0
    d0 = np.asarray(params["cheb_0"]["kernel"])
    d1 = np.asarray(p2["cheb_0"]["kernel"])
    assert not np.allclose(d0, d1)
    # max-norm constraint holds after updates (keras axis-0 norms)
    for layer in p2.values():
        for w in layer.values():
            norms = np.sqrt((np.asarray(w) ** 2).sum(axis=0))
            assert (norms <= 1.0 + 1e-6).all()


def test_max_norm_constraint_matches_keras_formula():
    w = jnp.asarray(np.array([[3.0, 0.1], [4.0, 0.1]]))  # col norms 5, ~0.14
    out = np.asarray(apply_max_norm_constraint({"k": w}, 1.0)["k"])
    norms = np.sqrt((np.array([[3.0, 0.1], [4.0, 0.1]]) ** 2).sum(axis=0))
    expect = np.array([[3.0, 0.1], [4.0, 0.1]]) * (
        np.clip(norms, 0, 1.0) / (1e-7 + norms)
    )
    np.testing.assert_allclose(out, expect, rtol=1e-12)


def test_dropout_active_in_training_path(setup):
    """`cfg.dropout > 0` must actually perturb the training grads
    (reference applies Dropout before every layer in training mode,
    `gnn_offloading_agent.py:94`)."""
    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    dmodel = ChebNet(param_dtype=jnp.float64, dropout=0.5)

    def grads(dropout_rng):
        out = forward_backward(dmodel, variables, inst, js,
                               jax.random.PRNGKey(3), dropout_rng=dropout_rng)
        return jax.flatten_util.ravel_pytree(out.grads)[0]

    g_det = grads(None)
    g_a = grads(jax.random.PRNGKey(10))
    g_b = grads(jax.random.PRNGKey(11))
    # no dropout key -> deterministic == the dropout-free model
    out0 = forward_backward(model, variables, inst, js, jax.random.PRNGKey(3))
    np.testing.assert_allclose(
        np.asarray(g_det),
        np.asarray(jax.flatten_util.ravel_pytree(out0.grads)[0]),
    )
    # dropout keys perturb grads, and different keys differ
    assert not np.allclose(np.asarray(g_det), np.asarray(g_a))
    assert not np.allclose(np.asarray(g_a), np.asarray(g_b))
    assert np.isfinite(np.asarray(g_a)).all()


def test_compat_cycled_diagonal_matches_fill_diagonal(setup):
    """compat mode must reproduce np.fill_diagonal's cycling of the shorter
    compute-node delay vector (`gnn_offloading_agent.py:269` + decision-path
    consumption at `offloading_v3.py:396`)."""
    from multihop_offload_tpu.agent.actor import (
        actor_delay_matrix, compat_cycled_diagonal,
    )

    rec, ca, inst, js, jobs_list, model, variables, pad = setup
    actor = actor_delay_matrix(model, variables, inst, js, inst.adj_ext)
    got = np.asarray(compat_cycled_diagonal(inst, actor.node_delay))

    # numpy emulation on the real (unpadded) case
    n = rec.topo.n
    comp_nodes = np.flatnonzero(np.asarray(inst.comp_mask))
    node_delay_comp = np.asarray(actor.node_delay)[comp_nodes]
    emul = np.zeros((n, n))
    np.fill_diagonal(emul, node_delay_comp)  # cycles when shorter
    np.testing.assert_allclose(got[:n], np.diagonal(emul)[:n], rtol=1e-12)

    # the cycled diagonal must actually differ from the correct one on a
    # case with relays (else the A/B switch is a no-op)
    assert rec.num_relays > 0
    correct = np.asarray(jnp.diagonal(actor.delay_matrix))
    assert not np.allclose(got[:n], correct[:n])
    # and both A/B paths evaluate end-to-end with finite masked totals
    from multihop_offload_tpu.agent import forward_env
    out_fix, _ = forward_env(model, variables, inst, js, jax.random.PRNGKey(0))
    out_bug, _ = forward_env(model, variables, inst, js, jax.random.PRNGKey(0),
                             compat_diagonal_bug=True)
    m = np.asarray(js.mask)
    assert np.isfinite(np.asarray(out_bug.delays.job_total))[m].all()
    assert np.isfinite(np.asarray(out_fix.delays.job_total))[m].all()

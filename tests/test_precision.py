"""Mixed-precision policy: bf16 hot path vs fp32 parity, fp32 islands.

Tier-1 (CPU) gate for the `cfg.precision` knob: the bf16 policy must make
the SAME offloading decisions as fp32 (>= 99% agreement) with per-method
job-total deltas inside the documented tolerance, while the ill-conditioned
steps (interference fixed point, delay reductions, decision read-back)
provably stay fp32.  A float64 reference column (conftest enables x64)
bounds how much of the observed delta is fp32's own rounding vs bf16's.

Tolerances: bf16 carries ~8 mantissa bits (relative step ~2^-8 = 0.4%);
after the M/M/1 amplification through `1/(mu - lambda)` at the moderate
loads used here, per-job totals land within a few percent.  The committed
gate (`benchmarks/precision_ab.json`) uses the same thresholds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multihop_offload_tpu.env.policies import baseline_policy, local_policy
from multihop_offload_tpu.env.queueing import (
    interference_fixed_point,
    interference_fixed_point_raw,
)
from multihop_offload_tpu.graphs import generators
from multihop_offload_tpu.graphs.instance import PadSpec
from multihop_offload_tpu.graphs.topology import build_topology
from multihop_offload_tpu.models.chebconv import chebyshev_support
from multihop_offload_tpu.precision import (
    FP32_ISLANDS,
    PrecisionPolicy,
    island_dtype,
    resolve_precision,
)
from multihop_offload_tpu.sim.fidelity import make_case

AGREEMENT_FLOOR = 0.99   # offload decisions: bf16 vs fp32
TAU_RTOL_BF16 = 0.05     # per-method mean job-total relative delta vs fp32
TAU_RTOL_FP32 = 1e-3     # fp32 vs float64 reference (sanity column)


def _case(seed, dtype, n_nodes=16, num_jobs=8):
    topo = build_topology(generators.barabasi_albert(n_nodes, seed=seed)[0])
    pad = PadSpec(n=16, l=-(-topo.num_links // 8) * 8, s=8, j=num_jobs)
    return make_case(seed, topo, pad, num_jobs, dtype=dtype)


def _run(policy, inst, jobs, key):
    apsp_fn = policy.wrap_apsp(None)
    out_b = baseline_policy(inst, jobs, key, apsp_fn=apsp_fn)
    out_l = local_policy(inst, jobs)
    return {"baseline": out_b, "local": out_l}


def _mean_tau(outcome, jobs):
    m = np.asarray(jobs.mask)
    return float(np.asarray(outcome.job_total, np.float64)[m].mean())


# ---- policy resolution -----------------------------------------------------


def test_resolve_identity_fp32():
    pol = resolve_precision("fp32", jnp.float32)
    assert not pol.mixed
    assert jnp.dtype(pol.param_dtype) == jnp.dtype(jnp.float32)
    assert jnp.dtype(pol.storage_dtype) == jnp.dtype(jnp.float32)
    # identity policy is a no-op wrapper: the resolved apsp_fn (None for the
    # XLA default) must pass through unchanged so `apsp_fn or apsp_minplus`
    # defaulting still applies downstream
    assert pol.wrap_apsp(None) is None
    f = lambda w: w  # noqa: E731
    assert pol.wrap_apsp(f) is f
    # resolving an already-resolved policy is idempotent
    assert resolve_precision(pol) is pol
    # None means fp32 (the default until the A/B gates pass)
    assert not resolve_precision(None).mixed


def test_resolve_bf16():
    pol = resolve_precision("bf16", jnp.float32)
    assert pol.mixed
    assert jnp.dtype(pol.compute_dtype) == jnp.dtype(jnp.bfloat16)
    assert jnp.dtype(pol.param_dtype) == jnp.dtype(jnp.float32)
    assert jnp.dtype(pol.accum_dtype) == jnp.dtype(jnp.float32)
    # storage dtype must be numpy-compatible (host-side packing uses it)
    z = np.zeros((3,), pol.storage_dtype)
    assert z.dtype == jnp.dtype(jnp.bfloat16)
    assert pol.islands == FP32_ISLANDS


def test_resolve_auto_off_tpu():
    # this suite runs on CPU (conftest pins the platform): auto -> fp32
    assert jax.default_backend() != "tpu"
    assert resolve_precision("auto").name == "fp32"


def test_island_dtype_floor_and_promotion():
    assert island_dtype(jnp.bfloat16) == jnp.dtype(jnp.float32)
    assert island_dtype(jnp.float32, jnp.bfloat16) == jnp.dtype(jnp.float32)
    # x64 is on in tests: a float64 operand keeps the island at float64
    assert island_dtype(jnp.float64) == jnp.dtype(jnp.float64)


# ---- fp32 islands ----------------------------------------------------------


def test_fixed_point_island_holds_under_bf16():
    """bf16 operands in, >= fp32 fixed point out, matching the fp32 run."""
    inst, jobs = _case(0, np.float32)
    lam = (0.3 * np.asarray(inst.link_rates, np.float32)
           * np.asarray(inst.link_mask, np.float32))
    mu32 = interference_fixed_point(inst, jnp.asarray(lam, jnp.float32))

    bf = jnp.bfloat16
    inst16 = inst.replace(
        adj_conflict=inst.adj_conflict.astype(bf),
        link_rates=inst.link_rates.astype(bf),
        cf_degs=inst.cf_degs.astype(bf),
    )
    mu16 = interference_fixed_point(inst16, jnp.asarray(lam).astype(bf))
    assert mu16.dtype == jnp.dtype(jnp.float32)
    # operands were rounded to bf16 once (~0.4% each) but the ITERATION ran
    # wide: the result tracks the fp32 run at input-rounding error, not at
    # the compounded error a bf16 iteration would show
    np.testing.assert_allclose(
        np.asarray(mu16), np.asarray(mu32), rtol=2e-2
    )

    # contrast: iterating the raw core natively in bf16 (what the island
    # prevents) visibly drifts from the wide run
    mu_native = interference_fixed_point_raw(
        inst16.adj_conflict, inst16.link_rates, inst16.cf_degs,
        jnp.asarray(lam).astype(bf),
    )
    assert mu_native.dtype == jnp.dtype(bf)


def test_laplacian_constants_survive_bf16_adjacency():
    """`chebyshev_support` on a bf16 adjacency computes wide internally and
    only narrows on the way out — the eye/degree constants never degrade."""
    inst, _ = _case(1, np.float32)
    adj32 = inst.adj.astype(jnp.float32)
    mask = jnp.ones((inst.num_pad_nodes,), bool)
    sup32 = chebyshev_support(adj32, mask)
    sup16 = chebyshev_support(adj32.astype(jnp.bfloat16), mask)
    assert sup16.dtype == jnp.dtype(jnp.bfloat16)
    np.testing.assert_allclose(
        np.asarray(sup16, np.float32), np.asarray(sup32), atol=4e-3
    )
    # explicit output-dtype override (the policy's compute dtype)
    sup_cast = chebyshev_support(adj32, mask, dtype=jnp.bfloat16)
    assert sup_cast.dtype == jnp.dtype(jnp.bfloat16)


# ---- fp32 vs bf16 end-to-end parity ---------------------------------------


def _parity_legs(seeds=(0, 1, 2, 3)):
    pol32 = resolve_precision("fp32", jnp.float32)
    pol16 = resolve_precision("bf16", jnp.float32)
    legs = []
    for seed in seeds:
        key = jax.random.PRNGKey(seed)
        inst32, jobs32 = _case(seed, np.float32)
        inst16, jobs16 = _case(seed, pol16.storage_dtype)
        inst64, jobs64 = _case(seed, np.float64)
        legs.append({
            "fp32": (_run(pol32, inst32, jobs32, key), jobs32),
            "bf16": (_run(pol16, inst16, jobs16, key), jobs16),
            "fp64": (_run(pol32, inst64, jobs64, key), jobs64),
        })
    return legs


@pytest.fixture(scope="module")
def parity_legs():
    return _parity_legs()


def test_decision_agreement_bf16(parity_legs):
    agree = total = 0
    for leg in parity_legs:
        out32, jobs = leg["fp32"]
        out16, _ = leg["bf16"]
        m = np.asarray(jobs.mask)
        d32 = np.asarray(out32["baseline"].decision.dst)[m]
        d16 = np.asarray(out16["baseline"].decision.dst)[m]
        agree += int((d32 == d16).sum())
        total += int(m.sum())
    assert total >= 16
    assert agree / total >= AGREEMENT_FLOOR, f"{agree}/{total} decisions agree"


def test_job_totals_within_tolerance(parity_legs):
    for leg in parity_legs:
        out32, jobs = leg["fp32"]
        out16, jobs16 = leg["bf16"]
        out64, jobs64 = leg["fp64"]
        for method in ("baseline", "local"):
            t32 = _mean_tau(out32[method], jobs)
            t16 = _mean_tau(out16[method], jobs16)
            t64 = _mean_tau(out64[method], jobs64)
            assert abs(t16 - t32) / t32 <= TAU_RTOL_BF16, (
                f"{method}: bf16 tau {t16} vs fp32 {t32}"
            )
            # sanity column: fp32 itself sits tight on the fp64 reference,
            # so the bf16 delta above is bf16's, not fp32's
            assert abs(t32 - t64) / t64 <= TAU_RTOL_FP32, (
                f"{method}: fp32 tau {t32} vs fp64 {t64}"
            )


def test_delay_outputs_stay_wide_under_bf16(parity_legs):
    """The delay_reduction island: bf16 storage in, fp32 job totals out."""
    for leg in parity_legs:
        out16, _ = leg["bf16"]
        for method in ("baseline", "local"):
            d = out16[method].delays
            for field in (d.job_total, d.link_lambda, d.link_mu):
                assert jnp.dtype(field.dtype) == jnp.dtype(jnp.float32), (
                    f"{method}: {field.dtype} leaked past the island"
                )


def test_policy_is_static_no_retrace():
    """The policy is resolved at build time and closed over — flipping it
    never shows up as a traced value (PrecisionPolicy is not a pytree leaf
    the jitted programs see)."""
    pol = resolve_precision("bf16", jnp.float32)
    assert isinstance(pol, PrecisionPolicy)
    traces = {"n": 0}

    def apsp_counting(w):
        traces["n"] += 1
        from multihop_offload_tpu.env.apsp import apsp_minplus

        return apsp_minplus(w)

    wrapped = pol.wrap_apsp(apsp_counting)
    inst, jobs = _case(2, pol.storage_dtype)
    f = jax.jit(lambda i, j, k: baseline_policy(i, j, k, apsp_fn=wrapped))
    key = jax.random.PRNGKey(0)
    f(inst, jobs, key)
    first = traces["n"]
    f(inst, jobs, jax.random.PRNGKey(1))
    assert traces["n"] == first, "jitted policy retraced on a steady call"

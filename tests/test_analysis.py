"""mho-lint engine tests: per-rule TP / waived / false-positive guard,
the SL001 multi-line regression the old regex missed, jit-reachability,
the baseline workflow, the CLI surfaces, and the two repo-level smokes
(clean repo, every rule fires on the seeded fixture dir).

Pure stdlib under test — none of this imports jax.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import textwrap

from multihop_offload_tpu.analysis import run_analysis, write_baseline
from multihop_offload_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEEDED = os.path.join(REPO, "tests", "fixtures", "analysis_seeded")
ALL_REPO_RULES = {"JX001", "JX002", "JX003", "JX004", "JX005", "JX006",
                  "JX007", "JX008", "JX009", "JX010", "JX011", "JX012",
                  "MP001", "SL001", "OB001", "OB002", "OB003"}


def run_on(tmp_path, files, select=None, baseline=None):
    """Write {relpath: source} under tmp_path and run the engine on it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_analysis([str(tmp_path)], select=select, baseline=baseline)


def rules_hit(report):
    return {f.rule for f in report.findings}


# ---------------------------------------------------------------------------
# per-rule: true positive / waived / false-positive guard
# ---------------------------------------------------------------------------


def test_mp001_tp_waived_and_alias_aware(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax.numpy as weird_alias

        def tp(x):
            return x.astype(weird_alias.float32)

        def waived(x):
            return x.astype(weird_alias.float32)  # fp32-island(test)
    """})
    mp = [f for f in rep.findings if f.rule == "MP001"]
    assert len(mp) == 1 and mp[0].line == 4  # the alias still resolves
    assert len([f for f in rep.waived if f.rule == "MP001"]) == 1


def test_mp001_not_outside_hot_dirs(tmp_path):
    rep = run_on(tmp_path, {"utils/m.py": """\
        import jax.numpy as jnp

        def fine(x):
            return x.astype(jnp.float32)
    """})
    assert "MP001" not in rules_hit(rep)


_OLD_SQUARE_DENSE = re.compile(  # the historical regex, verbatim
    r"\b(?:jnp|np|numpy)\.(?:zeros|ones|full|empty)\(\s*"
    r"\(\s*([A-Za-z_][\w.]*)\s*,\s*\1\s*[,)]"
)

_MULTILINE_DENSE = """\
import jax.numpy as jnp

def build(n, dt):
    return jnp.zeros(
        (n, n), dt
    )
"""


def test_sl001_multiline_regression_old_regex_missed_it(tmp_path):
    # the escape: no single LINE matches the old regex...
    assert not any(_OLD_SQUARE_DENSE.search(line)
                   for line in _MULTILINE_DENSE.splitlines())
    # ...but the AST rule sees the call whole
    rep = run_on(tmp_path, {"env/m.py": _MULTILINE_DENSE})
    sl = [f for f in rep.findings if f.rule == "SL001"]
    assert len(sl) == 1 and sl[0].line == 4


def test_sl001_waiver_on_any_physical_line_of_the_call(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax.numpy as jnp

        def build(n, dt):
            return jnp.zeros(
                (n, n), dt  # dense-ok(test target)
            )
    """})
    assert "SL001" not in rules_hit(rep)
    assert len([f for f in rep.waived if f.rule == "SL001"]) == 1


def test_sl001_fp_guards_rectangular_and_value_alias(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax.numpy as jnp

        def fine(n, m, dt):
            return jnp.zeros((n, m), dt)  # rectangular: not flagged

        def aliased(n, dt):
            z = jnp.zeros
            return z((n, n), dt)  # value alias: STILL flagged
    """})
    sl = [f for f in rep.findings if f.rule == "SL001"]
    assert len(sl) == 1 and sl[0].line == 8


def test_ob001_tp_waived_and_pprint_guard(tmp_path):
    rep = run_on(tmp_path, {"loop/m.py": """\
        from pprint import pprint

        def report(x):
            print(x)
            print(x)  # print-ok(operator feedback)
            pprint(x)
            x.print()
    """})
    ob = [f for f in rep.findings if f.rule == "OB001"]
    assert len(ob) == 1 and ob[0].line == 4  # pprint/.print() untouched
    assert len([f for f in rep.waived if f.rule == "OB001"]) == 1


def test_ob001_exempts_cli(tmp_path):
    rep = run_on(tmp_path, {"cli/m.py": "print('console surface')\n"})
    assert "OB001" not in rules_hit(rep)


def test_ob002_tp_waived_and_name_guard(tmp_path):
    rep = run_on(tmp_path, {"train/m.py": """\
        def facts(compiled, cost_analysis):
            ca = compiled.cost_analysis()
            mem = compiled.memory_analysis()  # prof-ok(test waiver)
            stats = device.memory_stats()
            other = cost_analysis()
            return ca, mem, stats, other
    """})
    ob = [f for f in rep.findings if f.rule == "OB002"]
    assert {f.line for f in ob} == {2, 4}  # bare-name call untouched
    assert len([f for f in rep.waived if f.rule == "OB002"]) == 1


def test_ob002_exempts_obs_dir(tmp_path):
    rep = run_on(tmp_path, {
        "obs/prof.py": "def f(c):\n    return c.cost_analysis()\n"})
    assert "OB002" not in rules_hit(rep)


def test_ob003_tp_waived_and_reachability_guard(tmp_path):
    rep = run_on(tmp_path, {"train/m.py": """\
        import jax
        from jax.experimental import io_callback

        @jax.jit
        def tp(x):
            jax.debug.print("x = {}", x)
            return x

        @jax.jit
        def tp_io(x):
            io_callback(print, None, x)
            return x

        @jax.jit
        def waived(x):
            jax.debug.print("x = {}", x)  # devcb-ok(test)
            return x

        def host_only(x):
            jax.debug.print("host {}", x)
            return x
    """})
    ob = [f for f in rep.findings if f.rule == "OB003"]
    assert {f.line for f in ob} == {6, 11}  # host-only fn untouched
    assert len([f for f in rep.waived if f.rule == "OB003"]) == 1


def test_ob003_exempts_obs_dir(tmp_path):
    rep = run_on(tmp_path, {"obs/bridge.py": """\
        import jax

        @jax.jit
        def deliberate_bridge(x):
            jax.debug.print("obs owns this hop {}", x)
            return x
    """})
    assert "OB003" not in rules_hit(rep)


def test_jx001_tp_waived_and_shadow_guard(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def tp(x):
            s = jnp.sum(x)
            if s > 0:
                return s
            return -s

        @jax.jit
        def waived(x):
            s = jnp.sum(x)
            if s > 0:  # trace-ok(test)
                return s
            return -s

        @jax.jit
        def shadowed(x):
            s = jnp.sum(x)
            s = 3  # traced name rebound to a Python int
            if s > 0:
                return x
            return -x

        def host_helper(flag):
            # NOT jit-reachable: plain Python branching is fine here
            if flag > 0:
                return 1
            return 0
    """})
    jx = [f for f in rep.findings if f.rule == "JX001"]
    assert len(jx) == 1 and jx[0].line == 7
    assert len([f for f in rep.waived if f.rule == "JX001"]) == 1


def test_jx001_static_shape_attrs_not_tainted(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fine(x):
            y = jnp.abs(x)
            if y.ndim == 2:          # static at trace time
                return y[: y.shape[0] // 2]
            return y
    """})
    assert "JX001" not in rules_hit(rep)


def test_jx001_reaches_through_package_calls(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax
        import jax.numpy as jnp

        def helper(x):
            s = jnp.max(x)
            return float(s)  # concretization, two hops below the jit

        def entry(x):
            return helper(x) + 1

        wrapped = jax.jit(entry)
    """})
    jx = [f for f in rep.findings if f.rule == "JX001"]
    assert len(jx) == 1 and jx[0].line == 6


def test_jx002_tp_waived_and_module_scope_guard(tmp_path):
    rep = run_on(tmp_path, {"serve/m.py": """\
        import jax

        def per_batch(batches):
            for b in batches:
                f = jax.jit(lambda v: v * 2)
                yield f(b)

        def per_bucket(steps):
            out = []
            for s in steps:
                out.append(jax.jit(s))  # retrace-ok(build loop)
            return out

        def fine(step):
            return jax.jit(step)  # once, outside any loop

        _module_level = jax.jit(lambda v: v + 1)  # built once at import
    """})
    jx = [f for f in rep.findings if f.rule == "JX002"]
    assert len(jx) == 1 and jx[0].line == 5
    assert len([f for f in rep.waived if f.rule == "JX002"]) == 1


def test_jx003_tp_waived_and_explicit_dtype_guard(tmp_path):
    rep = run_on(tmp_path, {"sim/m.py": """\
        import jax.numpy as jnp

        def tp(n):
            return jnp.arange(n)

        def waived(n):
            return jnp.arange(n)  # dtype-ok(test)

        def fine(n):
            return jnp.arange(n, dtype=jnp.int32) + jnp.zeros((n,), jnp.float16)
    """})
    jx = [f for f in rep.findings if f.rule == "JX003"]
    assert len(jx) == 1 and jx[0].line == 4
    assert len([f for f in rep.waived if f.rule == "JX003"]) == 1


def test_jx004_tp_waived_and_non_hot_function_guard(tmp_path):
    rep = run_on(tmp_path, {"serve/m.py": """\
        import numpy as np

        class S:
            def tick(self, out):
                a = np.asarray(out)
                b = np.asarray(out)  # host-sync-ok(test)
                return a, b

            def build(self, out):
                return np.asarray(out)  # not a hot-loop function
    """})
    jx = [f for f in rep.findings if f.rule == "JX004"]
    assert len(jx) == 1 and jx[0].line == 5
    assert len([f for f in rep.waived if f.rule == "JX004"]) == 1


def test_jx004_skips_jitted_steps(tmp_path):
    # a jitted *_step cannot host-sync (trace-time failure) — the rule is
    # about the HOST loop, so jit-reachable defs are excluded
    rep = run_on(tmp_path, {"sim/m.py": """\
        import jax
        import numpy as np

        @jax.jit
        def sim_step(x):
            return np.asarray(x)  # would fail at trace time anyway
    """})
    assert "JX004" not in rules_hit(rep)


def test_jx005_tp_waived_and_seeded_rng_guard(tmp_path):
    rep = run_on(tmp_path, {"loop/m.py": """\
        import time

        import numpy as np

        def tp():
            return time.time()

        def waived():
            return time.monotonic()  # nondet-ok(test)

        def fine(seed, clock=time.monotonic):
            rng = np.random.default_rng(seed)  # seeded: sanctioned
            return rng.random() + clock()      # injected clock: sanctioned
    """})
    jx = [f for f in rep.findings if f.rule == "JX005"]
    assert len(jx) == 1 and jx[0].line == 6
    assert len([f for f in rep.waived if f.rule == "JX005"]) == 1


def test_jx005_exempts_cli(tmp_path):
    rep = run_on(tmp_path, {"cli/m.py": """\
        import time

        def main():
            return time.time()
    """})
    assert "JX005" not in rules_hit(rep)


def test_jx006_swallowed_exceptions_tp_and_waived(tmp_path):
    rep = run_on(tmp_path, {"serve/m.py": """\
        def tp_bare(path):
            try:
                return open(path).read()
            except:
                pass

        def tp_pass_only(path):
            try:
                return open(path).read()
            except Exception:
                pass

        def waived(path):
            try:
                return open(path).read()
            except Exception:  # swallow-ok(best-effort probe)
                pass
    """})
    jx = [f for f in rep.findings if f.rule == "JX006"]
    assert len(jx) == 2 and [f.line for f in jx] == [4, 10]
    assert len([f for f in rep.waived if f.rule == "JX006"]) == 1


def test_jx006_handled_and_narrow_excepts_are_fine(tmp_path):
    rep = run_on(tmp_path, {"loop/m.py": """\
        def narrow(path):
            try:
                return open(path).read()
            except OSError:
                pass

        def handled(path):
            try:
                return open(path).read()
            except Exception:
                return None
    """})
    assert "JX006" not in rules_hit(rep)


def test_jx006_scoped_to_recovery_dirs(tmp_path):
    src = """\
        def swallow(path):
            try:
                return open(path).read()
            except Exception:
                pass
    """
    rep = run_on(tmp_path, {"cli/m.py": src, "analysis/m.py": src})
    assert "JX006" not in rules_hit(rep)
    rep = run_on(tmp_path, {"obs/m.py": src})
    assert "JX006" in rules_hit(rep)


def test_jx007_unplaced_device_put_tp_waived_and_explicit(tmp_path):
    rep = run_on(tmp_path, {"serve/m.py": """\
        import jax

        def tp(x):
            return jax.device_put(x)

        def waived(x):
            return jax.device_put(x)  # placement-ok(single-host tool path)

        def explicit(x, dev, shard):
            a = jax.device_put(x, dev)
            b = jax.device_put(x, device=dev)
            c = jax.device_put(x, sharding=shard)
            return a, b, c
    """})
    jx = [f for f in rep.findings if f.rule == "JX007"]
    assert len(jx) == 1 and jx[0].line == 4
    assert len([f for f in rep.waived if f.rule == "JX007"]) == 1


def test_jx007_scoped_to_serve(tmp_path):
    src = """\
        import jax

        def unplaced(x):
            return jax.device_put(x)
    """
    rep = run_on(tmp_path, {"train/m.py": src, "cli/m.py": src})
    assert "JX007" not in rules_hit(rep)
    rep = run_on(tmp_path, {"serve/m.py": src})
    assert "JX007" in rules_hit(rep)


def test_jx007_alias_aware(tmp_path):
    rep = run_on(tmp_path, {"serve/m.py": """\
        import jax as j
        from jax import device_put

        def a(x):
            return j.device_put(x)

        def b(x):
            return device_put(x)
    """})
    jx = [f for f in rep.findings if f.rule == "JX007"]
    assert [f.line for f in jx] == [5, 8]


def test_jx008_tp_waived_and_guarded_denominators(tmp_path):
    rep = run_on(tmp_path, {"env/m.py": """\
        import jax.numpy as jnp

        def tp(x, rho):
            return x / (1.0 - rho)

        def tp_int_one(x, rho):
            return x / (1 - rho)

        def tp_nested(x, rho, c):
            return x / ((1.0 - rho) * c)

        def waived(x, rho):
            return x / (1.0 - rho)  # div-ok(rho proven < 1 upstream)

        def clamped(x, rho, eps):
            return x / jnp.maximum(1.0 - rho, eps)

        def selected(x, rho):
            safe = jnp.where(rho < 1.0, 1.0 - rho, 1.0)
            return x / safe

        def other_sub(x, a, b):
            return x / (a - b)  # not the 1-minus saturation shape
    """})
    jx = [f for f in rep.findings if f.rule == "JX008"]
    assert [f.line for f in jx] == [4, 7, 10]
    assert len([f for f in rep.waived if f.rule == "JX008"]) == 1


def test_jx008_scoped_to_queueing_dirs(tmp_path):
    src = """\
        def tp(x, rho):
            return x / (1.0 - rho)
    """
    rep = run_on(tmp_path, {"serve/m.py": src, "obs/m.py": src,
                            "cli/m.py": src})
    assert "JX008" not in rules_hit(rep)
    rep = run_on(tmp_path, {"sim/m.py": src, "loop/m.py": src})
    assert "JX008" in rules_hit(rep)


def test_jx009_tp_waived_and_clean_scan_bodies(tmp_path):
    rep = run_on(tmp_path, {"rl/m.py": """\
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        def tp_rollout(state0, keys):
            def round_body(carry, key):
                jax.debug.callback(lambda c: None, carry)
                total = float(np.sum(carry))
                flag = carry.item()
                return carry + total + flag, None
            out, _ = lax.scan(round_body, state0, keys)
            return out

        def tp_lambda(state0, keys):
            out, _ = lax.scan(
                lambda c, k: (jax.experimental.io_callback(print, None, c),
                              None),
                state0, keys)
            return out

        def waived(state0, keys):
            def round_body(carry, key):
                jax.debug.print("r={r}", r=carry)  # rollout-ok(debug)
                return carry, None
            out, _ = lax.scan(round_body, state0, keys)
            return out

        def clean(state0, keys):
            def round_body(carry, key):
                return carry + jnp.sum(key), None
            out, _ = lax.scan(round_body, state0, keys)
            return out

        def outside_scan_is_fine(x):
            # host numpy OUTSIDE any scan body: not this rule's business
            return float(np.sum(x)) + x.item()
    """})
    jx = [f for f in rep.findings if f.rule == "JX009"]
    assert [f.line for f in jx] == [8, 9, 10, 17]
    assert len([f for f in rep.waived if f.rule == "JX009"]) == 1


def test_jx009_scoped_to_rl(tmp_path):
    src = """\
        import numpy as np
        from jax import lax

        def rollout(state0, keys):
            def round_body(carry, key):
                return carry + float(np.sum(carry)), None
            out, _ = lax.scan(round_body, state0, keys)
            return out
    """
    rep = run_on(tmp_path, {"sim/m.py": src, "agent/m.py": src,
                            "cli/m.py": src})
    assert "JX009" not in rules_hit(rep)
    rep = run_on(tmp_path, {"rl/m.py": src})
    assert "JX009" in rules_hit(rep)


def test_jx010_tp_waived_and_fp_guard(tmp_path):
    rep = run_on(tmp_path, {"parallel/m.py": """\
        import jax
        import jax.distributed as jd

        def tp_initialize(coord, n, pid):
            jax.distributed.initialize(coord, n, pid)

        def tp_alias(coord, n, pid):
            jd.initialize(coord, n, pid)

        def tp_index():
            return jax.process_index() == 0

        def tp_count():
            return jax.process_count()

        def waived():
            return jax.process_index() == 0  # mesh-ok(host0 write gate)

        def clean(d):
            # attribute READ on a device object, not a topology call
            return d.process_index
    """})
    jx = [f for f in rep.findings if f.rule == "JX010"]
    assert [f.line for f in jx] == [5, 8, 11, 14]
    assert len([f for f in rep.waived if f.rule == "JX010"]) == 1


def test_jx010_exempts_multihost(tmp_path):
    src = """\
        import jax

        def bootstrap(coord, n, pid):
            jax.distributed.initialize(coord, n, pid)
            return jax.process_index()
    """
    rep = run_on(tmp_path, {"multihost/runtime.py": src})
    assert "JX010" not in rules_hit(rep)
    rep = run_on(tmp_path, {"serve/m.py": src})
    assert "JX010" in rules_hit(rep)


def test_jx011_tp_waived_and_fp_guard(tmp_path):
    rep = run_on(tmp_path, {"scenarios/m.py": """\
        import networkx as nx
        from networkx import watts_strogatz_graph

        def tp_family(n, seed):
            return nx.barabasi_albert_graph(n, 2, seed=seed)

        def tp_alias(n, seed):
            return watts_strogatz_graph(n, 4, 0.2, seed=seed)

        def tp_container():
            return nx.Graph()

        def waived(n):
            return nx.path_graph(n)  # topo-ok(doc example, not a sim topology)

        def clean(g):
            # reads/algorithms on an existing graph are not draws
            return nx.is_connected(g), g.subgraph([0, 1])
    """})
    jx = [f for f in rep.findings if f.rule == "JX011"]
    assert [f.line for f in jx] == [5, 8, 11]
    assert len([f for f in rep.waived if f.rule == "JX011"]) == 1


def test_jx011_exempts_graphs_dir(tmp_path):
    src = """\
        import networkx as nx

        def draw(n, seed):
            return nx.barabasi_albert_graph(n, 2, seed=seed)
    """
    rep = run_on(tmp_path, {"graphs/generators.py": src})
    assert "JX011" not in rules_hit(rep)
    rep = run_on(tmp_path, {"env/m.py": src})
    assert "JX011" in rules_hit(rep)


def test_jx012_tp_waived_and_rebind_guard(tmp_path):
    rep = run_on(tmp_path, {"serve/m.py": """\
        import jax

        def _mul(w, x):
            return w * x

        step = jax.jit(_mul, donate_argnums=(1,))

        def tp(w, batch):
            out = step(w, batch)
            return out, batch.sum()

        def waived(w, batch):
            out = step(w, batch)
            return out, batch.sum()  # donate-ok(test)

        def rebound(w, batch):
            batch = step(w, batch)
            return batch * 2

        def weights_not_donated(w, batch):
            out = step(w, batch)
            return w.sum(), out
    """})
    jx = [f for f in rep.findings if f.rule == "JX012"]
    assert [f.line for f in jx] == [10]
    assert len([f for f in rep.waived if f.rule == "JX012"]) == 1


def test_jx012_dynamic_donation_skipped_and_alias_aware(tmp_path):
    rep = run_on(tmp_path, {"train/m.py": """\
        import jax
        from jax import jit as weird_jit

        DONATE = (1,)

        def _f(w, x):
            return w * x

        dyn = jax.jit(_f, donate_argnums=DONATE)
        aliased = weird_jit(_f, donate_argnums=1)

        def dynamic_vector_not_tracked(w, batch):
            out = dyn(w, batch)
            return out, batch.sum()

        def alias_tp(w, batch):
            out = aliased(w, batch)
            return out, batch.sum()
    """})
    jx = [f for f in rep.findings if f.rule == "JX012"]
    assert [f.line for f in jx] == [18]  # only the alias-resolved literal


# ---------------------------------------------------------------------------
# pyflakes set / syntax errors
# ---------------------------------------------------------------------------


def test_pyflakes_unused_import_and_syntax_error(tmp_path):
    rep = run_on(tmp_path, {
        "a.py": "import os\nimport sys\n\nprint(sys.argv)\n",
        "b.py": "def broken(:\n    pass\n",
    }, select="pyflakes")
    assert {f.rule for f in rep.findings} == {"F401", "E999"}
    f401 = [f for f in rep.findings if f.rule == "F401"]
    assert "os" in f401[0].message


# ---------------------------------------------------------------------------
# baseline workflow
# ---------------------------------------------------------------------------


def test_baseline_suppresses_then_resurfaces_on_change(tmp_path):
    files = {"env/m.py": """\
        import jax.numpy as jnp

        def tp(n):
            return jnp.arange(n)
    """}
    rep = run_on(tmp_path, files)
    assert rules_hit(rep) == {"JX003"}
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), rep.findings)

    rep2 = run_analysis([str(tmp_path)], baseline=str(bl))
    assert not rep2.findings and len(rep2.suppressed) == 1

    # edit the flagged line: the suppression no longer matches
    p = tmp_path / "env" / "m.py"
    p.write_text(p.read_text().replace("jnp.arange(n)", "jnp.arange(2 * n)"))
    rep3 = run_analysis([str(tmp_path)], baseline=str(bl))
    assert rules_hit(rep3) == {"JX003"} and not rep3.suppressed


# ---------------------------------------------------------------------------
# repo-level smokes + CLI surfaces
# ---------------------------------------------------------------------------


def test_repo_is_clean_under_the_engine():
    """mho-lint exits 0 on the repo itself (repo rules, default scope)."""
    rc = lint_main([os.path.join(REPO, "multihop_offload_tpu")])
    assert rc == 0


def test_seeded_fixture_dir_fires_every_rule():
    out = subprocess.run(
        [sys.executable, "-m", "multihop_offload_tpu.analysis.cli",
         "--json", SEEDED],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 1, out.stderr
    fired = {f["rule"] for f in json.loads(out.stdout)["findings"]}
    assert ALL_REPO_RULES <= fired, sorted(ALL_REPO_RULES - fired)


def test_cli_json_report_and_exit_codes(tmp_path):
    (tmp_path / "clean.py").write_text("X = 1\n")
    report_file = tmp_path / "report.json"
    rc = lint_main(["--json", "--report", str(report_file), str(tmp_path)])
    assert rc == 0
    data = json.loads(report_file.read_text())
    assert data["tool"] == "mho-lint" and data["files_scanned"] == 1
    assert set(data["rules"]) == ALL_REPO_RULES
    assert lint_main(["--select", "NOPE", str(tmp_path)]) == 2


def test_shim_maps_legacy_flags(tmp_path):
    shim = os.path.join(REPO, "scripts", "_lint_fallback.py")
    env = dict(os.environ, PYTHONPATH=REPO)
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    noisy = tmp_path / "noisy.py"
    noisy.write_text("print(1)\n")

    def shim_rc(*argv):
        out = subprocess.run([sys.executable, shim, *argv],
                             capture_output=True, text=True, cwd=REPO, env=env)
        return out.returncode, out.stdout + out.stderr

    for flags in (["--precision", str(clean)], ["--layout", str(clean)],
                  ["--prints", str(clean)], [str(clean)]):
        rc, log = shim_rc(*flags)
        assert rc == 0, (flags, log)
    rc, log = shim_rc("--prints", str(noisy))
    assert rc == 1 and "OB001" in log
    assert shim_rc("--bogus")[0] == 2

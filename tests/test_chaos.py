"""Chaos harness: fault primitives, durable-write machinery, and the full
kill-point matrix over the promotion state machine.

The matrix is the core guarantee: a SIGKILL-equivalent at EVERY named
crash site in the capture -> refit -> validate -> promote -> monitor ->
rollback cycle, followed by a restart, must land the journaled state
machine on the same terminal state and checkpoint lineage as an
uninterrupted run — and the recovered service must answer a golden
request set identically to the pre-fault champion.
"""

import os

import numpy as np
import pytest

from multihop_offload_tpu.chaos import faults
from multihop_offload_tpu.chaos.drills import KILL_SITES, ChaosSmoke
from multihop_offload_tpu.config import Config
from multihop_offload_tpu.obs.registry import registry as obs_registry
from multihop_offload_tpu.utils import durable

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


# ---- fault primitives -------------------------------------------------------


def test_crashpoint_unarmed_is_noop():
    faults.clear()
    faults.crashpoint("anywhere")  # no plan installed: must not raise
    faults.io_gate("anywhere")


def test_crashpoint_fires_once_at_nth_hit():
    plan = faults.FaultPlan(crash_at={"site": 3})
    faults.install(plan)
    try:
        faults.crashpoint("site")
        faults.crashpoint("site")
        with pytest.raises(faults.SimulatedCrash) as e:
            faults.crashpoint("site")
        assert e.value.site == "site"
        # fired once; the "restarted process" sails through the same site
        faults.crashpoint("site")
        assert plan.fired == {"site": 3}
    finally:
        faults.clear()


def test_simulated_crash_escapes_except_exception():
    """The whole point of BaseException: recovery code under test must
    not be able to swallow a simulated SIGKILL."""
    faults.install(faults.FaultPlan(crash_at={"s": 1}))
    try:
        with pytest.raises(faults.SimulatedCrash):
            try:
                faults.crashpoint("s")
            except Exception:
                pytest.fail("SimulatedCrash was swallowed")
    finally:
        faults.clear()


def test_io_gate_counts_down_then_clears():
    plan = faults.FaultPlan(io_fail={"w": 2})
    faults.install(plan)
    try:
        for _ in range(2):
            with pytest.raises(faults.TransientIOError):
                faults.io_gate("w")
        faults.io_gate("w")  # budget consumed: passes
        assert plan.io_hits == {"w": 2}
        assert isinstance(faults.TransientIOError("x"), OSError)
    finally:
        faults.clear()


def test_corruption_helpers_are_deterministic(tmp_path):
    p = str(tmp_path / "blob.bin")
    with open(p, "wb") as f:
        f.write(bytes(range(256)) * 4)
    assert faults.truncate_file(p, keep_fraction=0.25) == 256
    a = faults.bit_flip_file(p, seed=11, flips=4)
    # same seed on identical bytes flips the same offsets back
    assert faults.bit_flip_file(p, seed=11, flips=4) == a
    with open(p, "rb") as f:
        assert f.read() == bytes(range(256))  # double-flip restores
    faults.torn_tail(p)
    with open(p, "rb") as f:
        assert not f.read().endswith(b"\n")  # torn: no record terminator


# ---- durable-write machinery ------------------------------------------------


def test_with_backoff_absorbs_transient_oserror():
    obs_registry().reset()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("hiccup")
        return "ok"

    slept = []
    out = durable.with_backoff(flaky, site="t", retries=3, backoff_s=0.01,
                               sleep=slept.append)
    assert out == "ok" and calls["n"] == 3
    assert slept == [0.01, 0.02]  # exponential
    assert obs_registry().counter("mho_io_retries_total").total(site="t") == 2


def test_with_backoff_exhausted_budget_raises():
    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        durable.with_backoff(always, site="t", retries=2, backoff_s=0.0,
                             sleep=lambda s: None)


def test_with_backoff_non_oserror_propagates_immediately():
    """Corruption signals (bad JSON, checksum mismatch) must NOT be
    retried — they go to quarantine, not to backoff."""
    calls = {"n": 0}

    def corrupt():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        durable.with_backoff(corrupt, retries=5, sleep=lambda s: None)
    assert calls["n"] == 1


def test_atomic_write_json_leaves_no_tmp_and_round_trips(tmp_path):
    p = str(tmp_path / "deep" / "state.json")
    durable.atomic_write_json(p, {"b": 2, "a": 1})
    assert durable.load_json(p) == {"a": 1, "b": 2}
    assert os.listdir(os.path.dirname(p)) == ["state.json"]  # no tmp debris
    assert durable.load_json(str(tmp_path / "missing.json")) is None
    (tmp_path / "garbage.json").write_text("{not json")
    assert durable.load_json(str(tmp_path / "garbage.json")) is None


# ---- checkpoint integrity ---------------------------------------------------


def test_tree_checksum_is_content_keyed():
    from multihop_offload_tpu.train.checkpoints import tree_checksum

    t1 = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    t2 = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    assert tree_checksum(t1) == tree_checksum(t2)  # content, not identity
    t2["params"]["w"][0, 0] += 1e-3
    assert tree_checksum(t1) != tree_checksum(t2)  # any bit moves the hash
    t3 = {"params": {"w": t1["params"]["w"].astype(np.float64)}}
    assert tree_checksum(t1) != tree_checksum(t3)  # dtype is part of identity


def test_corrupt_checkpoint_quarantined_and_last_good_wins(tmp_path):
    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    obs_registry().reset()
    d = str(tmp_path / "orbax")
    good = {"params": {"w": np.ones((4,), np.float32)}}
    newer = {"params": {"w": np.full((4,), 2.0, np.float32)}}
    ckpt_lib.save_checkpoint(d, 1, good,
                             lineage=ckpt_lib.make_lineage("offline"))
    ckpt_lib.save_checkpoint(d, 2, newer,
                             lineage=ckpt_lib.make_lineage("refit"))
    assert ckpt_lib.has_verified(d, 2)
    # rot every byte of step 2's array data
    for root, _, files in os.walk(os.path.join(d, "2")):
        for f in files:
            p = os.path.join(root, f)
            if os.path.getsize(p):
                faults.bit_flip_file(p, seed=3, flips=32)
    assert not ckpt_lib.has_verified(d, 2)
    state, step = ckpt_lib.restore_verified(d)
    assert step == 1  # fell through to last-good
    np.testing.assert_array_equal(state["params"]["w"], good["params"]["w"])
    assert os.path.isdir(os.path.join(d, "quarantine"))
    assert ckpt_lib.all_steps(d) == [1]  # the corrupt step is gone
    assert obs_registry().counter("mho_ckpt_quarantined_total").total() >= 1


def test_poison_checkpoint_is_checksum_valid_and_seeded(tmp_path):
    """The semantic fault family's defining property: a weight-poisoned
    checkpoint goes through the NORMAL save path, so integrity verification
    passes — the corruption byte checks can never catch it."""
    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    d = str(tmp_path / "orbax")
    w = np.linspace(0.1, 1.6, 16, dtype=np.float32).reshape(4, 4)
    ckpt_lib.save_checkpoint(d, 1, {"params": {"w": w}},
                             lineage=ckpt_lib.make_lineage("offline"))
    step = faults.poison_checkpoint(d, mode="nan", seed=3, fraction=0.25)
    assert step == 2
    assert ckpt_lib.has_verified(d, 2)  # checksum-VALID poison
    restored, got = ckpt_lib.restore_verified(d)
    assert got == 2
    bad = np.asarray(restored["params"]["w"])
    assert int(np.isnan(bad).sum()) == 4  # fraction of the 16 entries
    np.testing.assert_array_equal(w[~np.isnan(bad)], bad[~np.isnan(bad)])
    assert ckpt_lib.load_lineage(d, step=2)["source"] == "poison"
    # determinism: the same seed poisons the same entries
    again, _ = ckpt_lib.restore_verified(d)
    np.testing.assert_array_equal(np.isnan(bad),
                                  np.isnan(np.asarray(again["params"]["w"])))
    with pytest.raises(ValueError, match="unknown poison mode"):
        faults.poison_checkpoint(d, mode="zero")


def test_gc_checkpoints_bounded_retention(tmp_path):
    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    obs_registry().reset()
    d = str(tmp_path / "cand")
    t = {"params": {"w": np.zeros((2,), np.float32)}}
    for s in (1, 2, 3):
        ckpt_lib.save_checkpoint(d, s, t,
                                 lineage=ckpt_lib.make_lineage("refit"))
    assert ckpt_lib.gc_checkpoints(d, keep=1, reason="test") == [1, 2]
    assert ckpt_lib.all_steps(d) == [3]
    assert not os.path.exists(os.path.join(d, "lineage", "1.json"))
    assert not os.path.exists(os.path.join(d, "integrity", "2.json"))
    assert obs_registry().counter("mho_ckpt_gc_total").total() == 2
    # keep <= 0 disables; nothing else to delete either way
    assert ckpt_lib.gc_checkpoints(d, keep=2) == []


# ---- journal durability -----------------------------------------------------


def test_journal_round_trip_and_cooldown_survive_restart(tmp_path):
    from multihop_offload_tpu.loop.promote import PromotionController

    t = {"now": 100.0}
    ctl = PromotionController(str(tmp_path), clock=lambda: t["now"],
                              cooldown_s=60.0)
    ctl.transition("refitting", candidate_step=5, champion_step=1)
    ctl.note(pre_tau=0.42)
    ctl.start_cooldown()
    # "restart": a fresh controller over the same dir
    ctl2 = PromotionController.resume(str(tmp_path),
                                      clock=lambda: t["now"],
                                      cooldown_s=60.0)
    assert ctl2.resumed and ctl2.state == "refitting"
    assert ctl2.ctx["candidate_step"] == 5
    assert ctl2.ctx["pre_tau"] == 0.42
    assert ctl2.cooldown_remaining() == 60.0
    t["now"] += 61.0
    assert ctl2.cooldown_remaining() == 0.0


def test_fresh_dir_resumes_idle(tmp_path):
    from multihop_offload_tpu.loop.promote import PromotionController

    ctl = PromotionController.resume(str(tmp_path / "virgin"))
    assert ctl.state == "idle" and not ctl.resumed


# ---- watchdog ---------------------------------------------------------------


def test_watchdog_verdicts_and_counters():
    from multihop_offload_tpu.serve.watchdog import TickWatchdog

    obs_registry().reset()
    wd = TickWatchdog(threshold_s=1.0, stuck_factor=10.0)
    assert wd.observe(0, 0.5) == "ok"
    assert wd.observe(0, 2.0) == "slow"
    assert wd.observe(0, 15.0) == "stuck"
    assert wd.slow == 1 and wd.stuck == 1
    reg = obs_registry()
    assert reg.counter("mho_watchdog_slow_total").total(bucket="0") == 1
    assert reg.counter("mho_watchdog_stuck_total").total(bucket="0") == 1
    with pytest.raises(ValueError):
        TickWatchdog(threshold_s=0.0)


# ---- the kill-point matrix --------------------------------------------------


@pytest.fixture(scope="module")
def smoke(tmp_path_factory):
    """One compiled service + the uninterrupted baseline cycle every kill
    case must converge to."""
    obs_registry().reset()
    harness = ChaosSmoke(Config(seed=0, dtype="float32"),
                         str(tmp_path_factory.mktemp("chaos")))
    rec = harness.run_baseline()
    assert rec["ok"], rec
    return harness


@pytest.mark.parametrize("site", KILL_SITES)
def test_kill_and_resume_reaches_baseline_terminal(smoke, site):
    rec = smoke.run_kill(site)
    checks = rec["checks"]
    assert checks["crash_fired"], f"{site}: fault never injected"
    assert checks["resumed"], f"{site}: restart did not complete"
    assert checks["same_terminal"], (
        f"{site}: resumed terminal {rec['terminal']} != "
        f"baseline {smoke.baseline_terminal}"
    )
    assert checks["decisions_never_wrong"], f"{site}: golden decisions moved"
    assert checks["conservation"], f"{site}: requests lost or duplicated"
    # the resumed run entered through the journaled phase, not from idle
    assert rec["resumed_from"] is not None, f"{site}: journal not consulted"


def test_device_loss_drill_replaces_and_recovers(smoke):
    """The kill-one-device drill on the virtual 8-device fleet: forced
    re-placement onto survivors, bit-parity (or honest degradation) across
    the loss, conservation, and fleet restoration."""
    rec = smoke.run_device_loss()
    assert "skipped" not in rec, rec  # conftest provides 8 devices
    checks = rec["checks"]
    assert checks["multi_device_before_loss"], rec
    assert checks["plan_excludes_lost_device"], rec
    assert checks["decisions_never_wrong"], "golden decisions moved"
    assert checks["conservation"], "requests lost or duplicated"
    assert checks["fleet_restored"] and checks["served_after_restore"], rec


def test_host_loss_drill_replans_and_conserves(smoke):
    """The kill-a-whole-host drill: a two-level plan spanning both
    simulated hosts, victim host removed -> forced replan onto the
    survivor only, bit-parity (or honest degradation) across the loss,
    conservation, zero unexpected retraces, host restored."""
    rec = smoke.run_host_loss()
    assert "skipped" not in rec, rec  # conftest provides 8 devices
    checks = rec["checks"]
    assert checks["plan_spans_hosts_before_loss"], rec
    assert checks["forced_replan_excludes_victim"], rec
    assert checks["decisions_never_wrong"], "golden decisions moved"
    assert checks["conservation"], "requests lost or duplicated"
    assert checks["zero_unexpected_retraces"], rec
    assert checks["host_restored"], rec


def test_weight_poison_hot_reload_drill(smoke):
    """Checksum-valid NaN poison at the hot-reload surface: both polls
    refused (second proves the cached rejection), champion keeps serving,
    nothing quarantined — refusal is semantic, not corruption."""
    rec = smoke.run_weight_poison_hot_reload()
    checks = rec["checks"]
    assert checks["poison_passes_checksum"], "poison must be checksum-valid"
    assert checks["reload_refused"], rec
    assert checks["stayed_on_champion"], rec
    assert checks["canary_reject_event"], "no canary_reject at stage hot_reload"
    assert checks["no_quarantine"], "semantic refusal must not quarantine"
    assert checks["still_gnn_on_champion"], rec


def test_weight_poison_promotion_drill(smoke):
    """The same fault class at the promotion surface: refused in the
    journaled 'canarying' state BEFORE any write-ahead intent, with the
    typed nonfinite reason, champion untouched."""
    rec = smoke.run_weight_poison_promotion()
    checks = rec["checks"]
    assert checks["promotion_refused"], rec
    assert checks["canarying_journaled"], rec
    assert checks["no_serving_step_pinned"], rec
    assert checks["canary_reject_event"], "no canary_reject at stage promote"
    assert checks["typed_reason"], rec
    assert checks["champion_still_serving"], rec

"""sim/: packet conservation, MWIS feasibility, failure-injection
determinism, low-utilization agreement with the analytic model, and
queue-state migration across mobility re-wiring."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.env.policies import baseline_policy
from multihop_offload_tpu.graphs import generators
from multihop_offload_tpu.graphs.instance import PadSpec, stack_instances
from multihop_offload_tpu.graphs.topology import build_topology
from multihop_offload_tpu.sim import (
    FleetSim,
    build_sim_params,
    conservation_gap,
    in_flight,
    make_policy,
    migrate_sim_state,
    spec_for,
)
from multihop_offload_tpu.sim.fidelity import (
    analytic_link_delay,
    empirical_queue_delays,
    make_case,
    scale_to_util,
)

PAD = PadSpec(n=16, l=32, s=8, j=8)
FAIL_SLOT = 300


def _cases(seeds, num_jobs=4):
    out = []
    for s in seeds:
        topo = build_topology(generators.barabasi_albert(10, seed=s)[0])
        inst, jobs = make_case(s, topo, PAD, num_jobs=num_jobs)
        out.append((topo, inst, jobs))
    return out


@pytest.fixture(scope="module")
def fleet_run():
    """One 2-lane baseline-policy run, schedule trace collected; lane 1
    loses a link and a (non-server, non-source) node at FAIL_SLOT."""
    cases = _cases((1, 2))
    topo1, inst1, jobs1 = cases[1]
    # fail the busiest link of lane 1's decision so the outage is observable
    out1 = baseline_policy(inst1, jobs1, jax.random.PRNGKey(0))
    lam1 = np.asarray(out1.delays.link_lambda, np.float64)
    lam1[~np.asarray(inst1.link_mask)] = -1.0
    kill_link = int(np.argmax(lam1))
    srcs = np.asarray(jobs1.src)[np.asarray(jobs1.mask)]
    servers = np.asarray(inst1.servers)[np.asarray(inst1.server_mask)]
    kill_node = int(np.setdiff1d(
        np.arange(topo1.n), np.concatenate([srcs, servers])
    )[0])
    paramss = []
    for i, (topo, inst, jobs) in enumerate(cases):
        fl = np.full((PAD.l,), -1, np.int32)
        fn = np.full((PAD.n,), -1, np.int32)
        if i == 1:
            fl[kill_link] = FAIL_SLOT
            fn[kill_node] = FAIL_SLOT
        paramss.append(build_sim_params(inst, jobs, margin=4.0,
                                        fail_link_slot=fl, fail_node_slot=fn))
    insts = stack_instances([c[1] for c in cases])
    jobss = stack_instances([c[2] for c in cases])
    params = stack_instances(paramss)
    spec = spec_for(cases[0][1], cases[0][2], cap=64)
    sim = FleetSim(spec, make_policy("baseline"), rounds=3,
                   slots_per_round=400, collect_schedule=True)
    keys = jax.random.split(jax.random.PRNGKey(7), 2)
    rates = jnp.stack([c[2].rate for c in cases])
    run = sim.run(insts, jobss, params, keys, init_rates=rates)
    return {
        "cases": cases, "spec": spec, "sim": sim, "run": run, "keys": keys,
        "insts": insts, "jobss": jobss, "params": params, "rates": rates,
        "kill_link": kill_link, "kill_node": kill_node,
    }


def test_packet_conservation(fleet_run):
    """generated == delivered + dropped + in-flight, exactly, per lane."""
    gap = jax.vmap(conservation_gap)(fleet_run["run"].state)
    np.testing.assert_array_equal(np.asarray(gap), 0)
    gen = np.asarray(fleet_run["run"].state.generated)
    assert (gen.sum(axis=1) > 0).all()
    assert (np.asarray(fleet_run["run"].state.delivered).sum(axis=1) > 0).all()


def test_mwis_schedule_is_always_feasible(fleet_run):
    """No slot ever activates two conflicting links (per-slot MWIS)."""
    for lane in range(2):
        inst = fleet_run["cases"][lane][1]
        sched = np.asarray(fleet_run["run"].sched[lane], np.float64)
        sched = sched.reshape(-1, fleet_run["spec"].num_links)
        cf = np.asarray(inst.adj_conflict, np.float64)
        violations = np.einsum("tl,lk,tk->t", sched, cf, sched)
        assert (violations == 0).all()


def test_failure_injection_takes_links_down(fleet_run):
    """The failed link transmits before its failure slot and never wins the
    schedule afterwards."""
    k = fleet_run["kill_link"]
    sched = np.asarray(fleet_run["run"].sched)  # (fleet, R, K, L)
    flat = sched.reshape(2, -1, fleet_run["spec"].num_links)
    assert flat[1, :FAIL_SLOT, k].any()
    assert not flat[1, FAIL_SLOT:, k].any()


def test_failure_run_is_deterministic_under_fixed_key(fleet_run):
    """Same fleet, same keys, failures included -> bitwise-identical
    counters (the whole program is one jitted pure function)."""
    rerun = fleet_run["sim"].run(
        fleet_run["insts"], fleet_run["jobss"], fleet_run["params"],
        fleet_run["keys"], init_rates=fleet_run["rates"],
    )
    for field in ("generated", "delivered", "dropped", "delay_sum", "count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rerun.state, field)),
            np.asarray(getattr(fleet_run["run"].state, field)),
        )


def test_migrate_sim_state_conserves_packets(fleet_run):
    """Dropping a link at a mobility boundary strands its queued packets;
    migration counts them as drops so conservation still holds, and a
    follow-on segment from the migrated state (same compiled program)
    conserves too."""
    spec = fleet_run["spec"]
    lane0 = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[0], fleet_run["run"].state
    )
    topo0 = fleet_run["cases"][0][0]
    # identity re-wiring except link 0 vanishes (new link 0 is "new")
    link_map = np.arange(topo0.num_links, dtype=np.int64)
    link_map[0] = -1
    stranded = int(lane0.count[0] + lane0.count[spec.num_links])
    mig = migrate_sim_state(lane0, link_map, spec)
    assert int(conservation_gap(mig)) == 0
    assert int(in_flight(lane0)) - int(in_flight(mig)) == stranded
    assert (np.asarray(mig.dropped).sum()
            == np.asarray(lane0.dropped).sum() + stranded)
    np.testing.assert_array_equal(np.asarray(mig.generated),
                                  np.asarray(lane0.generated))
    assert int(mig.count[0]) == 0 and int(mig.count[spec.num_links]) == 0

    states = stack_instances([mig, mig])
    seg2 = fleet_run["sim"].run(
        fleet_run["insts"], fleet_run["jobss"], fleet_run["params"],
        fleet_run["keys"], states=states, init_rates=fleet_run["rates"],
    )
    gap = jax.vmap(conservation_gap)(seg2.state)
    np.testing.assert_array_equal(np.asarray(gap), 0)


def test_low_utilization_matches_analytic_model():
    """At bottleneck rho ~0.35 the measured per-channel sojourn agrees with
    the analytic 1/(mu - lambda) within 25% traffic-weighted (the committed
    benchmarks/sim_fidelity.json record holds <=10% at larger horizons)."""
    cases = _cases((3, 4))
    bp = jax.jit(baseline_policy)
    insts, jobss, paramss, outs = [], [], [], []
    for s, (topo, inst, jobs) in enumerate(cases):
        jobs, out = scale_to_util(inst, jobs, jax.random.PRNGKey(s), 0.35,
                                  policy_fn=bp)
        insts.append(inst)
        jobss.append(jobs)
        outs.append(out)
        paramss.append(build_sim_params(inst, jobs, margin=6.0))
    spec = spec_for(insts[0], jobss[0], cap=64)
    sim = FleetSim(spec, make_policy("baseline"), rounds=2,
                   slots_per_round=2200)
    keys = jax.random.split(jax.random.PRNGKey(11), 2)
    run = sim.run(stack_instances(insts), stack_instances(jobss),
                  stack_instances(paramss), keys,
                  init_rates=jnp.stack([j.rate for j in jobss]))
    compared = 0
    for lane in range(2):
        st = jax.tree_util.tree_map(lambda x: np.asarray(x)[lane], run.state)
        dt = float(np.asarray(paramss[lane].dt))
        emp_l, _ = empirical_queue_delays(st, spec, dt, min_served=60)
        ana_l = analytic_link_delay(insts[lane], outs[lane])
        lam = np.asarray(outs[lane].delays.link_lambda, np.float64)
        ok = np.isfinite(emp_l) & np.isfinite(ana_l) & (lam > 0)
        assert ok.any(), "no comparable links at this horizon"
        rel = np.abs(emp_l[ok] - ana_l[ok]) / ana_l[ok]
        w = lam[ok] / lam[ok].sum()
        assert float((rel * w).sum()) < 0.25
        compared += int(ok.sum())
    assert compared >= 6


@pytest.mark.slow
def test_soak_10k_slots():
    """Long-horizon soak: 10k slots per lane, counters stay exact and every
    statistic stays finite."""
    cases = _cases((5, 6))
    paramss = [build_sim_params(inst, jobs, margin=4.0)
               for _, inst, jobs in cases]
    spec = spec_for(cases[0][1], cases[0][2], cap=64)
    sim = FleetSim(spec, make_policy("baseline"), rounds=5,
                   slots_per_round=2000)
    keys = jax.random.split(jax.random.PRNGKey(13), 2)
    run = sim.run(stack_instances([c[1] for c in cases]),
                  stack_instances([c[2] for c in cases]),
                  stack_instances(paramss), keys,
                  init_rates=jnp.stack([c[2].rate for c in cases]))
    gap = jax.vmap(conservation_gap)(run.state)
    np.testing.assert_array_equal(np.asarray(gap), 0)
    assert int(np.asarray(run.state.t).min()) == 10000
    for leaf in jax.tree_util.tree_leaves(run.state):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f":
            assert np.isfinite(arr).all()
    assert (np.asarray(run.state.delivered).sum(axis=1) > 0).all()

"""obs/prof + obs/memwatch: registration idempotence, FLOP-correction
parity with bench.py, MFU/HBM gauge math under fake peaks, breach-capture
fire-once semantics, degradation paths, and the report's performance
section."""

import math
import os
import sys

import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.obs import prof as obs_prof
from multihop_offload_tpu.obs.memwatch import MemWatch
from multihop_offload_tpu.obs.prof import (
    BreachCapture,
    ProgramRegistry,
    scan_corrected_flops,
)
from multihop_offload_tpu.obs.registry import MetricRegistry
from multihop_offload_tpu.obs.slo import SLOEngine, default_serving_slos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _series(reg: MetricRegistry, name: str) -> dict:
    snap = reg.snapshot().get(name) or {}
    return snap.get("series") or {}


def _program_value(reg: MetricRegistry, name: str, program: str):
    for labels, v in _series(reg, name).items():
        if f'program="{program}"' in labels:
            return v
    return None


# ---- registration -----------------------------------------------------------

def test_register_idempotent_across_recompiles():
    """Re-registering (hot-reload rebuild) refreshes facts and bumps the
    compile count but preserves cumulative call/device counters."""
    reg = MetricRegistry()
    prof = ProgramRegistry(reg, peak_tflops_=1.0, peak_hbm_gbps_=1.0)
    prof.register("p", flops=100.0, bytes_accessed=50.0, compile_s=1.0)
    prof.account("p", 2.0, calls=4)
    rec = prof.get("p")
    assert rec.compiles == 1 and rec.calls == 4 and rec.device_s == 2.0

    prof.register("p", flops=200.0, bytes_accessed=80.0, compile_s=0.5)
    rec = prof.get("p")
    assert rec.compiles == 2
    assert rec.flops == 200.0 and rec.bytes_accessed == 80.0
    assert rec.calls == 4 and rec.device_s == 2.0  # usage survives
    assert rec.compile_s == 0.5
    assert _program_value(reg, "mho_program_compile_seconds", "p") == 0.5


def test_register_extracts_from_compiled_executable():
    f = jax.jit(lambda x: (x @ x).sum())
    compiled = f.lower(jnp.ones((16, 16))).compile()
    prof = ProgramRegistry(MetricRegistry(), peak_tflops_=1.0,
                           peak_hbm_gbps_=1.0)
    rec = prof.register("mm", compiled, compile_s=0.1)
    assert rec.flops and rec.flops > 0
    assert rec.bytes_accessed and rec.bytes_accessed > 0
    assert rec.to_json()["arithmetic_intensity"] is not None


def test_wrap_registers_on_first_call_and_accounts():
    reg = MetricRegistry()
    prof = ProgramRegistry(reg, peak_tflops_=1.0, peak_hbm_gbps_=1.0)
    calls = []
    wrapped = obs_prof.wrap(
        "w", jax.jit(lambda x: x + 1), prof=prof,
        correction=lambda f: calls.append(f) or f)
    out = wrapped(jnp.arange(4.0))
    assert float(out[1]) == 2.0
    rec = prof.get("w")
    assert rec is not None and rec.compiles == 1
    assert rec.compile_s is not None and rec.compile_s > 0
    # second call reuses the compiled object — no re-register
    wrapped(jnp.arange(4.0))
    assert prof.get("w").compiles == 1
    wrapped.account(0.5)
    # the first accounted window deducts the pending compile time once
    assert prof.get("w").device_s == pytest.approx(
        max(0.5 - rec.compile_s, 0.0))


def test_wrap_passes_keyword_arguments():
    """The trainer calls its replay program with `key=`; the wrapper must
    thread kwargs through both the AOT executable and the jit fallback."""
    prof = ProgramRegistry(MetricRegistry(), peak_tflops_=1.0,
                           peak_hbm_gbps_=1.0)
    wrapped = obs_prof.wrap(
        "kw", jax.jit(lambda x, *, scale: x * scale), prof=prof)
    out = wrapped(jnp.arange(4.0), scale=jnp.float32(3.0))
    assert float(out[2]) == 6.0
    out = wrapped(jnp.arange(4.0), scale=jnp.float32(2.0))
    assert float(out[3]) == 6.0
    assert prof.get("kw") is not None


def test_wrap_falls_back_to_jit_on_shape_change():
    prof = ProgramRegistry(MetricRegistry(), peak_tflops_=1.0,
                           peak_hbm_gbps_=1.0)
    wrapped = obs_prof.wrap("shapes", jax.jit(lambda x: x * 2), prof=prof)
    wrapped(jnp.arange(4.0))
    out = wrapped(jnp.arange(8.0))  # AOT executable rejects; jit retraces
    assert out.shape == (8,)
    assert float(out[3]) == 6.0


# ---- the FLOP correction ----------------------------------------------------

def test_scan_corrected_flops_golden_parity_with_bench():
    """The exact bench.py math, and bench aliases THIS function — forking
    either copy fails here."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    assert bench._loop_corrected_flops is scan_corrected_flops

    ca, n, l, b = 1e9, 24, 64, 8
    iters = max(1, math.ceil(math.log2(n - 1)))
    expect = ca + (iters - 1) * 2.0 * b * n**3 + 5 * 9 * 2.0 * b * l**2
    assert scan_corrected_flops(ca, n, l, b) == pytest.approx(expect)
    # pallas path charges nothing for the fp interior: all 10 passes added
    expect_p = ca + (iters - 1) * 2.0 * b * n**3 + 5 * 10 * 2.0 * b * l**2
    assert scan_corrected_flops(ca, n, l, b,
                                fp_path="pallas") == pytest.approx(expect_p)


def test_peak_tables_and_env_override(monkeypatch):
    assert obs_prof.peak_tflops("TPU v4") == 275.0
    assert obs_prof.peak_hbm_gbps("TPU v5e") == 819.0
    assert obs_prof.peak_tflops("weird accelerator") is None
    monkeypatch.setenv("MHO_PROF_PEAK_TFLOPS", "123.5")
    assert obs_prof.peak_tflops("weird accelerator") == 123.5
    monkeypatch.setenv("MHO_PROF_PEAK_TFLOPS", "not-a-number")
    assert obs_prof.peak_tflops("TPU v2") == 46.0


# ---- gauge math -------------------------------------------------------------

def test_mfu_and_hbm_gauges_under_fake_peaks():
    """Injected peaks: 1 TFLOP/s and 10 GB/s.  2e11 corrected flops and
    4e9 bytes per call, 10 calls over 4 s -> MFU 0.5, HBM frac 1.0."""
    reg = MetricRegistry()
    prof = ProgramRegistry(reg, peak_tflops_=1.0, peak_hbm_gbps_=10.0)
    prof.register("g", flops=2e11, bytes_accessed=4e9)
    prof.account("g", 4.0, calls=10)
    assert _program_value(reg, "mho_program_mfu", "g") == pytest.approx(0.5)
    assert _program_value(
        reg, "mho_program_hbm_frac", "g") == pytest.approx(1.0)
    assert _program_value(
        reg, "mho_program_flops_total", "g") == pytest.approx(2e12)
    assert _program_value(
        reg, "mho_program_bytes_total", "g") == pytest.approx(4e10)


def test_no_gauges_without_peaks_or_time():
    reg = MetricRegistry()
    prof = ProgramRegistry(reg, peak_tflops_=None, peak_hbm_gbps_=None)
    prof.register("q", flops=1e9, bytes_accessed=1e6)
    prof.account("q", 1.0)
    assert _program_value(reg, "mho_program_mfu", "q") is None
    # zero device time: calls counted, no rate invented
    prof2 = ProgramRegistry(MetricRegistry(), peak_tflops_=1.0,
                            peak_hbm_gbps_=1.0)
    prof2.register("z", flops=1e9, bytes_accessed=1e6)
    prof2.account("z", 0.0)
    assert prof2.get("z").calls == 1


def test_snapshot_round_trips_records():
    prof = ProgramRegistry(MetricRegistry(), peak_tflops_=1.0,
                           peak_hbm_gbps_=1.0)
    prof.register("s", flops=10.0, bytes_accessed=5.0, compile_s=0.2)
    prof.account("s", 1.0, calls=2)
    snap = prof.snapshot()
    assert snap["s"]["flops"] == 10.0 and snap["s"]["calls"] == 2
    assert snap["s"]["arithmetic_intensity"] == 2.0


# ---- breach capture ---------------------------------------------------------

def test_breach_capture_fires_exactly_once_per_breach(tmp_path):
    """ok->firing grabs one capture; staying in breach grabs none; the
    resolve->re-breach cycle grabs exactly one more."""
    reg = MetricRegistry()
    engine = SLOEngine(
        default_serving_slos(latency_le=0.1), registry=reg,
        short_s=2.0, long_s=8.0,
    )
    traced = []
    cap = BreachCapture(
        str(tmp_path), slos=("serve_p99",), clock=lambda: now[0],
        tracer=lambda path, dur, fn: traced.append(path) or path,
    )
    engine.on_breach(cap.on_breach)
    lat = reg.histogram("mho_serve_latency_seconds", "latency")
    now = [0.0]

    def drive(value, ticks):
        for _ in range(ticks):
            lat.observe(value)
            now[0] += 1.0
            engine.observe(now[0])

    drive(0.5, 12)                       # breach: fires once
    assert len(traced) == 1 and "serve_p99" in traced[0]
    drive(0.5, 6)                        # still firing: no second capture
    assert len(traced) == 1
    drive(0.01, 30)                      # recover: alert resolves
    assert engine.state()["serve_p99"]["state"] == "ok"
    drive(0.5, 12)                       # re-breach: exactly one more
    assert len(traced) == 2
    assert cap.captures == traced


def test_breach_capture_filters_and_cooldown(tmp_path):
    traced = []
    cap = BreachCapture(
        str(tmp_path), slos=("serve_mfu",), clock=lambda: now[0],
        min_interval_s=10.0,
        tracer=lambda path, dur, fn: traced.append(path) or path,
    )
    now = [0.0]

    class Spec:
        name = "serve_p99"

    assert cap.on_breach(Spec(), {}) == ""   # unwatched SLO: ignored
    Spec.name = "serve_mfu"
    assert cap.on_breach(Spec(), {})         # watched: captures
    now[0] = 5.0
    assert cap.on_breach(Spec(), {}) == ""   # inside cooldown
    now[0] = 20.0
    assert cap.on_breach(Spec(), {})
    assert len(traced) == 2


def test_gauge_min_slo_fires_on_low_mfu():
    """The serve_mfu spec (gauge_min) breaches when any program's MFU
    gauge sits under the floor, and ignores a registry with no gauge."""
    reg = MetricRegistry()
    engine = SLOEngine(
        default_serving_slos(mfu_floor=0.5), registry=reg,
        short_s=2.0, long_s=8.0,
    )
    for tick in range(12):               # no gauge at all: never fires
        engine.observe(float(tick))
    assert engine.state()["serve_mfu"]["state"] == "ok"
    reg.gauge("mho_program_mfu", "").set(0.01, program="serve/bucket0/gnn")
    for tick in range(12, 30):
        engine.observe(float(tick))
    assert engine.state()["serve_mfu"]["state"] == "firing"


# ---- degradation ------------------------------------------------------------

def test_capture_trace_never_raises_on_bad_dir():
    path = obs_prof.capture_trace("/proc/definitely/not/writable")
    assert path == ""


def test_extract_cost_degrades_on_junk():
    class Junk:
        def cost_analysis(self):  # prof-ok(test double for the extractor)
            raise RuntimeError("no backend")

        def memory_analysis(self):  # prof-ok(same)
            raise RuntimeError("no backend")

    facts = obs_prof.extract_cost(Junk())
    assert facts == {"flops": None, "bytes_accessed": None,
                     "argument_bytes": None, "temp_bytes": None}


def test_memwatch_degrades_and_tracks_watermarks():
    reg = MetricRegistry()
    stats = {"cpu:0": {"bytes_in_use": 10, "peak_bytes_in_use": 100}}
    mw = MemWatch(reg, stats_fn=lambda: stats)
    assert mw.snapshot("warm")
    stats["cpu:0"]["peak_bytes_in_use"] = 50    # below the high water
    mw.snapshot("later")
    assert mw.watermarks()["cpu:0"] == 100

    broken = MemWatch(reg, stats_fn=lambda: (_ for _ in ()).throw(
        RuntimeError("wedged backend")))
    assert broken.snapshot("x") == {}            # never raises


# ---- report section ---------------------------------------------------------

def test_report_performance_section_and_graceful_omission(tmp_path):
    from multihop_offload_tpu.obs.events import RunLog, run_manifest
    from multihop_offload_tpu.obs.report import load_run, render_report

    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest=run_manifest(role="prof"))
    log.summary(
        metrics={
            "mho_program_mfu": {
                "kind": "gauge", "help": "",
                "series": {'{program="bench/step"}': 0.1234},
            },
        },
        programs={
            "bench/step": {"flops": 1e9, "flops_corrected": 2e9,
                           "bytes_accessed": 1e8,
                           "arithmetic_intensity": 20.0,
                           "compile_s": 3.2, "compiles": 1,
                           "calls": 10, "device_s": 1.5},
        },
    )
    log.close()
    run = load_run(path)
    assert run["programs"]["bench/step"]["calls"] == 10
    text = render_report(path)
    assert "performance (per program)" in text
    assert "bench/step" in text and "0.1234" in text

    # pre-prof log: the section is omitted, nothing raises
    old = str(tmp_path / "old.jsonl")
    log2 = RunLog(old, manifest=run_manifest(role="train"))
    log2.summary(phases={}, metrics={})
    log2.close()
    assert "performance (per program)" not in render_report(old)

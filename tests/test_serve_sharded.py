"""serve/sharded + serve/placement wired into the service: the multi-chip
serving tick.

The load-bearing property is the same one `tests/test_serve.py` pins for
batching: sharding is purely a throughput transform.  The sharded executor
compiles the SAME per-slot closures as the single-device one
(`BucketExecutor._bucket_closures` is shared), so decisions must be
bit-identical across any placement — the only cross-device communication
is the fleet-metrics allreduce.  On top of that: placement only changes
between ticks (re-placement compiles are EXPECTED builds, never unexpected
retraces), a stuck device degrades only the buckets placed on it, and
losing a chip re-places onto the survivors without dropping or corrupting
a single response.

Runs on 8 virtual CPU devices (conftest sets
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import time

import jax
import numpy as np
import pytest

from multihop_offload_tpu.cli.serve import build_service
from multihop_offload_tpu.config import Config
from multihop_offload_tpu.serve.workload import case_pool, request_stream


def _service(mesh=0, slots=4, buckets=1, sizes="10", clock=None, **cfg_kw):
    """Small sharded (or not) service on synthetic traffic, fresh-init
    weights — same seed everywhere, so every variant holds identical
    params and decisions are comparable bit-for-bit."""
    cfg = Config(seed=7, dtype="float32", serve_sizes=sizes,
                 serve_buckets=buckets, serve_slots=slots, serve_mesh=mesh,
                 serve_deadline_s=600.0, serve_queue_cap=256,
                 model_root="/nonexistent-model-root", **cfg_kw)
    pool = case_pool([int(s) for s in sizes.split(",")],
                     per_size=1, seed=cfg.seed)
    return build_service(cfg, pool=pool, clock=clock)


def _serve(service, pool, count, seed=11, id_offset=0):
    """Closed loop until drained; responses keyed by request id."""
    pending = list(request_stream(pool, count, seed=seed,
                                  id_offset=id_offset))
    pending.reverse()
    out = {}
    while pending or service.queue_depth:
        while pending:
            req = pending.pop()
            if not service.submit(req):
                pending.append(req)
                break
        for r in service.tick():
            out[r.request_id] = r
    return out


def _same_decisions(a, b) -> bool:
    return (np.array_equal(a.dst, b.dst)
            and np.array_equal(a.is_local, b.is_local)
            and np.array_equal(a.delay_est, b.delay_est)
            and np.array_equal(a.job_total, b.job_total))


# ---- bit parity ----------------------------------------------------------


def test_sharded_decisions_bit_identical_to_unsharded():
    """The tentpole invariant: the mesh never changes an answer.  Both
    executors compile the same closures; the batch-axis partition of a
    vmap is per-slot independent, so every decision array must match
    bit-for-bit."""
    plain, pool = _service(mesh=0, buckets=2, sizes="10,16")
    sharded, _ = _service(mesh=4, buckets=2, sizes="10,16")
    got_plain = _serve(plain, pool, 12)
    got_sharded = _serve(sharded, pool, 12)
    assert set(got_plain) == set(got_sharded) and len(got_plain) == 12
    for rid in got_plain:
        a, b = got_plain[rid], got_sharded[rid]
        assert a.served_by == b.served_by == "gnn"
        assert _same_decisions(a, b), f"request {rid} diverged under sharding"


def test_sharded_dispatch_spans_multiple_devices():
    service, pool = _service(mesh=4)
    got = _serve(service, pool, 8)
    assert len(got) == 8
    # read off the OUTPUT sharding, not the config: catches a silent
    # single-device fallback
    assert service.executor.last_devices_used > 1
    # demuxed responses carry the per-slot shard (device id) label
    assert len({r.shard for r in got.values()}) > 1
    assert all(r.shard != "" for r in got.values())
    # the fleet-metrics allreduce rode along with the last dispatch
    m = service.executor.last_metrics
    assert m is not None and {"job_total_sum", "delay_est_max"} <= set(m)


def test_summary_gains_buckets_and_shards_blocks():
    service, pool = _service(mesh=4, buckets=2, sizes="10,16")
    _serve(service, pool, 12)
    s = service.stats.summary(wall_s=1.0)
    assert set(s["buckets"]) == {"0", "1"}
    for entry in s["buckets"].values():
        assert entry["offered"] >= entry["served"] > 0
        assert "offered_per_sec" in entry and "served_per_sec" in entry
    assert len(s["shards"]) > 1
    assert sum(e["served"] for e in s["shards"].values()) == s["served"]


def test_unsharded_summary_stays_backward_compatible():
    """The `shards` block is sharded-only; `buckets` appears everywhere
    (offered counts are tracked by admission, not by the mesh)."""
    service, pool = _service(mesh=0)
    _serve(service, pool, 6)
    s = service.stats.summary(wall_s=1.0)
    assert "shards" not in s
    assert s["buckets"]["0"]["offered"] == 6


# ---- per-shard health ----------------------------------------------------


def test_stuck_device_degrades_only_co_placed_buckets():
    """Per-shard verdicts: a stall on bucket 0's devices must degrade
    bucket 0 to the baseline for the recovery window while bucket 1 —
    placed on OTHER chips — keeps serving the GNN, and recovery restores
    bucket 0."""
    from multihop_offload_tpu.serve.watchdog import TickWatchdog

    t = {"now": 0.0}
    service, pool = _service(mesh=4, buckets=2, sizes="10,16",
                             clock=lambda: t["now"])
    wd = TickWatchdog(threshold_s=0.5, recovery_s=30.0, stuck_factor=10.0,
                      clock=lambda: t["now"])
    service.attach_watchdog(wd)
    d0 = set(service.executor.devices_for(0))
    d1 = set(service.executor.devices_for(1))
    assert d0 and d1 and not (d0 & d1), "test needs disjoint placements"

    ex = service.executor
    orig_run = ex.run
    stall = {"s": 0.0}

    def stalling_run(bucket, *a, **kw):
        if bucket == 0:
            t["now"] += stall["s"]
        return orig_run(bucket, *a, **kw)

    ex.run = stalling_run
    try:
        stall["s"] = 6.0                      # stuck: 6.0 > 0.5 * 10
        _serve(service, pool, 8, id_offset=1_000)
        assert wd.stuck >= 1
        stall["s"] = 0.0                      # wedge cleared, window open
        held = _serve(service, pool, 8, id_offset=2_000)
        by_bucket = {}
        for r in held.values():
            by_bucket.setdefault(r.bucket, set()).add(r.served_by)
        assert by_bucket[0] == {"baseline"}, "stuck devices must degrade"
        assert by_bucket[1] == {"gnn"}, (
            "bucket on healthy devices must NOT degrade"
        )
        t["now"] += 31.0                      # recovery window expires
        back = _serve(service, pool, 8, id_offset=3_000)
        assert {r.served_by for r in back.values()} == {"gnn"}
    finally:
        ex.run = orig_run
    # the stuck counters carry per-device labels
    from multihop_offload_tpu.obs.registry import registry
    stuck = registry().counter("mho_watchdog_stuck_total")
    assert any("device" in dict(k) for k in getattr(
        stuck, "_series", {}) or []) or stuck.total() >= 1


# ---- device loss ---------------------------------------------------------


def test_device_loss_replaces_and_conserves():
    """Chip loss between windows: the planner re-places every bucket onto
    the survivors, the same request ids re-serve bit-identically (keys are
    structural), and admitted == served throughout."""
    service, pool = _service(mesh=4, buckets=2, sizes="10,16")
    golden = _serve(service, pool, 12, id_offset=5_000)
    victim = service.executor.devices_for(0)[-1]
    service.lose_device(victim)
    assert not service.planner.plan.uses(victim)
    assert all(devs for devs in service.planner.plan.assignments)
    again = _serve(service, pool, 12, id_offset=5_000)
    assert set(again) == set(golden)
    for rid in golden:
        assert (_same_decisions(golden[rid], again[rid])
                or again[rid].served_by == "baseline")
    assert service.stats.admitted == service.stats.served
    assert service.queue_depth == 0
    service.restore_device(victim)
    assert victim in service.planner.devices


# ---- retrace discipline --------------------------------------------------


def test_replacement_compiles_are_expected_not_retraces():
    """A placement change after steady state compiles NEW programs — but
    inside `expected_rebuild`, so the zero-unexpected-retrace invariant
    survives; returning to a previous placement is a cache hit."""
    from multihop_offload_tpu.obs import jaxhooks

    service, pool = _service(mesh=4, buckets=2, sizes="10,16")
    _serve(service, pool, 8, id_offset=7_000)          # warm initial plan
    victim = service.executor.devices_for(1)[-1]
    jaxhooks.install()
    jaxhooks.mark_steady()
    try:
        service.lose_device(victim)                     # forces a new plan
        _serve(service, pool, 8, id_offset=7_100)       # compiles, expected
        assert jaxhooks.unexpected_retraces() == 0
        programs_after_loss = len(service.executor._sharded)
        service.restore_device(victim)
        service.planner.observe([1, 1])
        service.executor.set_placement(service.planner.replan())
        _serve(service, pool, 8, id_offset=7_200)
        assert jaxhooks.unexpected_retraces() == 0
        # back on a seen placement: cache hit, no third program set
        assert len(service.executor._sharded) >= programs_after_loss
    finally:
        jaxhooks.clear_steady()


def test_hot_reload_survives_sharding():
    """Weights stay program ARGUMENTS under NamedSharding: swapping params
    must not touch any compiled executable."""
    service, pool = _service(mesh=4)
    _serve(service, pool, 4, id_offset=8_000)
    n_programs = len(service.executor._sharded)
    new_vars = jax.tree_util.tree_map(
        lambda x: np.asarray(x) * 1.01, service.executor.variables
    )
    service.executor.variables = new_vars
    got = _serve(service, pool, 4, id_offset=8_100)
    assert len(got) == 4
    assert len(service.executor._sharded) == n_programs


# ---- invalid plans -------------------------------------------------------


def test_set_placement_rejects_non_dividing_counts():
    from multihop_offload_tpu.serve.placement import PlacementPlan

    service, _ = _service(mesh=4)
    devs = service.planner.devices
    with pytest.raises(ValueError):
        service.executor.set_placement(PlacementPlan((tuple(devs[:3]),)))
    with pytest.raises(ValueError):
        service.executor.set_placement(PlacementPlan(()))


# ---- the 8x soak ---------------------------------------------------------


@pytest.mark.slow
def test_soak_8x_load_p99_within_budget():
    """8-device soak at 8x the single-device per-tick load (32 slots vs 4).

    The CPU-honest gate: the sharded tick's p99 must beat 1.5x the wall
    time a single device needs to serve the SAME 8x window (8 sequential
    ticks at its p50).  Virtual devices time-share one host core, so
    strict linear scaling is not assertable here — that claim is the
    on-chip record, which stays null until a real multi-chip leg runs
    (benchmarks/serving.json `sharded.linear_scaling`)."""
    single, pool = _service(mesh=0, slots=4)
    _serve(single, pool, 16, id_offset=9_000)           # warm
    sharded, _ = _service(mesh=8, slots=32)
    _serve(sharded, pool, 64, id_offset=9_100)          # warm
    walls_single, walls_sharded = [], []
    for i in range(12):
        pending = list(request_stream(pool, 4, seed=21 + i,
                                      id_offset=10_000 + 100 * i))
        for r in pending:
            assert single.submit(r)
        t0 = time.perf_counter()
        while single.queue_depth:
            single.tick()
        walls_single.append(time.perf_counter() - t0)
    for i in range(12):
        pending = list(request_stream(pool, 32, seed=21 + i,
                                      id_offset=20_000 + 100 * i))
        for r in pending:
            assert sharded.submit(r)
        t0 = time.perf_counter()
        while sharded.queue_depth:
            sharded.tick()
        walls_sharded.append(time.perf_counter() - t0)
    p50_single = float(np.percentile(walls_single, 50))
    p99_sharded = float(np.percentile(walls_sharded, 99))
    budget = 1.5 * 8 * p50_single
    assert sharded.executor.last_devices_used == 8
    assert p99_sharded <= budget, (
        f"sharded p99 {p99_sharded * 1e3:.1f} ms over budget "
        f"{budget * 1e3:.1f} ms (single p50 {p50_single * 1e3:.1f} ms)"
    )

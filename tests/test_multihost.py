"""multihost/ + loadgen/ unit tests — no `jax.distributed` needed.

The two-level planner, the open-loop driver and the federation merge are
pure host-side Python by design, so everything here runs in one process:
plans come from worked host-table examples, open-loop runs drive a fake
service on the virtual clock, and federation scrapes callable targets
instead of HTTP endpoints.  The real multi-process loop (2 CPU processes
under `jax.distributed`) is `mho-mesh --smoke` / scripts/smoke.sh step 13.
"""

from __future__ import annotations

import math
from types import SimpleNamespace

import pytest

from multihop_offload_tpu.loadgen import (
    TrafficModel,
    VirtualClock,
    arrival_times,
    max_sustained_rate,
    poisson,
    run_open_loop,
)
from multihop_offload_tpu.loadgen.driver import OpenLoopReport
from multihop_offload_tpu.multihost import (
    FleetFederation,
    TwoLevelPlan,
    TwoLevelPlanner,
    federated_slo_engine,
    local_placement,
    parse_prometheus_text,
    plan_two_level,
    validate_plan,
)
from multihop_offload_tpu.obs.registry import MetricRegistry

HOSTS = {"hostA": [0, 1, 2, 3], "hostB": [10, 11]}


# ---------------------------------------------------------------------------
# two-level placement
# ---------------------------------------------------------------------------


def test_plan_two_level_worked_example():
    """rates [4, 2, 1] over a 4-chip host and a 2-chip host, slots=4.

    Greedy in descending-rate order, minimizing resulting per-chip load:
      bucket0 (4): hostA 4/4=1.0 beats hostB 4/2=2.0      -> hostA
      bucket1 (2): hostB 2/2=1.0 beats hostA (4+2)/4=1.5  -> hostB
      bucket2 (1): hostA (4+1)/4=1.25 beats hostB 3/2=1.5 -> hostA
    """
    plan = plan_two_level([4.0, 2.0, 1.0], HOSTS, slots=4)
    assert plan.hosts == ("hostA", "hostB", "hostA")
    assert plan.buckets_on_host("hostA") == [0, 2]
    assert plan.buckets_on_host("hostB") == [1]
    # DCN invariant: every bucket's chips live on its own host
    for b in range(3):
        h = plan.host_of(b)
        assert set(plan.devices_for(b)) <= set(HOSTS[h])
        assert plan.devices_for(b)  # never empty
    d = plan.describe()
    assert d["1"]["host"] == "hostB"
    assert set(d["1"]["devices"]) <= {10, 11}


def test_plan_two_level_deterministic_and_tie_breaks_lex():
    a = plan_two_level([3.0, 3.0], HOSTS, slots=4)
    b = plan_two_level([3.0, 3.0], HOSTS, slots=4)
    assert a == b
    # equal rates, equal per-chip hosts: ties go to the lower bucket index
    # first and the lexicographically first host id
    even = plan_two_level([2.0, 2.0], {"a": [0, 1], "b": [2, 3]}, slots=2)
    assert even.hosts == ("a", "b")


def test_plan_two_level_rejects_bad_tables():
    with pytest.raises(ValueError, match="at least one host"):
        plan_two_level([1.0], {}, slots=4)
    with pytest.raises(ValueError, match="no devices"):
        plan_two_level([1.0], {"a": []}, slots=4)


def test_validate_plan_catches_dcn_spanning():
    bad = TwoLevelPlan(hosts=("hostA",), devices=((0, 10),))  # 10 is hostB's
    with pytest.raises(ValueError, match="spans the DCN boundary"):
        validate_plan(bad, HOSTS)
    with pytest.raises(ValueError, match="unknown host"):
        validate_plan(TwoLevelPlan(("ghost",), ((0,),)), HOSTS)
    with pytest.raises(ValueError, match="no devices"):
        validate_plan(TwoLevelPlan(("hostA",), ((),)), HOSTS)


def test_local_placement_projects_and_placeholders():
    plan = plan_two_level([4.0, 2.0, 1.0], HOSTS, slots=4)
    # hostB's process: bucket 1 translated onto its local device objects,
    # the foreign buckets get a 1-device placeholder
    local = ["devX", "devY"]
    pp = local_placement(plan, "hostB", local)
    assert len(pp.assignments) == 3
    assert set(pp.assignments[1]) <= set(local)
    assert len(pp.assignments[1]) == len(plan.devices_for(1))
    assert pp.assignments[0] == ("devX",)   # placeholder: fallback device
    assert pp.assignments[2] == ("devX",)
    # explicit fallback override
    pp2 = local_placement(plan, "hostB", local, fallback_device="devY")
    assert pp2.assignments[0] == ("devY",)
    # a plan wanting more chips than this process has is a loud error
    with pytest.raises(ValueError, match="has 1 locally"):
        local_placement(plan, "hostA", ["only_one"])
    with pytest.raises(ValueError, match="at least one local device"):
        local_placement(plan, "hostB", [])


def test_planner_hysteresis_does_not_thrash_on_jitter():
    planner = TwoLevelPlanner(2, HOSTS, slots=4, alpha=0.5, hysteresis=0.2)
    planner.observe([8.0, 4.0])
    first = planner.replan()
    base = planner.replans
    # +-10% jitter around the same rates: the candidate never beats the
    # current plan by the 20% hysteresis margin -> zero switches
    for jitter in (1.1, 0.9, 1.05, 0.95, 1.0):
        planner.observe([8.0 * jitter, 4.0 * jitter])
        assert planner.replan() is first
    assert planner.replans == base


def test_planner_host_removal_forces_replan_and_recovery_waits():
    planner = TwoLevelPlanner(2, HOSTS, slots=4)
    planner.observe([3.0, 2.0])
    plan = planner.replan()
    assert set(plan.hosts) == {"hostA", "hostB"}  # spans both
    before = planner.replans
    plan2 = planner.remove_host("hostB")
    assert planner.replans == before + 1
    assert set(plan2.hosts) == {"hostA"}
    validate_plan(plan2, planner.hosts)
    # recovery: capacity restored, but hysteresis decides adoption — the
    # returned plan must still be valid against the grown table
    plan3 = planner.add_host("hostB", HOSTS["hostB"])
    validate_plan(plan3, planner.hosts)
    assert "hostB" in planner.hosts


def test_planner_rejects_mismatch_and_empty_fleet():
    planner = TwoLevelPlanner(2, HOSTS, slots=4)
    with pytest.raises(ValueError, match="arrival counts"):
        planner.observe([1.0])
    planner.remove_host("hostB")
    with pytest.raises(ValueError, match="empty after host removal"):
        planner.remove_host("hostA")


# ---------------------------------------------------------------------------
# loadgen: arrivals
# ---------------------------------------------------------------------------


def test_arrivals_deterministic_and_sorted():
    m = TrafficModel(base_rate=50.0, diurnal_amplitude=0.3,
                     diurnal_period_s=10.0, mmpp_burst_factor=2.0,
                     mmpp_dwell_slow_s=2.0, mmpp_dwell_fast_s=1.0,
                     flashes=((4.0, 1.0, 3.0),))
    a = arrival_times(m, 10.0, seed=7)
    b = arrival_times(m, 10.0, seed=7)
    assert a == b
    assert a == sorted(a)
    assert all(0.0 <= t < 10.0 for t in a)
    assert arrival_times(m, 10.0, seed=8) != a


def test_arrivals_mean_rate_tracks_base_rate():
    # plain Poisson: count over a long window concentrates near rate*T
    n = len(arrival_times(poisson(200.0), 50.0, seed=3))
    assert abs(n - 200.0 * 50.0) < 5 * math.sqrt(200.0 * 50.0)


def test_traffic_model_validation_and_envelope():
    with pytest.raises(ValueError):
        TrafficModel(base_rate=0.0)
    with pytest.raises(ValueError):
        TrafficModel(base_rate=1.0, diurnal_amplitude=1.0)
    with pytest.raises(ValueError):
        TrafficModel(base_rate=1.0, mmpp_burst_factor=0.5)
    m = TrafficModel(base_rate=10.0, diurnal_amplitude=0.5,
                     mmpp_burst_factor=2.0, flashes=((0.0, 1.0, 3.0),))
    assert m.envelope_rate() == pytest.approx(10.0 * 1.5 * 2.0 * 3.0)
    assert m.at(20.0).base_rate == 20.0
    # flash window half-open: active at start, off at start+dur
    assert m.flash_factor(0.0) == 3.0 and m.flash_factor(1.0) == 1.0


# ---------------------------------------------------------------------------
# loadgen: open-loop driver + bisection (fake service, pure python)
# ---------------------------------------------------------------------------


class FakeService:
    """Bounded queue, fixed drain per tick — a deterministic M/D/1-ish
    stand-in exposing the submit/tick subset the driver uses."""

    def __init__(self, queue_cap: int = 8, per_tick: int = 4):
        self.queue_cap = queue_cap
        self.per_tick = per_tick
        self.q = []
        self.last_submit_outcome = None

    def submit(self, req, now=None):
        if len(self.q) >= self.queue_cap:
            self.last_submit_outcome = "backpressure"
            return False
        self.q.append(float(now))
        self.last_submit_outcome = "admitted"
        return True

    def tick(self, now=None):
        batch, self.q = self.q[: self.per_tick], self.q[self.per_tick:]
        return [SimpleNamespace(latency_s=float(now) - t, served_by="gnn")
                for t in batch]


def test_open_loop_underload_serves_everything():
    clock = VirtualClock()
    svc = FakeService(queue_cap=8, per_tick=4)  # 4/0.1s = 40 req/s capacity
    arr = arrival_times(poisson(10.0), 5.0, seed=1)
    rep = run_open_loop(svc, [object()] * len(arr), arr,
                        clock=clock, tick_interval_s=0.1)
    assert rep.offered == len(arr)
    assert rep.dropped == 0 and rep.drop_fraction == 0.0
    assert rep.served == rep.admitted == rep.offered  # conservation
    assert rep.drained
    assert rep.outcomes == {"admitted": rep.offered}
    assert rep.p99_s is not None and rep.p99_s <= 0.3
    assert rep.meets(p99_slo_s=0.5, max_drop_fraction=0.0)


def test_open_loop_overload_shows_drops_not_backoff():
    clock = VirtualClock()
    svc = FakeService(queue_cap=8, per_tick=4)  # 40 req/s capacity
    arr = arrival_times(poisson(200.0), 3.0, seed=2)
    rep = run_open_loop(svc, [object()] * len(arr), arr,
                        clock=clock, tick_interval_s=0.1)
    # open loop keeps offering at 200/s: ~80% must drop, visibly
    assert rep.drop_fraction > 0.5
    assert rep.outcomes.get("backpressure", 0) == rep.dropped
    assert rep.served == rep.admitted and rep.drained  # admitted all answer
    assert not rep.meets(p99_slo_s=10.0, max_drop_fraction=0.01)


def test_open_loop_rejects_bad_tick_and_clock_never_rewinds():
    with pytest.raises(ValueError, match="tick_interval_s"):
        run_open_loop(FakeService(), [], [], clock=VirtualClock(),
                      tick_interval_s=0.0)
    c = VirtualClock(5.0)
    with pytest.raises(ValueError, match="rewind"):
        c.seek(4.0)
    c.advance(1.0)
    assert c() == 6.0


def _fake_report(ok: bool) -> OpenLoopReport:
    return OpenLoopReport(
        offered=100, admitted=100 if ok else 60,
        dropped=0 if ok else 40, served=100 if ok else 60, degraded=0,
        duration_s=1.0, offered_rate=100.0, served_rate=100.0,
        drop_fraction=0.0 if ok else 0.4,
        p50_s=0.01, p95_s=0.02, p99_s=0.05 if ok else 9.0, max_s=0.1,
        drained=True, outcomes={},
    )


def test_bisection_pins_the_knee():
    knee = 37.0
    res = max_sustained_rate(
        lambda r: _fake_report(r <= knee),
        lo_rps=10.0, p99_slo_s=1.0, iters=8, max_doublings=4,
    )
    assert res.sustained_rps <= knee < res.collapse_rps
    assert res.collapse_rps - res.sustained_rps < 1.0  # 8 bisection steps
    assert all("offered_rps" in p for p in res.probes)  # whole search path
    assert any(p["ok"] for p in res.probes)
    assert any(not p["ok"] for p in res.probes)


def test_bisection_walks_down_when_lo_fails_and_reports_zero_floor():
    res = max_sustained_rate(
        lambda r: _fake_report(r <= 5.0),
        lo_rps=40.0, p99_slo_s=1.0, iters=6, max_doublings=4,
    )
    assert 0 < res.sustained_rps <= 5.0
    # a service that sustains nothing reports 0, not an exception
    res0 = max_sustained_rate(
        lambda r: _fake_report(False),
        lo_rps=8.0, p99_slo_s=1.0, iters=4, max_doublings=3,
    )
    assert res0.sustained_rps == 0.0
    with pytest.raises(ValueError):
        max_sustained_rate(lambda r: _fake_report(True), lo_rps=0.0,
                           p99_slo_s=1.0)


def test_bisection_never_failing_returns_proven_rate():
    res = max_sustained_rate(
        lambda r: _fake_report(True),
        lo_rps=10.0, p99_slo_s=1.0, iters=4, max_doublings=3,
    )
    assert res.sustained_rps == 80.0  # 10 * 2^3, the last PROVEN rate
    assert res.collapse_rps is None


# ---------------------------------------------------------------------------
# federation (callable targets — no sockets)
# ---------------------------------------------------------------------------


def _host_registry(served: int, lat: float) -> MetricRegistry:
    reg = MetricRegistry()
    reg.counter("mho_serve_served_total", "t").inc(served, served_by="gnn")
    reg.histogram("mho_serve_latency_seconds", "t",
                  buckets=[0.1, 1.0]).observe(lat)
    reg.gauge("mho_serve_queue_depth", "t").set(3.0)
    return reg


def test_prometheus_parse_round_trip():
    reg = _host_registry(served=7, lat=0.05)
    fams = parse_prometheus_text(reg.prometheus_text())
    c = fams["mho_serve_served_total"]
    assert c["kind"] == "counter"
    assert c["series"][(("served_by", "gnn"),)] == 7.0
    h = fams["mho_serve_latency_seconds"]
    assert h["kind"] == "histogram"
    assert h["boundaries"] == [0.1, 1.0]
    (key, s), = h["series"].items()
    assert s["count"] == 1 and s["buckets"] == [1, 0, 0]  # de-cumulated
    assert s["sum"] == pytest.approx(0.05)
    assert fams["mho_serve_queue_depth"]["series"][()] == 3.0


def test_federation_merges_hosts_and_deltas():
    regs = {"host0": _host_registry(7, 0.05), "host1": _host_registry(5, 2.0)}
    fed = FleetFederation(
        {h: r.prometheus_text for h, r in regs.items()})
    assert fed.scrape() == {"host0": True, "host1": True}
    served = fed.registry.counter("mho_serve_served_total")
    assert served.total() == 12.0                      # fleet-wide
    assert served.total(host="host0") == 7.0           # per-host breakdown
    assert served.total(host="host1") == 5.0
    # second scrape with only host0 moving: DELTA applied, not re-added
    regs["host0"].counter("mho_serve_served_total").inc(3, served_by="gnn")
    fed.scrape()
    assert served.total() == 15.0
    assert served.total(host="host1") == 5.0
    # histograms federate too: host1's 2.0s obs lands above the 1.0 edge
    hist = fed.registry.histogram("mho_serve_latency_seconds",
                                  buckets=[0.1, 1.0])
    good, total = hist.le_total(1.0)
    assert (good, total) == (1, 2)


def test_federation_counter_reset_treated_as_fresh():
    reg = _host_registry(10, 0.05)
    fed = FleetFederation({"host0": reg.prometheus_text})
    fed.scrape()
    served = fed.registry.counter("mho_serve_served_total")
    assert served.total() == 10.0
    # source restarted: its cumulative count went DOWN — the whole new
    # value is the delta (never negative, never double-subtracted)
    fresh = _host_registry(2, 0.05)
    fed.targets["host0"] = fresh.prometheus_text
    fed.scrape()
    assert served.total() == 12.0


def test_federation_dead_host_is_data():
    live = _host_registry(7, 0.05)

    def dead():
        raise OSError("connection refused")

    fed = FleetFederation({"host0": live.prometheus_text, "host1": dead})
    ok = fed.scrape()
    assert ok == {"host0": True, "host1": False}
    up = fed.registry.gauge("mho_mesh_host_up")
    assert up.value(host="host0") == 1.0
    assert up.value(host="host1") == 0.0
    fails = fed.registry.counter("mho_mesh_scrape_failures_total")
    assert fails.total(host="host1") == 1.0
    # the live host's series merged regardless
    assert fed.registry.counter("mho_serve_served_total").total() == 7.0


def test_federated_slo_engine_sees_fleet_series():
    regs = {"host0": _host_registry(7, 0.05), "host1": _host_registry(5, 0.2)}
    for r in regs.values():  # delivered-ratio denominators
        r.counter("mho_serve_submits_total", "t").inc(7, outcome="admitted")
    fed = FleetFederation({h: r.prometheus_text for h, r in regs.items()})
    fed.scrape()
    engine = federated_slo_engine(fed, short_s=1.0, long_s=2.0)
    assert engine.registry is fed.registry
    # two observations so every spec has a window; no alert may fire on
    # healthy fleet data
    assert engine.observe(0.0) == []
    assert engine.observe(1.0) == []

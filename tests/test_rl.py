"""rl/ subsystem: the Anakin closed loop, proven piece by piece.

- rollout reward math vs a hand-stepped tiny sim (same keys, same routes
  -> identical counters; rewards recomputed from the exposed deltas)
- on-device buffer carry round-trip (structure-stable, correct baseline,
  ring eviction)
- zero unexpected retraces across repeated compiled train steps
- delivered-ratio improvement over random init on a fixed seed (the
  acceptance gate, exercised through the CLI's own run_train)
- sharded-vs-single-device update parity on the 8-virtual-device mesh
- checkpoint interop: source="rl" lineage, verified restore, and the
  serve/ hot-reload signature pin
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multihop_offload_tpu.cli.rl import build_fleet, run_train
from multihop_offload_tpu.config import Config
from multihop_offload_tpu.layouts import zeros_support
from multihop_offload_tpu.models import make_model
from multihop_offload_tpu.obs import jaxhooks
from multihop_offload_tpu.rl import (
    RLBuffer,
    RLTrainer,
    buffer_baseline,
    buffer_init,
    buffer_push,
    reward_from_deltas,
    rollout,
)
from multihop_offload_tpu.sim.state import init_state
from multihop_offload_tpu.sim.step import sim_slot_step

TINY = Config(sim_nodes=8, sim_jobs=3, sim_cap=64,
              rl_fleet=2, rl_rounds=2, rl_slots=40, rl_steps=3)


@pytest.fixture(scope="module")
def tiny_fleet():
    return build_fleet(TINY)


@pytest.fixture(scope="module")
def tiny_model(tiny_fleet):
    _, _, _, _, pad = tiny_fleet
    model = make_model(TINY)
    variables = model.init(
        jax.random.PRNGKey(TINY.seed),
        jnp.zeros((pad.e, 4), TINY.jnp_dtype),
        zeros_support(pad, TINY.jnp_dtype, TINY.layout_policy),
    )
    return model, variables


def _lane(tree, i):
    return jax.tree_util.tree_map(lambda x: x[i], tree)


# ---------------------------------------------------------------------------
# rollout reward math vs a hand-stepped sim
# ---------------------------------------------------------------------------


def test_rollout_matches_hand_stepped_sim(tiny_fleet, tiny_model):
    """The rollout's inner dynamics ARE `sim_slot_step`: replaying its own
    sampled routes through a host-driven slot loop with the identical key
    schedule must land on the same terminal counters, and the rewards must
    equal the reward spec applied to the exposed per-round deltas."""
    insts, jobss, paramss, spec, _ = tiny_fleet
    model, variables = tiny_model
    inst, jobs, sp = _lane(insts, 0), _lane(jobss, 0), _lane(paramss, 0)
    st0 = init_state(spec, jnp.float32)
    rates0 = jnp.zeros((spec.num_jobs,), jnp.float32)
    key = jax.random.PRNGKey(42)
    rounds, slots = TINY.rl_rounds, TINY.rl_slots

    loss, out = jax.jit(
        lambda v, k: rollout(model, v, inst, jobs, spec, sp, st0, rates0,
                             k, 0.0, rounds, slots,
                             TINY.rl_temp, TINY.rl_delay_weight)
    )(variables, key)

    # hand-step: same key tree (round keys -> (k_dec, k_slots) -> slot
    # keys), same per-round routes (read back off the rollout's own tape)
    step1 = jax.jit(
        lambda routes, state, k: sim_slot_step(
            inst, spec, sp, routes, jobs, state, k
        )[0]
    )
    st = st0
    hand_rewards = []
    for r in range(rounds):
        kr = jax.random.split(key, rounds)[r]
        _, k_slots = jax.random.split(kr)
        routes_r = _lane(out.routes, r)
        before = st
        for kk in jax.random.split(k_slots, slots):
            st = step1(routes_r, st, kk)
        gen_d = int(np.sum(np.asarray(st.generated - before.generated)))
        del_d = int(np.sum(np.asarray(st.delivered - before.delivered)))
        drop_d = int(np.sum(np.asarray(st.dropped - before.dropped)))
        delay_d = float(np.sum(np.asarray(st.delay_sum - before.delay_sum)))
        assert gen_d == int(out.deltas.generated[r])
        assert del_d == int(out.deltas.delivered[r])
        assert drop_d == int(out.deltas.dropped[r])
        np.testing.assert_allclose(delay_d, float(out.deltas.delay_sum[r]),
                                   rtol=1e-6)
        hand_rewards.append(float(reward_from_deltas(
            jnp.asarray(gen_d), jnp.asarray(del_d),
            jnp.asarray(delay_d, jnp.float32), sp.dt,
            TINY.rl_delay_weight,
        )))

    # terminal counters: identical packets, bit for bit
    np.testing.assert_array_equal(np.asarray(st.generated),
                                  np.asarray(out.state.generated))
    np.testing.assert_array_equal(np.asarray(st.delivered),
                                  np.asarray(out.state.delivered))
    np.testing.assert_array_equal(np.asarray(st.dropped),
                                  np.asarray(out.state.dropped))
    np.testing.assert_allclose(np.asarray(out.rewards),
                               np.asarray(hand_rewards), rtol=1e-6)
    # surrogate loss composes the exposed pieces
    np.testing.assert_allclose(
        float(loss),
        float(-np.sum(np.asarray(out.logps) * np.asarray(out.rewards))),
        rtol=1e-5,
    )


# ---------------------------------------------------------------------------
# buffer carry
# ---------------------------------------------------------------------------


def test_buffer_round_trip_structure_and_baseline():
    buf = buffer_init(4)
    td0 = jax.tree_util.tree_structure(buf)
    assert float(buffer_baseline(buf)) == 0.0  # empty -> zero baseline

    buf = buffer_push(buf, jnp.asarray([1.0, 2.0], jnp.float32))
    assert jax.tree_util.tree_structure(buf) == td0
    assert buf.rewards.dtype == jnp.float32 and buf.count.dtype == jnp.int32
    assert float(buffer_baseline(buf)) == pytest.approx(1.5)

    # wraparound evicts oldest-first: [1,2,3,4,5,6] in cap 4 -> [3,4,5,6]
    buf = buffer_push(buf, jnp.asarray([3.0, 4.0, 5.0, 6.0], jnp.float32))
    assert int(buf.count) == 4
    assert float(buffer_baseline(buf)) == pytest.approx((3 + 4 + 5 + 6) / 4)

    # jittable as a carry: structure in == structure out under jit
    jit_push = jax.jit(buffer_push)
    buf2 = jit_push(buf, jnp.asarray([7.0], jnp.float32))
    assert jax.tree_util.tree_structure(buf2) == td0
    assert isinstance(buf2, RLBuffer)
    assert float(buffer_baseline(buf2)) == pytest.approx((4 + 5 + 6 + 7) / 4)


# ---------------------------------------------------------------------------
# one steady compiled program
# ---------------------------------------------------------------------------


def test_zero_unexpected_retraces_across_steps(tiny_fleet, tiny_model):
    insts, jobss, paramss, spec, _ = tiny_fleet
    model, variables = tiny_model
    tr = RLTrainer(TINY, model, variables, spec)
    jaxhooks.install()
    key = jax.random.PRNGKey(7)

    key, k = jax.random.split(key)
    tr.train_step(insts, jobss, paramss, jax.random.split(k, TINY.rl_fleet))
    tr.mark_steady()
    before = jaxhooks.unexpected_retraces()
    for _ in range(3):
        key, k = jax.random.split(key)
        out = tr.train_step(insts, jobss, paramss,
                            jax.random.split(k, TINY.rl_fleet))
    jaxhooks.clear_steady()
    assert jaxhooks.unexpected_retraces() == before, (
        "repeated train steps retraced — the step is not one steady program"
    )
    assert int(out.skipped) == 0
    assert np.isfinite(float(out.loss))


# ---------------------------------------------------------------------------
# the acceptance gate, through the CLI's own driver
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_smoke_improves_over_random_init():
    cfg = dataclasses.replace(
        Config(), sim_nodes=8, sim_jobs=3, sim_cap=64,
        rl_fleet=4, rl_rounds=2, rl_slots=100, rl_steps=20,
    )
    record = run_train(cfg, smoke=True)  # asserts its own gates
    assert record["improved"]
    assert record["unexpected_retraces"] == 0
    assert record["conservation"]["exact"]
    assert record["rho_target"] >= 0.7


# ---------------------------------------------------------------------------
# sharded-vs-single-device parity
# ---------------------------------------------------------------------------


def test_sharded_update_parity_on_virtual_mesh(tiny_model):
    """Same fleet batch, same keys: the shard_map(data=8) step and the
    single-device step must produce the same updated params (the pmean of
    per-shard grad means equals the global mean up to reduction order)."""
    from multihop_offload_tpu.parallel.mesh import make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    cfg = dataclasses.replace(TINY, rl_fleet=8, rl_slots=20)
    insts, jobss, paramss, spec, _ = build_fleet(cfg)
    model, variables = tiny_model
    keys = jax.random.split(jax.random.PRNGKey(5), 8)

    tr_single = RLTrainer(cfg, model, variables, spec, devmetrics=False)
    tr_shard = RLTrainer(cfg, model, variables, spec,
                         mesh=make_mesh(8, 1), devmetrics=False)
    out_s = tr_single.train_step(insts, jobss, paramss, keys)
    out_p = tr_shard.train_step(insts, jobss, paramss, keys)

    # identical rollouts (per-lane outputs don't cross the reduction)...
    np.testing.assert_array_equal(np.asarray(out_s.rewards),
                                  np.asarray(out_p.rewards))
    # ...and matching updates up to fp reduction order in the grad mean
    flat_s = jax.tree_util.tree_leaves(tr_single.params)
    flat_p = jax.tree_util.tree_leaves(tr_shard.params)
    for a, b in zip(flat_s, flat_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# checkpoint interop: rl lineage -> verified restore -> serve signature pin
# ---------------------------------------------------------------------------


def test_checkpoint_interop_rl_lineage_and_signature(tmp_path, tiny_fleet,
                                                     tiny_model):
    from multihop_offload_tpu.serve.executor import param_signature
    from multihop_offload_tpu.train import checkpoints as ckpt_lib

    insts, jobss, paramss, spec, pad = tiny_fleet
    model, variables = tiny_model
    tr = RLTrainer(TINY, model, variables, spec, devmetrics=False)
    tr.train_step(insts, jobss, paramss,
                  jax.random.split(jax.random.PRNGKey(3), TINY.rl_fleet))
    directory = str(tmp_path / "orbax_rl")
    step = tr.save(directory)

    # lineage names the rl source (the flywheel's provenance contract)
    lin = ckpt_lib.load_lineage(directory, step)
    assert lin is not None and lin["source"] == "rl"
    assert lin["rl_step"] == step

    # verified restore (integrity sidecar honored), bit-compatible payload
    restored, got = ckpt_lib.restore_verified(directory)
    assert got == step and restored is not None
    saved_params = jax.tree_util.tree_map(np.asarray, tr.params)
    assert (ckpt_lib.tree_checksum(restored["params"])
            == ckpt_lib.tree_checksum(saved_params))

    # the serve/ hot-reload gate: an RL checkpoint must be swappable for a
    # fresh-init tree of the same config without retrace/reshape
    fresh = make_model(TINY).init(
        jax.random.PRNGKey(TINY.seed + 9),
        jnp.zeros((pad.e, 4), TINY.jnp_dtype),
        zeros_support(pad, TINY.jnp_dtype, TINY.layout_policy),
    )["params"]
    assert param_signature(restored["params"]) == param_signature(fresh)
    # and loop/ refit resumes the SAME optimizer moments, not a cold Adam
    assert "opt_state" in restored

"""Test harness: run JAX on a virtual 8-device CPU mesh with float64 enabled.

Multi-chip sharding tests run here without TPU hardware
(`--xla_force_host_platform_device_count=8`); float64 lets oracle comparisons
be exact against NumPy references.

Note: this machine's interpreter pre-registers a remote TPU backend via
`sitecustomize` (jax is already imported when conftest runs), so selecting
CPU must go through `jax.config.update("jax_platforms", ...)` — the
JAX_PLATFORMS env var is captured before we get control.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/data/aco_data_ba_10"
REFERENCE_CKPT = "/root/reference/model/model_ChebConv_BAT800_a5_c5_ACO_agent"


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(scope="session")
def small_cases():
    """A handful of real reference cases (smoke-test dataset), if present."""
    import multihop_offload_tpu.graphs.matio as matio

    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip("reference dataset unavailable")
    names = matio.list_dataset(REFERENCE_DATA)
    picks = [n for n in names if "_n20_" in n][:2] + [n for n in names if "_n40_" in n][:1]
    return [matio.load_case_mat(os.path.join(REFERENCE_DATA, n)) for n in picks]

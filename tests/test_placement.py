"""serve/placement: greedy per-bucket device assignment from arrival rates.

The properties that make placement safe to run inside the serving loop:
determinism (same rates -> same plan, bit-stable across processes),
divisibility (every bucket's device count divides the slot count, the
sharded executor's compile-time invariant), hysteresis (small rate jitter
never thrashes placements — each switch costs a compile), and forced
re-planning on device loss (an invalid plan can never be held)."""

import pytest

from multihop_offload_tpu.serve.placement import (
    PlacementPlan,
    PlacementPlanner,
    allowed_counts,
    peak_device_load,
    plan_assignments,
)


def test_allowed_counts_are_divisors():
    assert allowed_counts(8, 6) == [1, 2, 4]
    assert allowed_counts(8, 8) == [1, 2, 4, 8]
    assert allowed_counts(4, 2) == [1, 2]
    assert allowed_counts(5, 8) == [1, 5]


def test_greedy_plan_is_deterministic_for_fixed_rates():
    """The worked example the module docs promise: a hot bucket (10) and a
    cold one (1) over six chips with eight slots — hot gets four chips,
    cold absorbs the remaining two."""
    plan = plan_assignments([10.0, 1.0], devices=list(range(6)), slots=8)
    assert plan == ((0, 1, 2, 3), (4, 5))
    # determinism: recomputing from the same rates is bit-identical
    assert plan == plan_assignments([10.0, 1.0], list(range(6)), 8)


def test_every_bucket_count_divides_slots():
    for rates in ([1, 1, 1], [9, 3, 1], [0, 0, 5]):
        plan = plan_assignments(rates, devices=list(range(8)), slots=8)
        for devs in plan:
            assert devs and 8 % len(devs) == 0


def test_all_cold_spreads_evenly():
    """Zero observed rates (startup) must not pile every chip on bucket 0:
    the rate floor makes ties spread."""
    plan = plan_assignments([0.0, 0.0], devices=list(range(4)), slots=4)
    assert plan == ((0, 1), (2, 3))


def test_fleet_smaller_than_ladder_shares_round_robin():
    plan = plan_assignments([1.0, 2.0, 3.0], devices=[0, 1], slots=4)
    assert plan == ((0,), (1,), (0,))
    assert PlacementPlan(plan).buckets_on(0) == [0, 2]


def test_peak_device_load():
    plan = ((0, 1, 2, 3), (4, 5))
    assert peak_device_load(plan, [10.0, 1.0]) == pytest.approx(2.5)
    assert peak_device_load(plan, [4.0, 8.0]) == pytest.approx(4.0)


def test_planner_stable_under_small_jitter():
    """±5% arrival jitter around a settled rate vector must never switch
    the plan: each switch costs a compile, and jitter is not a signal."""
    p = PlacementPlanner(2, devices=list(range(6)), slots=8, alpha=1.0)
    p.observe([100, 10])
    settled = p.replan()
    assert settled.assignments == ((0, 1, 2, 3), (4, 5))
    switches = p.replans
    for a, b in ((105, 10), (95, 11), (102, 9), (98, 10)):
        p.observe([a, b])
        assert p.replan().assignments == settled.assignments
    assert p.replans == switches, "jitter thrashed the placement"


def test_planner_switches_when_clearly_better():
    """A genuine load inversion (hot and cold swap) must eventually win
    through the hysteresis gate."""
    p = PlacementPlanner(2, devices=list(range(6)), slots=8,
                         alpha=1.0, hysteresis=0.2)
    p.observe([100, 10])
    assert p.replan().assignments == ((0, 1, 2, 3), (4, 5))
    p.observe([10, 100])
    flipped = p.replan()
    assert flipped.assignments == ((0, 1), (2, 3, 4, 5))


def test_device_removal_forces_replan():
    """Losing a chip invalidates any plan referencing it: hysteresis cannot
    hold an invalid plan, and the survivors cover every bucket."""
    p = PlacementPlanner(2, devices=list(range(6)), slots=8, alpha=1.0)
    p.observe([100, 10])
    before = p.replan()
    assert before.uses(5)
    after = p.remove_device(5)
    assert not after.uses(5)
    assert all(devs for devs in after.assignments)
    assert after.assignments == ((0, 1, 2, 3), (4,))
    # recovery: the chip returns to the fleet and the old plan may win back
    restored = p.add_device(5)
    assert 5 in p.devices
    assert all(8 % len(devs) == 0 for devs in restored.assignments)


def test_remove_last_device_raises():
    p = PlacementPlanner(1, devices=[0], slots=4)
    with pytest.raises(ValueError):
        p.remove_device(0)


def test_observe_rejects_wrong_arity():
    p = PlacementPlanner(2, devices=[0, 1], slots=4)
    with pytest.raises(ValueError):
        p.observe([1, 2, 3])


def test_plan_describe_uses_device_ids():
    class Dev:
        def __init__(self, i):
            self.id = i

    plan = PlacementPlan(((Dev(0), Dev(1)), (Dev(2),)))
    assert plan.describe() == {"0": [0, 1], "1": [2]}

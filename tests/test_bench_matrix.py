"""Gate-flip logic of `mho-bench --matrix` + the committed record schema.

The flip rules are load-bearing: they own the shipped `--precision` /
`--layout` defaults (`multihop_offload_tpu/_defaults.json`, read by
`config.shipped_defaults()`).  Fabricated records pin the contract: every
gate passing flips the axis to auto; any null or failed gate leaves it
conservative; a record missing gate keys flips NOTHING and emits a typed
warning event.
"""

import json
import os

from multihop_offload_tpu.cli.bench import (
    GATE_KEYS,
    LAYOUT_GATES,
    PRECISION_GATES,
    apply_defaults,
    flip_defaults,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RECORD = os.path.join(_REPO, "benchmarks", "bench_matrix.json")

_CONSERVATIVE = {"precision": "fp32", "layout": "dense"}


def _all_pass():
    return {k: {"criterion": "c", "measured": 1.0, "pass": True}
            for k in GATE_KEYS}


def test_gate_key_groups_are_consistent():
    assert set(PRECISION_GATES) <= set(GATE_KEYS)
    assert set(LAYOUT_GATES) <= set(GATE_KEYS)
    assert not set(PRECISION_GATES) & set(LAYOUT_GATES)


def test_flip_on_full_pass():
    defaults, events = flip_defaults(_all_pass())
    assert defaults == {"precision": "auto", "layout": "auto"}
    assert events == []


def test_null_or_failed_gate_blocks_its_axis_only():
    g = _all_pass()
    g["precision_perf"]["pass"] = None  # awaiting chip run
    defaults, events = flip_defaults(g)
    assert defaults == {"precision": "fp32", "layout": "auto"}
    assert events == []

    g = _all_pass()
    g["layout_perf_tpu"]["pass"] = False
    defaults, _ = flip_defaults(g)
    assert defaults == {"precision": "auto", "layout": "dense"}

    # kernel-impl / serving gates close backlog but never drive the flip
    g = _all_pass()
    for k in ("fp_rung_384", "fp_rung_512", "chebconv_perf",
              "coo_apsp_perf", "serve_scaling"):
        g[k]["pass"] = None
    defaults, events = flip_defaults(g)
    assert defaults == {"precision": "auto", "layout": "auto"}
    assert events == []


def test_partial_record_no_flip_and_typed_warning():
    g = _all_pass()
    del g["coo_apsp_perf"]
    g["layout_ai"] = "not-a-gate-dict"
    defaults, events = flip_defaults(g)
    assert defaults == _CONSERVATIVE  # nothing flips on a partial record
    assert len(events) == 1
    assert events[0]["event"] == "warning"
    assert events[0]["code"] == "partial_gate_record"
    assert set(events[0]["missing"]) == {"coo_apsp_perf", "layout_ai"}

    defaults, events = flip_defaults(None)
    assert defaults == _CONSERVATIVE
    assert events[0]["code"] == "invalid_gate_record"

    # truthy-but-not-True pass values must not flip (None/False/1.0 ...)
    g = _all_pass()
    g["precision_parity"]["pass"] = 1.0
    defaults, _ = flip_defaults(g)
    assert defaults["precision"] == "fp32"


def test_apply_defaults_round_trip(tmp_path):
    p = tmp_path / "_defaults.json"
    p.write_text(json.dumps(
        {"precision": "fp32", "layout": "dense", "_comment": "keep me"}))
    assert apply_defaults({"precision": "auto", "layout": "auto"}, str(p))
    rec = json.loads(p.read_text())
    assert rec["precision"] == "auto" and rec["layout"] == "auto"
    assert rec["_comment"] == "keep me"
    # idempotent: same defaults -> no rewrite
    assert not apply_defaults({"precision": "auto", "layout": "auto"}, str(p))
    # a regressed gate set downgrades (the flip is not a ratchet)
    assert apply_defaults(dict(_CONSERVATIVE), str(p))
    assert json.loads(p.read_text())["precision"] == "fp32"
    # missing file: written fresh
    q = tmp_path / "fresh.json"
    assert apply_defaults(dict(_CONSERVATIVE), str(q))
    assert json.loads(q.read_text())["layout"] == "dense"


def test_committed_record_schema_round_trip():
    """The committed campaign record must carry the full gate schema, and
    re-running the pure flip logic on its gates must reproduce its own
    committed defaults (no hidden state in the runner)."""
    with open(_RECORD) as f:
        rec = json.load(f)

    for key in ("description", "platform", "legs", "gates",
                "all_gates_pass", "defaults", "defaults_applied",
                "unexpected_retraces", "events", "roofline", "workload"):
        assert key in rec, f"record missing {key}"
    assert set(GATE_KEYS) == set(rec["gates"])
    for k, g in rec["gates"].items():
        assert "criterion" in g and "measured" in g and "pass" in g, k

    assert rec["unexpected_retraces"] == 0
    for leg in rec["legs"].values():
        assert leg["steps_per_sec"] > 0
        assert set(leg["paths"]) == {"apsp", "fp", "cheb", "coo_apsp"}

    if rec["platform"] != "tpu":
        # null-preserving convention: chip gates stay null off-TPU (or are
        # preserved verbatim from a committed TPU record)
        for k, g in rec["gates"].items():
            if "source" in g:
                continue
            assert g["pass"] is None or "preserved" in g.get("note", ""), k
        assert rec["defaults"] == _CONSERVATIVE
        assert rec["defaults_applied"] is False

    defaults, events = flip_defaults(rec["gates"])
    assert defaults == rec["defaults"]
    assert not events


def test_shipped_defaults_match_committed_record():
    """config.shipped_defaults() (what drivers actually boot with) must
    agree with the campaign record's verdict — the record owns the file."""
    from multihop_offload_tpu.config import shipped_defaults

    with open(_RECORD) as f:
        rec = json.load(f)
    shipped = shipped_defaults()
    assert shipped["precision"] in ("fp32", "bf16", "auto")
    assert shipped["layout"] in ("dense", "sparse", "auto")
    if rec.get("defaults_applied"):
        assert shipped == rec["defaults"]
    else:
        assert shipped == _CONSERVATIVE

"""obs/ health layer: SLO burn rates, request tracing, drift, flight recorder."""

import json
import os

import pytest

from multihop_offload_tpu.obs import events as obs_events
from multihop_offload_tpu.obs.drift import (
    DriftMonitor,
    EWMADetector,
    PageHinkley,
    outcome_features,
)
from multihop_offload_tpu.obs.events import RunLog, segment_paths
from multihop_offload_tpu.obs.flightrec import FlightRecorder
from multihop_offload_tpu.obs.registry import (
    LATENCY_BUCKETS,
    MetricRegistry,
    log_buckets,
)
from multihop_offload_tpu.obs.slo import (
    SLOEngine,
    SLOSpec,
    default_serving_slos,
)
from multihop_offload_tpu.obs.trace import hop, reconstruct, render_trace


# ---- registry additions -----------------------------------------------------

def test_log_buckets_preset_shape():
    lb = log_buckets(0.001, 60.0, per_decade=4)
    assert lb[0] == 0.001 and lb[-1] == 60.0
    assert all(a < b for a, b in zip(lb, lb[1:]))
    # constant relative resolution: every step within ~10^(1/4), modulo the
    # 3-sig-fig rounding and the final snap to `hi`
    for a, b in zip(lb, lb[1:]):
        assert 1.0 < b / a < 2.2
    assert LATENCY_BUCKETS == lb
    with pytest.raises(ValueError):
        log_buckets(0.0, 1.0)
    with pytest.raises(ValueError):
        log_buckets(2.0, 1.0)


def test_histogram_le_total_snaps_down_and_quantile():
    reg = MetricRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    assert h.le_total(0.1) == (1, 3)
    assert h.le_total(0.5) == (1, 3)     # snaps DOWN to 0.1 (conservative)
    assert h.le_total(1.0) == (2, 3)
    assert h.le_total(0.05) == (0, 3)    # below the first boundary
    assert h.quantile(0.5) == pytest.approx(0.55)  # interpolated in (0.1, 1]
    assert h.quantile(0.99) == pytest.approx(2.0)  # +Inf tail -> observed max
    assert reg.histogram("empty_seconds").quantile(0.5) is None


def test_counter_total_subset_label_filter():
    reg = MetricRegistry()
    c = reg.counter("sub_total")
    c.inc(3, outcome="admitted", bucket="0")
    c.inc(4, outcome="admitted", bucket="1")
    c.inc(2, outcome="backpressure")
    assert c.total() == 9
    assert c.total(outcome="admitted") == 7
    assert c.total(outcome="backpressure") == 2
    assert c.total(outcome="nope") == 0


# ---- SLO burn-rate engine ---------------------------------------------------

def test_window_error_math_on_synthetic_series():
    samples = [(0.0, 0.0, 0.0), (10.0, 90.0, 100.0), (20.0, 90.0, 200.0)]
    # window 10 at t=20: baseline is the t=10 sample -> 100 obs, 0 good
    assert SLOEngine._window_error(samples, 20.0, 10.0) == pytest.approx(1.0)
    # window 20 at t=20: baseline t=0 -> 200 obs, 90 good
    assert SLOEngine._window_error(samples, 20.0, 20.0) == pytest.approx(0.55)
    # fewer than two samples -> no evidence, no error
    assert SLOEngine._window_error(samples[:1], 20.0, 10.0) == 0.0
    # no traffic in the window -> 0, not NaN
    flat = [(0.0, 5.0, 5.0), (10.0, 5.0, 5.0)]
    assert SLOEngine._window_error(flat, 10.0, 10.0) == 0.0


def test_slo_engine_fires_on_sustained_burn_and_resolves():
    reg = MetricRegistry()
    spec = SLOSpec("p99", "histogram_le", "lat_seconds",
                   objective=0.9, le=0.1)
    engine = SLOEngine([spec], registry=reg, short_s=10.0, long_s=30.0)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))

    transitions = []
    breaches = []
    engine.on_breach(lambda s, info: breaches.append((s.name, info["state"])))
    t = 0.0
    for _ in range(6):                      # calm: all under the bound
        for _ in range(10):
            h.observe(0.05)
        transitions += engine.observe(t)
        t += 1.0
    assert transitions == [] and breaches == []
    for _ in range(4):                      # burst: all over the bound
        for _ in range(10):
            h.observe(0.5)
        transitions += engine.observe(t)
        t += 1.0
    firing = [x for x in transitions if x["state"] == "firing"]
    assert len(firing) == 1 and firing[0]["name"] == "p99"
    assert firing[0]["burn_short"] > 1.0 and firing[0]["burn_long"] > 1.0
    assert breaches == [("p99", "firing")]
    assert reg.gauge("mho_alert_active").value(slo="p99") == 1

    for _ in range(15):                     # recovery: good traffic only
        for _ in range(10):
            h.observe(0.05)
        transitions += engine.observe(t)
        t += 1.0
    resolved = [x for x in transitions if x["state"] == "resolved"]
    assert len(resolved) == 1
    assert reg.gauge("mho_alert_active").value(slo="p99") == 0
    assert breaches == [("p99", "firing")]  # resolve is not a breach
    assert engine.state()["p99"]["state"] == "ok"


def test_slo_engine_short_spike_does_not_page():
    # one bad tick trips the short window but not the long one -> no alert
    reg = MetricRegistry()
    spec = SLOSpec("p99", "histogram_le", "lat_seconds",
                   objective=0.99, le=0.1)
    engine = SLOEngine([spec], registry=reg, short_s=4.0, long_s=100.0)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    transitions = []
    for t in range(51):
        for _ in range(9):
            h.observe(0.05)
        h.observe(0.5 if t == 50 else 0.05)
        transitions += engine.observe(float(t))
    assert transitions == []
    short, long_ = engine.burn_rates("p99", 50.0)
    assert short > 1.0 and long_ <= 1.0


def test_slo_counter_zero_fires_on_any_increment():
    reg = MetricRegistry()
    spec = SLOSpec("no_retrace", "counter_zero", "retr_total", objective=1.0)
    engine = SLOEngine([spec], registry=reg, short_s=2.0, long_s=4.0)
    transitions = []
    for t in range(5):
        transitions += engine.observe(float(t))
    assert transitions == []
    reg.counter("retr_total").inc()
    transitions += engine.observe(5.0)
    assert [x["state"] for x in transitions] == ["firing"]
    for t in range(6, 12):                  # counter quiet again -> resolve
        transitions += engine.observe(float(t))
    assert [x["state"] for x in transitions] == ["firing", "resolved"]


def test_slo_gauge_max_fires_above_bound():
    reg = MetricRegistry()
    spec = SLOSpec("queue", "gauge_max", "depth", objective=0.5, bound=5.0)
    engine = SLOEngine([spec], registry=reg, short_s=2.0, long_s=4.0)
    reg.gauge("depth").set(10.0)
    transitions = []
    for t in range(3):
        transitions += engine.observe(float(t))
    assert any(x["state"] == "firing" for x in transitions)


def test_default_serving_slos_cover_the_issue_set():
    specs = {s.name: s for s in default_serving_slos()}
    assert set(specs) == {
        "serve_p99", "serve_delivered", "serve_drops", "serve_queue",
        "zero_unexpected_retraces", "serve_nonfinite",
    }
    assert specs["serve_nonfinite"].kind == "counter_zero"
    assert specs["serve_nonfinite"].metric == "mho_dev_serve_nonfinite_total"
    assert specs["serve_p99"].kind == "histogram_le"
    assert specs["serve_p99"].le == 0.25
    assert specs["zero_unexpected_retraces"].objective == 1.0
    with pytest.raises(ValueError):
        SLOSpec("bad", "nope", "m", objective=0.9)
    with pytest.raises(ValueError):
        SLOSpec("bad", "ratio", "m", objective=0.0)


# ---- request-scoped tracing -------------------------------------------------

def test_trace_hop_is_noop_without_run_log():
    assert obs_events.get_run_log() is None
    hop("submit", [1, 2], bucket=0)  # must not raise


def test_trace_reconstruct_across_rotated_segments(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest={"event": "manifest", "ts": 0},
                 max_bytes=512)
    obs_events.set_run_log(log)
    try:
        hop("submit", [7], bucket=0, queue_depth=1)
        hop("pack", [5, 7, 9], bucket=0, degraded=False)
        for i in range(30):   # filler traffic forces several rotations
            hop("decision", [1000 + i], bucket=0,
                latency_s=[0.001], served_by="gnn")
        hop("decision", [5, 7, 9], bucket=0,
            latency_s=[0.01, 0.02, 0.03], served_by="gnn")
        hop("promotion", [7, 9], step=2, candidate_step=1)
    finally:
        obs_events.set_run_log(None)
        log.close()

    assert len(segment_paths(path)) >= 2
    hops = reconstruct(path, 7)
    assert [h["hop"] for h in hops] == [
        "submit", "pack", "decision", "promotion",
    ]
    # aligned list columns flatten to this request's own element
    assert hops[2]["latency_s"] == pytest.approx(0.02)
    assert hops[2]["batch"] == 3
    # scalar fields pass through untouched
    assert hops[3]["step"] == 2
    assert reconstruct(path, 4242) == []

    text = render_trace(path, 7)
    assert "4 hops" in text and "promotion" in text
    assert "no trace events" in render_trace(path, 4242)

    from multihop_offload_tpu.cli.obs import main as obs_main

    assert obs_main([path, "--trace", "7"]) == 0


# ---- drift detectors --------------------------------------------------------

def test_page_hinkley_trips_on_shift_not_on_stationary():
    det = PageHinkley(delta=0.2, threshold=12.0, min_samples=16)
    stationary = [0.4, 0.6] * 60
    assert not any(det.update(x) for x in stationary)
    assert not det.tripped

    det2 = PageHinkley(delta=0.2, threshold=12.0, min_samples=16)
    for x in [0.4, 0.6] * 8:                # warmup: mu=0.5, small sigma
        assert not det2.update(x)
    trips = [det2.update(3.0) for _ in range(10)]
    assert any(trips)
    assert trips.count(True) == 1           # True exactly once (latched)
    assert det2.tripped
    with pytest.raises(ValueError):
        PageHinkley(min_samples=1)


def test_ewma_detector_trips_after_patience_run():
    det = EWMADetector(alpha=0.01, k=4.0, min_samples=8, patience=3)
    for x in [0.4, 0.6] * 30:
        assert not det.update(x)
    det2 = EWMADetector(alpha=0.01, k=4.0, min_samples=8, patience=3)
    for x in [0.4, 0.6] * 4:
        det2.update(x)
    trips = [det2.update(50.0) for _ in range(5)]
    assert any(trips) and trips.count(True) == 1
    with pytest.raises(ValueError):
        EWMADetector(alpha=0.0)


def test_outcome_features_from_event_dict():
    f = outcome_features({
        "tau": 3.5, "is_local": [True, False, False, False],
        "job_rate": [1.0, 2.0, 0.5],
    })
    assert f["tau"] == 3.5
    assert f["offload_frac"] == pytest.approx(0.75)
    assert f["arrival_rate"] == pytest.approx(3.5)


def test_drift_monitor_trips_latch_and_count():
    reg_outcomes = [
        {"tau": 1.0 + 0.01 * (i % 3), "is_local": [True, False],
         "job_rate": [0.5, 0.5]}
        for i in range(24)
    ]
    shifted = [
        {"tau": 40.0, "is_local": [True, False], "job_rate": [6.0, 6.0]}
        for _ in range(20)
    ]
    mon = DriftMonitor(min_samples=16)
    assert mon.feed(reg_outcomes) == []
    trips = mon.feed(shifted)
    signals = {t["signal"] for t in trips}
    assert "tau" in signals and "arrival_rate" in signals
    assert mon.samples == 44
    # latched: the same shift reported once, not once per sample
    assert mon.feed(shifted) == []
    assert mon.trips == trips
    mon.reset()
    assert all(not d.tripped for d in mon.detectors.values())


# ---- flight recorder --------------------------------------------------------

def test_flight_recorder_ring_evicts_oldest():
    t = {"now": 0.0}
    rec = FlightRecorder(capacity=3, clock=lambda: t["now"])
    for i in range(7):
        t["now"] = float(i)
        rec.record("tick", tick=i)
    assert len(rec) == 3
    assert [r["tick"] for r in rec.records()] == [4, 5, 6]
    assert [r["ts"] for r in rec.records()] == [4.0, 5.0, 6.0]
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_flight_recorder_dump_bundle_and_failure(tmp_path):
    rec = FlightRecorder(capacity=4, clock=lambda: 123.0)
    for i in range(6):
        rec.record("tick", tick=i, queue_depth=i * 2)
    out = rec.dump(str(tmp_path), "serve_p99 breach!",
                   alerts={"serve_p99": {"state": "firing"}},
                   extra={"note": "drill"})
    assert os.path.basename(out) == "flight-001-serve_p99-breach"
    rows = [json.loads(ln) for ln in
            open(os.path.join(out, "records.jsonl"))]
    assert [r["tick"] for r in rows] == [2, 3, 4, 5]
    meta = json.load(open(os.path.join(out, "bundle.json")))
    assert meta["reason"] == "serve_p99 breach!"
    assert meta["records"] == 4 and meta["capacity"] == 4
    assert meta["alerts"]["serve_p99"]["state"] == "firing"
    assert meta["note"] == "drill"
    assert os.path.getsize(os.path.join(out, "metrics.prom")) >= 0

    out2 = rec.dump(str(tmp_path), "again")
    assert os.path.basename(out2) == "flight-002-again"

    # an unwritable target reports a failure, never raises into the tick
    blocker = tmp_path / "blocked"
    blocker.write_text("not a directory")
    assert rec.dump(str(blocker), "nope") == ""


# ---- drift-triggered capture transition -------------------------------------

def test_promotion_controller_drift_triggered(tmp_path):
    from multihop_offload_tpu.loop.promote import PromotionController

    c = PromotionController(str(tmp_path))
    c.drift_triggered(
        {"signal": "tau", "detector": "page_hinkley", "stat": 15.2,
         "value": 3.3, "samples": 40},
        cycle=2,
    )
    assert c.state == "capturing"
    last = c.history[-1]
    assert last["trigger"] == "drift_triggered"
    assert last["signal"] == "tau" and last["cycle"] == 2


# ---- report: alerts & drift section -----------------------------------------

def test_report_renders_alerts_and_degrades_without_them(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = RunLog(path, manifest={"event": "manifest", "ts": 0, "role": "t"})
    log.emit("alert", name="serve_p99", state="firing", at=5.0,
             burn_short=12.0, burn_long=4.0)
    log.emit("drift", signal="tau", detector="page_hinkley", samples=40,
             stat=15.2)
    log.emit("flight_record", path="/x/flight-001-serve_p99",
             reason="serve_p99", records=64)
    log.close()

    from multihop_offload_tpu.obs.report import load_run, render_report

    run = load_run(path)
    assert len(run["health"]["alert"]) == 1
    text = render_report(path)
    assert "alerts & drift" in text
    assert "serve_p99" in text and "firing" in text
    assert "still firing at log end: serve_p99" in text
    assert "drift trip: tau" in text
    assert "flight-001-serve_p99" in text

    # a pre-health log renders with no section and no crash
    old = str(tmp_path / "old.jsonl")
    log2 = RunLog(old, manifest={"event": "manifest", "ts": 0})
    log2.tick(n=1, served=2, queue_depth=0)
    log2.close()
    assert "alerts & drift" not in render_report(old)

"""Every `[project.scripts]` target must resolve: import the module, find
the callable.  A dangling entry point (the `mho-bench` gap this pins) only
explodes at `pip install` + first invocation — too late.

Python 3.10 has no tomllib, so the section is regex-parsed; the parse is
itself asserted so a reformatted pyproject can't silently empty the list.
"""

import importlib
import os
import re

import pytest

_PYPROJECT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "pyproject.toml")


def _script_targets():
    with open(_PYPROJECT, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"\[project\.scripts\]\n(.*?)(?=\n\[)", text, re.S)
    assert m, "pyproject.toml has no [project.scripts] section"
    targets = re.findall(
        r'^([A-Za-z0-9_-]+)\s*=\s*"([A-Za-z0-9_.]+):([A-Za-z0-9_]+)"',
        m.group(1), re.M)
    assert len(targets) >= 12, f"parsed only {len(targets)} script targets"
    return targets


def test_script_section_parses():
    names = [t[0] for t in _script_targets()]
    assert "mho-bench" in names  # the once-dangling entry point
    assert len(names) == len(set(names))


@pytest.mark.parametrize(
    "script,module,func", _script_targets(), ids=[t[0] for t in _script_targets()]
)
def test_entry_point_resolves(script, module, func):
    mod = importlib.import_module(module)
    fn = getattr(mod, func, None)
    assert callable(fn), f"{script}: {module}:{func} is not callable"

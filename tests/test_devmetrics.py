"""obs/devmetrics: device-side accumulators through jit/vmap/scan/shards.

The contract under test: declared-once metrics updated with pure jnp ops
inside compiled programs, merged across leading axes (vmap lanes, shard
copies) at flush, landing in the host registry with EXACT counts — plus
the registry's label-cardinality cap that keeps the flush sink bounded.
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.obs import jaxhooks
from multihop_offload_tpu.obs.devmetrics import DevMetrics, pow2_buckets
from multihop_offload_tpu.obs.registry import DROPPED_LABELSETS, MetricRegistry

BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0)


def _dm():
    dm = DevMetrics()
    c = dm.counter("mho_dev_t_events_total", "events seen")
    g = dm.gauge("mho_dev_t_level", "last level")
    h = dm.histogram("mho_dev_t_depth", BOUNDS, "depth")
    return dm.freeze(), c, g, h


def _hand_hist(values, weights=None):
    """Prometheus `le` bucketing (+Inf tail) in plain numpy."""
    v = np.ravel(np.asarray(values, np.float64))
    w = (np.ones(v.shape, np.int64) if weights is None
         else np.ravel(np.asarray(weights, np.int64)))
    idx = np.searchsorted(np.asarray(BOUNDS, np.float64), v, side="left")
    counts = np.zeros(len(BOUNDS) + 1, np.int64)
    np.add.at(counts, idx, w)
    live = v[w > 0]
    return {
        "counts": counts.tolist(),
        "count": int(w.sum()),
        "sum": float(np.sum(v * w)),
        "min": float(live.min()) if live.size else None,
        "max": float(live.max()) if live.size else None,
    }


def test_pow2_buckets_ladder():
    assert pow2_buckets(64) == (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)
    assert pow2_buckets(6) == (0.0, 1.0, 2.0, 4.0, 6.0)


def test_roundtrip_through_jit_vmap_scan():
    dm, C, G, H = _dm()
    lanes, steps, width = 3, 7, 4
    xs_np = (np.arange(lanes * steps * width) % 9).astype(np.float32)
    xs_np = xs_np.reshape(lanes, steps, width)

    def body(dev, x):
        dev = dm.inc(dev, C, x > 0)
        dev = dm.set(dev, G, jnp.sum(x))
        dev = dm.observe(dev, H, x)
        return dev, ()

    @jax.jit
    def run(xs):
        def lane(x_lane):
            dev, _ = jax.lax.scan(body, dm.init(), x_lane)
            return dev

        return jax.vmap(lane)(xs)

    flushed = dm.flush(run(jnp.asarray(xs_np)), reg=MetricRegistry())
    want = _hand_hist(xs_np)
    assert int(flushed[C]) == int((xs_np > 0).sum())
    assert flushed[H]["counts"] == want["counts"]
    assert flushed[H]["count"] == want["count"]
    assert flushed[H]["sum"] == want["sum"]  # small ints: exact in f32
    assert (flushed[H]["min"], flushed[H]["max"]) == (want["min"], want["max"])
    # gauge keeps the last written value per lane; flush averages lanes
    assert flushed[G] == pytest.approx(
        float(np.mean(xs_np[:, -1, :].sum(axis=1))))


def test_flush_merges_leading_axes_like_hand_math():
    dm, C, G, H = _dm()
    d1 = dm.init()
    d1 = dm.observe(d1, H, jnp.asarray([0.0, 0.5, 3.0]),
                    weights=jnp.asarray([1, 0, 2]))
    d1 = dm.inc(d1, C, 5)
    d1 = dm.set(d1, G, 2.0)
    d2 = dm.init()
    d2 = dm.observe(d2, H, jnp.asarray([9.0, 1.0]))
    d2 = dm.inc(d2, C, jnp.asarray([True, False, True]))
    d2 = dm.set(d2, G, 4.0)
    stacked = jax.tree_util.tree_map(lambda a, b: jnp.stack([a, b]), d1, d2)

    reg = MetricRegistry()
    out = dm.flush(stacked, reg=reg, shard="x")
    assert int(out[C]) == 7
    assert out[G] == pytest.approx(3.0)  # replica gauges average
    # weight-0 entries touch neither counts nor sum/min/max
    assert out[H]["counts"] == [1, 1, 0, 2, 0, 1]
    assert out[H] == {"counts": [1, 1, 0, 2, 0, 1], "count": 5,
                      "sum": 16.0, "min": 0.0, "max": 9.0}
    # the registry saw the same series, under the flush-site labels
    assert reg.counter("mho_dev_t_events_total").value(shard="x") == 7.0
    snap = reg.snapshot()["mho_dev_t_depth"]["series"]['{shard="x"}']
    assert (snap["count"], snap["sum"]) == (5, 16.0)

    # a second window flush ACCUMULATES into the same registry series
    dm.flush(stacked, reg=reg, shard="x")
    assert reg.counter("mho_dev_t_events_total").value(shard="x") == 14.0


def test_cross_shard_reduction_on_virtual_mesh():
    """Under a sharded program the accumulators reduce across the mesh
    inside the compiled program (GSPMD allreduce), landing replicated."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = jax.devices()
    assert len(devs) >= 8, "conftest forces an 8-device host platform"
    mesh = Mesh(np.asarray(devs[:8]), ("d",))
    dm, C, G, H = _dm()

    @jax.jit
    def step(x):
        dev = dm.init()
        dev = dm.inc(dev, C, x > 0)
        dev = dm.set(dev, G, jnp.mean(x))
        dev = dm.observe(dev, H, x)
        return dev

    x_np = (np.arange(64) % 11).astype(np.float32)
    xs = jax.device_put(jnp.asarray(x_np), NamedSharding(mesh, PartitionSpec("d")))
    dev = step(xs)
    assert dev["c"][C].sharding.is_fully_replicated

    flushed = dm.flush(dev, reg=MetricRegistry())
    want = _hand_hist(x_np)
    assert int(flushed[C]) == int((x_np > 0).sum())
    assert flushed[H]["counts"] == want["counts"]
    assert flushed[H]["sum"] == want["sum"]


def test_steady_state_updates_and_flushes_do_not_retrace():
    dm, C, G, H = _dm()

    @jax.jit
    def step(x):
        dev = dm.init()
        dev = dm.inc(dev, C, x > 0)
        dev = dm.observe(dev, H, x)
        return dev

    jaxhooks.install()
    x = jnp.arange(16.0)
    dm.flush(step(x), reg=MetricRegistry())  # warm: program + bulk packer
    before = jaxhooks.unexpected_retraces()
    jaxhooks.mark_steady()
    try:
        for _ in range(3):
            dm.flush(step(x), reg=MetricRegistry())
        assert jaxhooks.unexpected_retraces() == before
    finally:
        jaxhooks.clear_steady()


def test_declaration_is_frozen_after_init():
    dm = DevMetrics()
    dm.counter("mho_dev_t_a_total")
    dm.init()
    with pytest.raises(RuntimeError):
        dm.counter("mho_dev_t_b_total")
    with pytest.raises(ValueError):
        DevMetrics().histogram("mho_dev_t_h", ())


def test_registry_label_cardinality_cap(monkeypatch):
    monkeypatch.setenv("MHO_REGISTRY_MAX_LABELSETS", "3")
    reg = MetricRegistry()
    c = reg.counter("capped_total", "cap drill")
    with pytest.warns(RuntimeWarning, match="label-set cap"):
        for i in range(5):
            c.inc(1, worker=str(i))
    assert c.value(worker="0") == 1.0
    assert c.value(worker="2") == 1.0
    assert c.value(worker="4") == 0.0  # beyond the cap: dropped
    assert c.total() == 3.0            # only the admitted series count
    assert reg.counter(DROPPED_LABELSETS).value(metric="capped_total") == 2.0
    # existing series keep updating — only NEW label sets are refused
    c.inc(1, worker="1")
    assert c.value(worker="1") == 2.0

"""serve/guards: typed admission validation — the semantic front door.

The contract under test: every rejection reason in `guards.REASONS` is
reachable, every `faults.REQUEST_MUTATIONS` family maps to exactly the
reason its catalogue row predicts (across seeds), and validation is a pure
veto — accepted requests come out of the guard bit-identical to how they
went in.  All host-side numpy; no jit, no service.
"""

import dataclasses

import numpy as np
import pytest

from multihop_offload_tpu.chaos import faults
from multihop_offload_tpu.graphs.topology import build_topology
from multihop_offload_tpu.serve import guards
from multihop_offload_tpu.serve.workload import case_pool, request_stream

SEEDS = (0, 1, 2, 3, 4)


def _valid_request(seed=0, n=12):
    pool = case_pool([n], per_size=1, seed=seed)
    return next(iter(request_stream(pool, 1, seed=seed + 1)))


def test_valid_requests_accepted_across_seeds():
    for seed in SEEDS:
        req = _valid_request(seed=seed)
        assert guards.validate_request(req) is None


@pytest.mark.parametrize("mutation,want", faults.REQUEST_MUTATIONS)
def test_every_mutation_family_rejected_with_predicted_reason(mutation, want):
    for seed in SEEDS:
        base = _valid_request(seed=seed)
        rej = guards.validate_request(faults.fuzz_request(base, mutation,
                                                          seed=seed))
        assert rej is not None, f"{mutation} seed {seed} slipped through"
        assert rej.reason == want
        assert rej.detail


def test_every_reason_reachable():
    """The closed REASONS vocabulary has no dead entries: the fuzz
    catalogue reaches most, and the two topology-level reasons
    (disconnected, plus bad_role via a serverless instance) are reached
    by direct construction."""
    hit = {
        guards.validate_request(
            faults.fuzz_request(_valid_request(seed=s), mutation, seed=s)
        ).reason
        for mutation, _ in faults.REQUEST_MUTATIONS
        for s in SEEDS[:2]
    }
    # disconnected: two 6-rings with no bridge, otherwise-valid request
    ring = np.zeros((12, 12), dtype=np.uint8)
    for comp in (range(0, 6), range(6, 12)):
        comp = list(comp)
        for a, b in zip(comp, comp[1:] + comp[:1]):
            ring[a, b] = ring[b, a] = 1
    topo = build_topology(ring)
    assert not topo.connected
    roles = np.zeros(12, dtype=np.int32)
    roles[[1, 7]] = 1
    split = dataclasses.replace(
        _valid_request(seed=0),
        topo=topo, roles=roles,
        proc_bws=np.full(12, 50.0),
        link_rates=np.full(topo.num_links, 10.0),
        job_src=np.array([0, 6], dtype=np.int32),
        job_rate=np.array([0.2, 0.2]),
        topo_key=None,
    )
    rej = guards.validate_request(split)
    assert rej is not None and rej.reason == "disconnected"
    hit.add(rej.reason)
    # bad_role via the no-server branch (relay_src covers the other branch)
    serverless = dataclasses.replace(
        split, roles=np.zeros(12, dtype=np.int32))
    assert guards.validate_request(serverless).reason == "bad_role"
    assert hit | {"bad_role"} == set(guards.REASONS)


def test_validation_is_a_pure_veto():
    """Accepted or rejected, the request comes out bit-identical: the
    guard reads, it never writes — the unguarded serve path sees exactly
    the bytes the client sent."""
    for req in (_valid_request(seed=3),
                faults.fuzz_request(_valid_request(seed=3), "nan_rate")):
        before = {
            f: np.array(getattr(req, f), copy=True)
            for f in ("roles", "proc_bws", "link_rates", "job_src", "job_rate")
        }
        guards.validate_request(req)
        for f, snap in before.items():
            assert np.array_equal(np.asarray(getattr(req, f)), snap,
                                  equal_nan=True), f"guard mutated {f}"


def test_nonfinite_wins_over_positivity():
    """First-failure-wins ordering: a NaN rate that is also 'not > 0'
    reads as nonfinite, so the reason names the root cause."""
    req = _valid_request(seed=1)
    rate = np.array(req.job_rate, copy=True)
    rate[0] = np.nan
    rate[-1] = -1.0
    rej = guards.validate_request(dataclasses.replace(req, job_rate=rate))
    assert rej.reason == "nonfinite"


def test_saturation_threshold_is_max_rho():
    req = _valid_request(seed=2)
    assert guards.validate_request(req, max_rho=1.0) is None
    rej = guards.validate_request(req, max_rho=1e-9)
    assert rej.reason == "saturated"
    assert "rho=" in rej.detail


def test_rejection_vocabulary_is_closed():
    with pytest.raises(ValueError):
        guards.Rejection("bogus_reason", "nope")
    assert {want for _, want in faults.REQUEST_MUTATIONS} < set(guards.REASONS)

"""NumPy/SciPy oracle of the reference environment semantics, for tests only.

An independent re-statement (per-job Python loops, scipy Dijkstra) of the
behavior specified by `/root/reference/src/offloading_v3.py` and the decision
math of `gnn_offloading_agent.py`, used to certify the fixed-shape JAX
kernels.  Operates on the framework's CaseRecord/array types, canonical link
order, deterministic (explore=0) decisions.
"""

from __future__ import annotations

import numpy as np
from scipy.sparse import csr_matrix
from scipy.sparse.csgraph import dijkstra


def apsp_oracle(weight_mtx: np.ndarray) -> np.ndarray:
    """All-pairs Dijkstra over an (N,N) one-hop weight matrix (inf = no edge)."""
    n = weight_mtx.shape[0]
    w = np.array(weight_mtx, dtype=np.float64)
    np.fill_diagonal(w, 0.0)
    mask = np.isfinite(w) & (w > 0)
    g = csr_matrix((w[mask], np.nonzero(mask)), shape=(n, n))
    d = dijkstra(g, directed=False)
    np.fill_diagonal(d, 0.0)
    return d


def hop_oracle(adj: np.ndarray) -> np.ndarray:
    w = np.where(adj > 0, 1.0, np.inf)
    return apsp_oracle(w)


def greedy_route(adj, sp, src, dst):
    """Reference routing (`offloading_v3.py:441-453`): descend sp toward dst,
    ties to the lowest-index neighbor."""
    route = [src]
    node = src
    hops = 0
    while node != dst:
        nbs = np.flatnonzero(adj[node])
        node = int(nbs[np.argmin(sp[nbs, dst])])
        route.append(node)
        hops += 1
        assert hops <= adj.shape[0], "routing did not terminate"
    return route, hops


def offload_oracle(case_arrays, jobs, sp_in_diag, sp, hop):
    """Greedy decision per job (`offloading_v3.py:388-439`), explore=0.

    case_arrays: dict with adj, servers (ascending), ...
    jobs: list of dicts {src, rate, ul, dl}
    sp_in_diag: (N,) unit delays that sat on the SP diagonal
    sp/hop: zero-diagonal matrices.
    Returns decisions (dst list), delay estimates, routes, hop counts.
    """
    servers = case_arrays["servers"]
    adj = case_arrays["adj"]
    out = []
    for job in jobs:
        src, ul, dl = job["src"], job["ul"], job["dl"]
        local = sp_in_diag[src] * ul
        cand = []
        for s in servers:
            d_ul = max(sp[src, s] * ul, hop[src, s])
            d_dl = max(sp[s, src] * dl, hop[s, src])
            d_pr = max(sp_in_diag[s] * ul, 1.0)
            cand.append(d_ul + d_dl + d_pr)
        costs = np.array(cand + [local])
        k = int(np.argmin(costs))
        if k < len(servers):
            dst = int(servers[k])
            route, hops = greedy_route(adj, sp, src, dst)
        else:
            dst, route, hops = src, [src, src], 0
        out.append(
            {"dst": dst, "route": route, "nhop": hops, "est": costs[k],
             "costs": costs}
        )
    return out


def fixed_point_oracle(link_rates, cf_degs, adj_conflict, link_lambda, iters=10):
    """`offloading_v3.py:500-506`."""
    mu = link_rates / (cf_degs + 1.0)
    for _ in range(iters):
        with np.errstate(divide="ignore", invalid="ignore"):
            busy = np.clip(link_lambda / mu, 0.0, 1.0)
        mu = link_rates / (1.0 + adj_conflict @ busy)
    return mu


def run_oracle(case_arrays, jobs, flows, T):
    """Empirical delays (`offloading_v3.py:455-550`).

    Returns per-job totals, the unit-delay matrix (NaN = unwritten), and the
    aggregates, with the reference's exact branch conditions.
    """
    link_index = case_arrays["link_index"]
    link_rates = case_arrays["link_rates"]
    cf_degs = case_arrays["cf_degs"]
    adjc = case_arrays["adj_conflict"]
    proc_bws = case_arrays["proc_bws"]
    n = proc_bws.shape[0]
    num_links = link_rates.shape[0]
    J = len(jobs)

    link_lambda = np.zeros(num_links)
    server_load = np.zeros(n)
    for job, fl in zip(jobs, flows):
        rate_ul = job["ul"] * job["rate"]
        rate_dl = job["dl"] * job["rate"]
        if job["src"] != fl["dst"]:
            for a, b in zip(fl["route"][:-1], fl["route"][1:]):
                link_lambda[link_index[a, b]] += rate_ul + rate_dl
        server_load[fl["dst"]] += rate_ul

    mu = fixed_point_oracle(link_rates, cf_degs, adjc, link_lambda)

    unit_mtx = np.full((n, n), np.nan)
    link_part = np.zeros(J)
    serv_part = np.zeros(J)
    for j, (job, fl) in enumerate(zip(jobs, flows)):
        nhop = float(fl["nhop"])
        if job["src"] != fl["dst"]:
            for a, b in zip(fl["route"][:-1], fl["route"][1:]):
                li = link_index[a, b]
                if mu[li] - link_lambda[li] <= 0:
                    u = T * link_lambda[li] / ((job["ul"] + job["dl"]) * mu[li])
                else:
                    u = 1.0 / (mu[li] - link_lambda[li])
                unit_mtx[a, b] = unit_mtx[b, a] = u
                link_part[j] += max(job["ul"] * u, nhop) + max(job["dl"] * u, nhop)
        dst = fl["dst"]
        if proc_bws[dst] - server_load[dst] <= 0:
            us = T * server_load[dst] / (job["ul"] * proc_bws[dst])
        else:
            us = 1.0 / (proc_bws[dst] - server_load[dst])
        unit_mtx[dst, dst] = us
        serv_part[j] = max(job["ul"] * us, 1.0)

    return {
        "total": link_part + serv_part,
        "link_part": link_part,
        "server_part": serv_part,
        "unit_mtx": unit_mtx,
        "link_lambda": link_lambda,
        "link_mu": mu,
        "server_load": server_load,
    }


def case_arrays(rec, link_rates_realized):
    """Bundle a CaseRecord + realized link rates for the oracle calls."""
    return {
        "adj": rec.topo.adj.astype(np.int64),
        "link_index": rec.topo.link_index,
        "link_rates": np.asarray(link_rates_realized, dtype=np.float64),
        "cf_degs": rec.topo.cf_degs.astype(np.float64),
        "adj_conflict": rec.topo.adj_conflict.astype(np.float64),
        "proc_bws": rec.proc_bws.astype(np.float64),
        "servers": np.flatnonzero(rec.roles == 1),
    }


def baseline_oracle(ca, T):
    """dmtx_baseline semantics (`offloading_v3.py:341-361`)."""
    with np.errstate(divide="ignore"):
        dlist = 1.0 / ca["link_rates"]
        dproc = 1.0 / ca["proc_bws"]
    n = ca["proc_bws"].shape[0]
    w = np.full((n, n), np.inf)
    iu, ju = np.nonzero(ca["adj"])
    w[iu, ju] = dlist[ca["link_index"][iu, ju]]
    return w, dlist, dproc

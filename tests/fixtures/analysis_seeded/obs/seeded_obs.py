"""Seeded violations: OB001 (library print) and JX005 (nondeterminism)."""

import time

import numpy as np


def noisy_telemetry(value):
    print(f"value={value}")  # OB001: bare print in library code
    stamp = time.time()  # JX005: wall clock without an injected clock
    jitter = np.random.rand()  # JX005: legacy global-state RNG
    rng = np.random.default_rng()  # JX005: unseeded generator
    return stamp + jitter + rng.random()

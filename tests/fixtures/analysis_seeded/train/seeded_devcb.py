"""Seeded violation: OB003 (host callback inside jit-reachable code).

Lives under train/ (NOT obs/ — the obs layer owns deliberate host
bridges and is exempt), so the jit-reachable `jax.debug.print` below
must fire, while the host-only helper and the waived site must not.
"""

import jax
import jax.numpy as jnp
from jax.experimental import io_callback


@jax.jit
def traced_debug(x):
    jax.debug.print("x = {}", x)  # OB003: device stalls on the host hop
    return x * 2


@jax.jit
def traced_io(x):
    io_callback(print, None, x)  # OB003: same, io_callback spelling
    return x + 1


@jax.jit
def waived_site(x):
    jax.debug.print("x = {}", x)  # devcb-ok(test fixture waiver)
    return x


def host_only_logger(x):
    # NOT jit-reachable: host callbacks are fine outside compiled programs
    jax.debug.print("host {}", jnp.sum(x))
    return x

"""Seeded violation: OB002 (direct XLA cost introspection outside obs/)."""


def roll_your_own_roofline(jitted, args):
    compiled = jitted.lower(*args).compile()
    ca = compiled.cost_analysis()  # OB002: prof layer owns this surface
    mem = compiled.memory_analysis()  # OB002: same
    return ca, mem


def waived_site(compiled):
    return compiled.cost_analysis()  # prof-ok(test fixture waiver)

"""Seeded violation: JX012 (use-after-donate)."""

import jax


def _mul(w, x):
    return w * x


step = jax.jit(_mul, donate_argnums=(1,))


def run_tick(weights, batch):
    out = step(weights, batch)
    # JX012: `batch` was donated to step() — its pages may back `out`
    return out, batch.sum()

"""Seeded violation: JX007 (unplaced device_put in the serving path)."""

import jax


def stage_weights(variables):
    # JX007: no device/sharding — lands on jax's default device and fights
    # the placement planner's assignment
    staged = jax.device_put(variables)
    ok = jax.device_put(variables, jax.devices()[0])  # explicit: clean
    return staged, ok

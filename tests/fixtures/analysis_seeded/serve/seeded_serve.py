"""Seeded violation: JX004 (host sync inside a hot-loop `tick`)."""

import numpy as np


class MiniService:
    def __init__(self, device_out):
        self.device_out = device_out

    def tick(self):
        # JX004: one device sync per tick
        host = np.asarray(self.device_out)
        self.device_out.block_until_ready()  # JX004 again
        return float(host[0])

"""Seeded violations: JX010 (process-group bring-up outside multihost/).

Both halves of the rule — a raw `jax.distributed.initialize` call and
ad-hoc process-index/count branching — in a non-multihost directory,
plus one waived line proving the `# mesh-ok(<why>)` escape hatch
suppresses a finding without silencing the rest.
"""

import jax


def bring_up(coordinator: str, n: int, pid: int):
    # JX010: initialize is once-per-process; multihost.runtime owns the
    # guard, retries and env fallback
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=n, process_id=pid
    )


def who_am_i() -> bool:
    return jax.process_index() == 0  # JX010: ad-hoc host-0 fork


def fleet_size() -> int:
    return jax.process_count()  # JX010: topology read outside the runtime


def waived_gate() -> bool:
    return jax.process_index() == 0  # mesh-ok(fixture: reviewed host0-only write gate)

"""Seeded violation: JX006 (swallowed exceptions in a recovery-critical dir)."""


def resume_state(path):
    try:
        with open(path) as f:
            return f.read()
    except Exception:
        # JX006: a swallowed load failure here hides checkpoint corruption
        pass
    return None


def cleanup(path):
    try:
        import os

        os.remove(path)
    except:  # JX006: bare except swallows even KeyboardInterrupt
        pass

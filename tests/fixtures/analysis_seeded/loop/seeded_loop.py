"""Seeded violations: JX006 (swallowed exceptions in a recovery-critical
dir) and JX008 (unguarded `1 - rho` saturation denominator)."""


def resume_state(path):
    try:
        with open(path) as f:
            return f.read()
    except Exception:
        # JX006: a swallowed load failure here hides checkpoint corruption
        pass
    return None


def cleanup(path):
    try:
        import os

        os.remove(path)
    except:  # JX006: bare except swallows even KeyboardInterrupt
        pass


def saturation_delay(rho):
    # JX008: inf at rho=1, negative past it — must clamp, select, or waive
    return 1.0 / (1 - rho)


def saturation_delay_waived(rho):
    return 1.0 / (1 - rho)  # div-ok(caller clamps rho to [0, 0.95])

"""Seeded violations: JX009 (host sync / callback in a rollout-scan body).

Every pattern the rule exists to catch, inside bodies that actually feed
`jax.lax.scan` — plus one waived line proving the `# rollout-ok(<why>)`
escape hatch suppresses a finding without silencing the rest.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def bad_rollout(state0, keys):
    def round_body(carry, key):
        jax.debug.callback(lambda c: None, carry)  # JX009: host callback in scan
        total = float(np.sum(carry))  # JX009: host numpy inside the scan
        flag = carry.item()  # JX009: .item() device->host sync per round
        return carry + total + flag, None

    out, _ = lax.scan(round_body, state0, keys)
    return out


def lambda_rollout(state0, keys):
    # JX009: io_callback inside an inline lambda scan body
    out, _ = lax.scan(
        lambda c, k: (jax.experimental.io_callback(print, None, c), None),
        state0, keys,
    )
    return out


def waived_rollout(state0, keys):
    def round_body(carry, key):
        jax.debug.print("r={r}", r=carry)  # rollout-ok(one-off debug session, removed before merge)
        return carry + jnp.sum(key), None

    out, _ = lax.scan(round_body, state0, keys)
    return out

"""Seeded violations: JX011 (raw networkx topology draws outside graphs/).

Three spellings of the ad-hoc draw — a `*_graph` family constructor, an
aliased import, and a bare `nx.Graph()` hand-build — plus one waived
line proving the `# topo-ok(<why>)` escape hatch suppresses a finding
without silencing the rest.
"""

import networkx as nx
from networkx import barabasi_albert_graph


def adhoc_family_draw(n: int, m: int, seed: int):
    # JX011: skips the connectivity retry and (adj, pos) contract that
    # graphs.generators.generate owns
    return nx.barabasi_albert_graph(n, m, seed=seed)


def adhoc_aliased_draw(n: int, seed: int):
    return barabasi_albert_graph(n, 2, seed=seed)  # JX011: alias resolves


def hand_built():
    g = nx.Graph()  # JX011: hand-built container, same hazard
    g.add_edge(0, 1)
    return g


def waived_draw(n: int):
    return nx.path_graph(n)  # topo-ok(fixture: reviewed doc example, not a sim topology)

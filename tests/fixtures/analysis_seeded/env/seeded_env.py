"""Seeded violations: MP001, SL001 (multi-line!), JX001, JX002, JX003.

The SL001 site is split across lines exactly the way the old
`_SQUARE_DENSE` regex could not see (tests/test_analysis.py reproduces
the miss against the historical pattern).
"""

import jax
import jax.numpy as jnp


def hardcoded_dtype(x):
    return x.astype(jnp.float32)  # MP001: hardcoded float32 in hot dir


def dense_square(n):
    return jnp.zeros(
        (n, n)  # SL001: dense (N, N) — and JX003: no dtype — multi-line
    )


def unpinned_iota(n):
    return jnp.arange(n)  # JX003: arange without dtype


@jax.jit
def traced_branch(x):
    s = jnp.sum(x)
    if s > 0:  # JX001: Python `if` on a traced value
        return s
    return -s


def retrace_hazard(batches):
    outs = []
    for b in batches:
        f = jax.jit(lambda v: v * 2)  # JX002: jit built per iteration
        outs.append(f(b))
    return outs

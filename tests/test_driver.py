"""End-to-end drivers: datagen -> train -> test on tiny scales."""

import os

import numpy as np
import pandas as pd
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.cli.datagen import generate_dataset
from multihop_offload_tpu.train.driver import (
    Evaluator,
    Trainer,
    TEST_COLUMNS,
    TRAIN_COLUMNS,
)


@pytest.fixture(scope="module")
def tiny_dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("data") / "aco_data_ba_tiny")
    generate_dataset(d, gtype="ba", size=2, seed0=500, graph_sizes=[20, 30],
                     verbose=False)
    return d


def _cfg(tmp_path, datapath, **kw):
    defaults = dict(
        datapath=datapath, out=str(tmp_path / "out"), T=1000,
        arrival_scale=0.15, dtype="float64", num_instances=4, batch=6,
        memory_size=32, training_set="TEST", seed=3,
        learning_rate=1e-5, epochs=1,
    )
    defaults.update(kw)
    cfg = Config(**defaults)
    return cfg


def test_datagen_schema(tiny_dataset):
    from multihop_offload_tpu.graphs.matio import list_dataset, load_case_mat

    names = list_dataset(tiny_dataset)
    assert len(names) == 4  # 2 seeds x 2 sizes
    rec = load_case_mat(os.path.join(tiny_dataset, names[0]))
    assert rec.topo.connected
    assert rec.num_servers >= 1 and rec.num_relays >= 1
    assert (rec.roles == 2).sum() + (rec.roles == 1).sum() + rec.mobile_nodes.size == rec.topo.n
    # servers are concentrated, with Pareto-drawn capacities >= 100
    assert rec.proc_bws[rec.roles == 1].min() >= 100


def test_trainer_runs_and_updates_weights(tmp_path, tiny_dataset, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset)
    trainer = Trainer(cfg)
    p0 = np.asarray(trainer.variables["params"]["cheb_0"]["kernel"]).copy()
    csv = trainer.run(epochs=1, verbose=False)
    df = pd.read_csv(csv)
    assert list(df.columns) == TRAIN_COLUMNS
    # 4 files x 4 instances x 4 methods
    assert len(df) == 4 * 4 * 4
    assert set(df["method"]) == {"baseline", "local", "GNN", "GNN-test"}
    assert np.isfinite(df["tau"]).all()
    # baseline rows have ratio 1 and gap 0 against themselves
    bl = df[df["method"] == "baseline"]
    assert np.allclose(bl["gnn_bl_ratio"], 1.0) and np.allclose(bl["gap_2_bl"], 0.0)
    # replay fired (memory 16 >= batch 6 after file 2) and moved the weights
    p1 = np.asarray(trainer.variables["params"]["cheb_0"]["kernel"])
    assert not np.allclose(p0, p1)
    # orbax checkpoint was written and restores
    step = trainer.try_restore()
    assert step == 0


def test_evaluator_csv_schema(tmp_path, tiny_dataset, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset)
    ev = Evaluator(cfg)
    csv = ev.run(files_limit=2, verbose=False)
    df = pd.read_csv(csv)
    assert list(df.columns) == TEST_COLUMNS
    assert len(df) == 2 * 4 * 3
    assert set(df["Algo"]) == {"baseline", "local", "GNN"}
    # local never congests more than baseline on these tiny loads
    assert np.isfinite(df["tau"]).all()

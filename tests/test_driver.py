"""End-to-end drivers: datagen -> train -> test on tiny scales."""

import os

import jax
import numpy as np
import pandas as pd
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.cli.datagen import generate_dataset
from multihop_offload_tpu.train.driver import (
    Evaluator,
    Trainer,
    TEST_COLUMNS,
    TRAIN_COLUMNS,
)


@pytest.fixture(scope="module")
def tiny_dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("data") / "aco_data_ba_tiny")
    generate_dataset(d, gtype="ba", size=2, seed0=500, graph_sizes=[20, 30],
                     verbose=False)
    return d


def _cfg(tmp_path, datapath, **kw):
    defaults = dict(
        datapath=datapath, out=str(tmp_path / "out"), T=1000,
        arrival_scale=0.15, dtype="float64", num_instances=4, batch=6,
        memory_size=32, training_set="TEST", seed=3,
        learning_rate=1e-5, epochs=1,
    )
    defaults.update(kw)
    cfg = Config(**defaults)
    return cfg


def test_datagen_schema(tiny_dataset):
    from multihop_offload_tpu.graphs.matio import list_dataset, load_case_mat

    names = list_dataset(tiny_dataset)
    assert len(names) == 4  # 2 seeds x 2 sizes
    rec = load_case_mat(os.path.join(tiny_dataset, names[0]))
    assert rec.topo.connected
    assert rec.num_servers >= 1 and rec.num_relays >= 1
    assert (rec.roles == 2).sum() + (rec.roles == 1).sum() + rec.mobile_nodes.size == rec.topo.n
    # servers are concentrated, with Pareto-drawn capacities >= 100
    assert rec.proc_bws[rec.roles == 1].min() >= 100


def test_trainer_runs_and_updates_weights(tmp_path, tiny_dataset, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset)
    trainer = Trainer(cfg)
    p0 = np.asarray(trainer.variables["params"]["cheb_0"]["kernel"]).copy()
    csv = trainer.run(epochs=1, verbose=False)
    df = pd.read_csv(csv)
    assert list(df.columns) == TRAIN_COLUMNS
    # 4 files x 4 instances x 4 methods
    assert len(df) == 4 * 4 * 4
    assert set(df["method"]) == {"baseline", "local", "GNN", "GNN-test"}
    assert np.isfinite(df["tau"]).all()
    # baseline rows have ratio 1 and gap 0 against themselves
    bl = df[df["method"] == "baseline"]
    assert np.allclose(bl["gnn_bl_ratio"], 1.0) and np.allclose(bl["gap_2_bl"], 0.0)
    # replay fired (memory 16 >= batch 6 after file 2) and moved the weights
    p1 = np.asarray(trainer.variables["params"]["cheb_0"]["kernel"]).copy()
    assert not np.allclose(p0, p1)
    # the checkpoint restores the FINAL weights (orbax silently keeps the
    # first save of a step id, so saving under a fixed step froze the
    # checkpoint at its first write — the regression behind round 2's
    # useless committed model)
    step = trainer.try_restore()
    assert step is not None and step >= 1  # one save per file visit
    np.testing.assert_array_equal(
        np.asarray(trainer.variables["params"]["cheb_0"]["kernel"]), p1
    )


def test_evaluator_csv_schema(tmp_path, tiny_dataset, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset)
    ev = Evaluator(cfg)
    csv = ev.run(files_limit=2, verbose=False)
    df = pd.read_csv(csv)
    assert list(df.columns) == TEST_COLUMNS
    assert len(df) == 2 * 4 * 3
    assert set(df["Algo"]) == {"baseline", "local", "GNN"}
    # local never congests more than baseline on these tiny loads
    assert np.isfinite(df["tau"]).all()


def test_pad_buckets_partition_and_cover(tiny_dataset):
    """Bucketed pads: every record's true sizes fit its bucket's pad, buckets
    ascend, and bucket count respects the config."""
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.train.data import DatasetCache

    cfg = Config(datapath=tiny_dataset, pad_buckets=3, dtype="float64")
    data = DatasetCache.load(cfg)
    assert 1 <= len(data.pads) <= 3
    for p_ in data.pads:
        assert (data.pad.n >= p_.n and data.pad.l >= p_.l
                and data.pad.s >= p_.s and data.pad.j >= p_.j)
    for i, rec in enumerate(data.records):
        pad = data.pad_of(i)
        assert rec.topo.n <= pad.n
        assert rec.topo.num_links <= pad.l
        assert rec.num_servers <= pad.s
        assert rec.mobile_nodes.size <= pad.j
    ns = [p.n for p in data.pads]
    assert ns == sorted(ns)


def test_evaluator_with_buckets_matches_schema(tmp_path, tiny_dataset, monkeypatch):
    """The bucketed Evaluator produces the same CSV schema; each bucket
    compiles its own step."""
    import pandas as pd

    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.train.driver import TEST_COLUMNS, Evaluator

    monkeypatch.chdir(tmp_path)
    cfg = Config(datapath=tiny_dataset, pad_buckets=2, num_instances=2,
                 dtype="float64", epochs=1, seed=3)
    ev = Evaluator(cfg)
    csv = ev.run(files_limit=4, verbose=False)
    df = pd.read_csv(csv)
    assert list(df.columns) == TEST_COLUMNS
    assert set(df["Algo"]) == {"baseline", "local", "GNN"}


def test_prob_mode_plumbed_through_evaluator(tmp_path, tiny_dataset, monkeypatch):
    """cfg.prob (reference FLAGS.prob softmax sampling) must change GNN
    decisions; baseline/local are unaffected."""
    import pandas as pd

    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.train.driver import Evaluator

    monkeypatch.chdir(tmp_path)
    out = {}
    for prob in (False, True):
        cfg = Config(datapath=tiny_dataset, num_instances=2, dtype="float64",
                     seed=11, prob=prob, out=f"out_{prob}")
        df = pd.read_csv(Evaluator(cfg).run(files_limit=2, verbose=False))
        out[prob] = df
    for algo in ("baseline", "local"):
        a = out[False][out[False].Algo == algo]["tau"].to_numpy()
        b = out[True][out[True].Algo == algo]["tau"].to_numpy()
        np.testing.assert_allclose(a, b)
    g0 = out[False][out[False].Algo == "GNN"]["tau"].to_numpy()
    g1 = out[True][out[True].Algo == "GNN"]["tau"].to_numpy()
    assert not np.allclose(g0, g1)  # softmax sampling changes decisions


def test_dp_evaluator_matches_single_device(tmp_path, tiny_dataset, monkeypatch):
    """File-sharded evaluation over the 8-device mesh must be bit-equal to
    the single-device loop: same seed -> same workloads (keys are unused by
    deterministic argmin decisions), so tau/congestion match exactly."""
    monkeypatch.chdir(tmp_path)
    cols = ["filename", "n_instance", "Algo", "tau", "congest_jobs"]
    dfs = {}
    for mesh_data, tag in ((1, "single"), (0, "auto")):
        # pad_buckets=2: the DP path visits files bucket-by-bucket, the
        # single-device loop in fid order — per-file RNG keying must make
        # the workloads identical anyway
        cfg = _cfg(tmp_path, tiny_dataset, mesh_data=mesh_data,
                   pad_buckets=2, out=str(tmp_path / f"out_{tag}"))
        ev = Evaluator(cfg)
        assert ev.n_dp == (1 if mesh_data == 1 else 8)
        dfs[tag] = pd.read_csv(ev.run(verbose=False)).sort_values(
            ["filename", "Algo", "n_instance"]
        )[cols].reset_index(drop=True)
    pd.testing.assert_frame_equal(dfs["single"], dfs["auto"])


def test_cli_train_dp_on_mesh(tmp_path, tiny_dataset, monkeypatch):
    """`cli/train.py` end-to-end on the 8-virtual-device mesh: the Trainer
    takes the data-parallel path (mesh_data auto), writes the training CSV,
    and checkpoints restorably."""
    from multihop_offload_tpu.cli import train as cli_train
    from multihop_offload_tpu.config import from_args

    monkeypatch.chdir(tmp_path)
    argv = [
        f"--datapath={tiny_dataset}", f"--out={tmp_path / 'out_cli'}",
        f"--model_root={tmp_path / 'model_cli'}", "--epochs=1",
        "--num_instances=4", "--batch=6", "--memory_size=32",
        "--dtype=float64", "--seed=3", "--training_set=CLI",
        "--learning_rate=1e-5",
    ]
    cli_train.main(argv)
    csvs = list((tmp_path / "out_cli").glob("aco_training_data_*.csv"))
    assert len(csvs) == 1
    df = pd.read_csv(csvs[0])
    assert list(df.columns) == TRAIN_COLUMNS
    assert len(df) == 4 * 4 * 4  # files x instances x methods
    assert np.isfinite(df["tau"]).all()
    # one Trainer both proves the CLI config resolves to the DP path and
    # restores the checkpoint the CLI run wrote (latest file-visit step)
    tr = Trainer(from_args(argv))
    assert tr.n_dp == 8
    assert tr.try_restore() == 3  # 4 files visited, one save per visit


def test_file_batched_evaluator_matches_plain(tmp_path, tiny_dataset, monkeypatch):
    """file_batch>1 stacks several files into one device program; results
    must be bit-equal to the plain per-file loop (per-file RNG keying)."""
    monkeypatch.chdir(tmp_path)
    cols = ["filename", "n_instance", "Algo", "tau", "congest_jobs"]
    dfs = {}
    for fb, tag in ((1, "plain"), (3, "batched")):
        cfg = _cfg(tmp_path, tiny_dataset, mesh_data=1, file_batch=fb,
                   out=str(tmp_path / f"out_fb{fb}"))
        ev = Evaluator(cfg)
        assert ev.eval_chunk == fb
        dfs[tag] = pd.read_csv(ev.run(verbose=False)).sort_values(
            ["filename", "Algo", "n_instance"]
        )[cols].reset_index(drop=True)
    pd.testing.assert_frame_equal(dfs["plain"], dfs["batched"])


def test_apsp_impl_knob_plumbs_through_evaluator(tmp_path, tiny_dataset, monkeypatch):
    """apsp_impl='pallas' resolves to the self-dispatching Pallas wrapper
    (XLA fallback off-TPU) and must give identical results to 'xla'."""
    monkeypatch.chdir(tmp_path)
    cols = ["filename", "n_instance", "Algo", "tau", "congest_jobs"]
    dfs = {}
    for impl in ("xla", "pallas"):
        cfg = _cfg(tmp_path, tiny_dataset, mesh_data=1, apsp_impl=impl,
                   out=str(tmp_path / f"out_{impl}"))
        ev = Evaluator(cfg)
        assert ev.apsp_path == ("xla" if impl == "xla" else "xla-fallback")
        dfs[impl] = pd.read_csv(ev.run(files_limit=2, verbose=False)).sort_values(
            ["filename", "Algo", "n_instance"]
        )[cols].reset_index(drop=True)
    pd.testing.assert_frame_equal(dfs["xla"], dfs["pallas"])


def test_restore_across_optimizer_structures(tmp_path, tiny_dataset, monkeypatch):
    """A checkpoint trained under an LR-schedule optimizer (learning_decay
    < 1 changes the optax state tree) must still evaluate under the default
    constant-lr config: try_restore falls back to a params-only raw restore
    instead of refusing the whole tree."""
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset, mesh_data=1, learning_decay=0.95,
               model_root=str(tmp_path / "m_sched"))
    tr = Trainer(cfg)
    tr.run(epochs=2, verbose=False)
    trained = jax.tree_util.tree_map(np.asarray, tr.variables["params"])

    ev = Evaluator(_cfg(tmp_path, tiny_dataset, mesh_data=1,
                        model_root=str(tmp_path / "m_sched")))
    assert ev.cfg.learning_decay == 1.0  # structures genuinely differ
    assert ev.try_restore() is not None
    restored = jax.tree_util.tree_map(np.asarray, ev.variables["params"])
    jax.tree_util.tree_map(np.testing.assert_array_equal, trained, restored)

    # a PARAMS mismatch (wrong model order) must keep failing loudly — the
    # fallback is for opt_state-only divergence
    ev2 = Evaluator(_cfg(tmp_path, tiny_dataset, mesh_data=1, cheb_k=2,
                         model_root=str(tmp_path / "m_sched")))
    with pytest.raises(ValueError):
        ev2.try_restore()


def test_best_checkpoint_tracking(tmp_path, tiny_dataset, monkeypatch):
    """The Trainer keeps a separate best-rolling-tau checkpoint that
    restores independently of the latest (training collapses late —
    training/README.md)."""
    import json

    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset, mesh_data=1, best_window=2,
               model_root=str(tmp_path / "m_best"))
    tr = Trainer(cfg)
    tr.run(epochs=2, verbose=False)
    best_dir = os.path.join(cfg.model_dir(), "orbax_best")
    assert os.path.isdir(best_dir)
    with open(os.path.join(best_dir, "best.json")) as f:
        rec = json.load(f)
    assert np.isfinite(rec["rolling_gnn_test_tau"])
    assert rec["rolling_gnn_test_tau"] == tr.best_tau
    # best restores, and may differ from latest
    ev = Evaluator(Config(**{**cfg.__dict__}))
    step_best = ev.try_restore(which="best")
    assert step_best == rec["step"]


def test_csv_flusher_append_equals_rewrite(tmp_path):
    """Append-mode flushing must produce byte-identical files to the full
    per-flush rewrite it replaced (reference per-file flush semantics)."""
    import pandas as pd

    from multihop_offload_tpu.train.driver import _CsvFlusher

    cols = ["a", "b", "c"]
    rows = []
    p_new = str(tmp_path / "append.csv")
    p_old = str(tmp_path / "rewrite.csv")
    fl = _CsvFlusher(p_new, cols)
    rng = np.random.default_rng(0)
    for step in range(7):
        for _ in range(int(rng.integers(0, 4))):
            rows.append({"a": float(rng.normal()), "b": int(rng.integers(100)),
                         "c": f"s{rng.integers(10)}"})
        fl.flush(rows)
        pd.DataFrame(rows, columns=cols).to_csv(p_old, index=False)
    assert open(p_new, "rb").read() == open(p_old, "rb").read()


def test_file_ids_shard_matches_sequential(tmp_path, tiny_dataset, monkeypatch):
    """Explicit `file_ids` shards (the two-process file-sharding unit,
    scripts/multiprocess_eval.py) merged together must be bit-equal to the
    sequential sweep over the same files — `_file_rng` keys workloads on
    (seed, fid) alone, so sharding cannot change any realized workload."""
    monkeypatch.chdir(tmp_path)
    cols = ["filename", "n_instance", "Algo", "tau", "congest_jobs"]

    cfg = _cfg(tmp_path, tiny_dataset, mesh_data=1,
               out=str(tmp_path / "out_seq"))
    ev = Evaluator(cfg)
    n = len(ev.data)
    seq = pd.read_csv(ev.run(verbose=False))

    shards = []
    for p in range(2):
        cfg_p = _cfg(tmp_path, tiny_dataset, mesh_data=1,
                     out=str(tmp_path / f"out_p{p}"))
        shards.append(pd.read_csv(
            Evaluator(cfg_p).run(file_ids=range(p, n, 2), verbose=False)
        ))
    merged = pd.concat(shards)
    key = ["filename", "Algo", "n_instance"]
    pd.testing.assert_frame_equal(
        seq.sort_values(key)[cols].reset_index(drop=True),
        merged.sort_values(key)[cols].reset_index(drop=True),
    )


def test_empty_file_ids_raises(tmp_path, tiny_dataset, monkeypatch):
    """A shard spec that selects zero files is a misconfiguration (wrong
    process count / dataset size) and must fail at the Evaluator, not as a
    missing-CSV error in whatever merges the shards downstream."""
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(tmp_path, tiny_dataset, mesh_data=1)
    ev = Evaluator(cfg)
    n = len(ev.data)
    with pytest.raises(ValueError, match="file_ids selects no files"):
        ev.run(file_ids=range(n, n + 4), verbose=False)
    # a generator that filters empty is caught too (not just empty lists)
    with pytest.raises(ValueError, match="file_ids selects no files"):
        ev.run(file_ids=(f for f in [-1, n]), verbose=False)

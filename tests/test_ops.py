"""Pallas min-plus APSP kernel (interpret mode on CPU) vs the XLA version."""

import numpy as np
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.env.apsp import apsp_minplus
from multihop_offload_tpu.ops.minplus import apsp_minplus_pallas


def _random_symmetric_weights(rng, n, p=0.1):
    w = np.full((n, n), np.inf)
    iu, ju = np.where(np.triu(rng.uniform(size=(n, n)) < p, 1))
    vals = rng.uniform(0.1, 5.0, iu.size)
    w[iu, ju] = w[ju, iu] = vals
    return w


@pytest.mark.parametrize("n", [40, 128, 150])
def test_pallas_apsp_matches_xla(n):
    rng = np.random.default_rng(n)
    w = _random_symmetric_weights(rng, n, p=4.0 / n)
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(w, jnp.float32), interpret=True)
    )
    expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert (np.isinf(got) == np.isinf(expect)).all()
    assert (np.diag(got) == 0).all()


def test_pallas_apsp_batched():
    rng = np.random.default_rng(0)
    ws = np.stack([_random_symmetric_weights(rng, 64, 0.1) for _ in range(3)])
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(ws, jnp.float32), interpret=True)
    )
    for b in range(3):
        expect = np.asarray(apsp_minplus(jnp.asarray(ws[b], jnp.float32)))
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[b][finite], expect[finite], rtol=1e-6)

"""Pallas min-plus APSP kernel (interpret mode on CPU) vs the XLA version."""

import numpy as np
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.env.apsp import apsp_minplus
from multihop_offload_tpu.ops.minplus import apsp_minplus_pallas


def _random_symmetric_weights(rng, n, p=0.1):
    w = np.full((n, n), np.inf)
    iu, ju = np.where(np.triu(rng.uniform(size=(n, n)) < p, 1))
    vals = rng.uniform(0.1, 5.0, iu.size)
    w[iu, ju] = w[ju, iu] = vals
    return w


@pytest.mark.parametrize("n", [40, 128, 150])
def test_pallas_apsp_matches_xla(n):
    rng = np.random.default_rng(n)
    w = _random_symmetric_weights(rng, n, p=4.0 / n)
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(w, jnp.float32), interpret=True)
    )
    expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert (np.isinf(got) == np.isinf(expect)).all()
    assert (np.diag(got) == 0).all()


def test_pallas_apsp_batched():
    rng = np.random.default_rng(0)
    ws = np.stack([_random_symmetric_weights(rng, 64, 0.1) for _ in range(3)])
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(ws, jnp.float32), interpret=True)
    )
    for b in range(3):
        expect = np.asarray(apsp_minplus(jnp.asarray(ws[b], jnp.float32)))
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[b][finite], expect[finite], rtol=1e-6)


def test_forward_env_accepts_pallas_apsp():
    """The large-scale path (scripts/large_scale_demo.py) swaps the APSP
    kernel via `apsp_fn`; decisions and delays must be invariant to it."""
    import functools

    import jax

    from multihop_offload_tpu.agent import forward_env
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.graphs import generators
    from multihop_offload_tpu.graphs.instance import (
        PadSpec, build_instance, build_jobset,
    )
    from multihop_offload_tpu.graphs.topology import build_topology, sample_link_rates
    from multihop_offload_tpu.models import make_model

    rng = np.random.default_rng(3)
    adj, _ = generators.generate("er", 24, seed=5)
    topo = build_topology(adj)
    roles = np.zeros(24, dtype=np.int32)
    roles[[3, 11]] = 1
    bws = np.where(roles == 1, 80.0, 4.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=np.float64)
    mobile = np.flatnonzero(roles == 0)
    jobs = build_jobset(mobile[:6], 0.15 * rng.uniform(0.1, 0.5, 6), pad_jobs=8,
                        dtype=np.float64)

    cfg = Config(dtype="float64")
    model = make_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), inst.adj_ext
    )
    key = jax.random.PRNGKey(9)
    out_xla, _ = forward_env(model, variables, inst, jobs, key)
    out_pl, _ = forward_env(
        model, variables, inst, jobs, key,
        apsp_fn=functools.partial(apsp_minplus_pallas, interpret=True),
    )
    np.testing.assert_array_equal(
        np.asarray(out_xla.decision.dst), np.asarray(out_pl.decision.dst)
    )
    np.testing.assert_allclose(
        np.asarray(out_xla.job_total), np.asarray(out_pl.job_total),
        rtol=1e-9, equal_nan=True,
    )

"""Pallas min-plus APSP kernel (interpret mode on CPU) vs the XLA version."""

import numpy as np
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.env.apsp import apsp_minplus
from multihop_offload_tpu.ops.minplus import apsp_minplus_pallas


def _random_symmetric_weights(rng, n, p=0.1):
    w = np.full((n, n), np.inf)
    iu, ju = np.where(np.triu(rng.uniform(size=(n, n)) < p, 1))
    vals = rng.uniform(0.1, 5.0, iu.size)
    w[iu, ju] = w[ju, iu] = vals
    return w


@pytest.mark.parametrize("n", [40, 128, 150])
def test_pallas_apsp_matches_xla(n):
    rng = np.random.default_rng(n)
    w = _random_symmetric_weights(rng, n, p=4.0 / n)
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(w, jnp.float32), interpret=True)
    )
    expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert (np.isinf(got) == np.isinf(expect)).all()
    assert (np.diag(got) == 0).all()


def test_pallas_apsp_batched():
    rng = np.random.default_rng(0)
    ws = np.stack([_random_symmetric_weights(rng, 64, 0.1) for _ in range(3)])
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(ws, jnp.float32), interpret=True)
    )
    for b in range(3):
        expect = np.asarray(apsp_minplus(jnp.asarray(ws[b], jnp.float32)))
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[b][finite], expect[finite], rtol=1e-6)


def test_forward_env_accepts_pallas_apsp():
    """The large-scale path (scripts/large_scale_demo.py) swaps the APSP
    kernel via `apsp_fn`; decisions and delays must be invariant to it."""
    import functools

    import jax

    from multihop_offload_tpu.agent import forward_env
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.graphs import generators
    from multihop_offload_tpu.graphs.instance import (
        PadSpec, build_instance, build_jobset,
    )
    from multihop_offload_tpu.graphs.topology import build_topology, sample_link_rates
    from multihop_offload_tpu.models import make_model

    rng = np.random.default_rng(3)
    adj, _ = generators.generate("er", 24, seed=5)
    topo = build_topology(adj)
    roles = np.zeros(24, dtype=np.int32)
    roles[[3, 11]] = 1
    bws = np.where(roles == 1, 80.0, 4.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=np.float64)
    mobile = np.flatnonzero(roles == 0)
    jobs = build_jobset(mobile[:6], 0.15 * rng.uniform(0.1, 0.5, 6), pad_jobs=8,
                        dtype=np.float64)

    cfg = Config(dtype="float64")
    model = make_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), inst.adj_ext
    )
    key = jax.random.PRNGKey(9)
    out_xla, _ = forward_env(model, variables, inst, jobs, key)
    out_pl, _ = forward_env(
        model, variables, inst, jobs, key,
        apsp_fn=functools.partial(apsp_minplus_pallas, interpret=True),
    )
    np.testing.assert_array_equal(
        np.asarray(out_xla.decision.dst), np.asarray(out_pl.decision.dst)
    )
    np.testing.assert_allclose(
        np.asarray(out_xla.job_total), np.asarray(out_pl.job_total),
        rtol=1e-9, equal_nan=True,
    )


def _fp_xla(adj, rates, cf, lam):
    """The framework's own fixed-point core (env.queueing) is the reference
    for every Pallas fixed-point test — one definition, no drift."""
    from multihop_offload_tpu.env.queueing import interference_fixed_point_raw

    return interference_fixed_point_raw(adj, rates, cf, lam, 10)


def _random_conflict_case(rng, l, p=0.15):
    a = (rng.uniform(size=(l, l)) < p).astype(np.float64)
    a = np.triu(a, 1)
    a = a + a.T
    return a, rng.uniform(30, 70, l), a.sum(0), rng.uniform(0, 50, l)


def test_pallas_fixed_point_matches_xla_and_grads():
    """Fused VMEM fixed point == `env.queueing.interference_fixed_point`,
    values and gradients (custom VJP recomputes through the XLA scan)."""
    import jax

    from multihop_offload_tpu.ops import fixed_point_pallas

    rng = np.random.default_rng(17)
    args = tuple(map(jnp.asarray, _random_conflict_case(rng, 72)))
    got = fixed_point_pallas(*args, 10, True)
    expect = _fp_xla(*args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), rtol=1e-12)

    # gradient of a scalar loss w.r.t. lambda and rates
    g_got = jax.grad(
        lambda lam_, r_: jnp.sum(fixed_point_pallas(args[0], r_, args[2], lam_,
                                                    10, True) ** 2),
        argnums=(0, 1),
    )(args[3], args[1])
    g_exp = jax.grad(
        lambda lam_, r_: jnp.sum(_fp_xla(args[0], r_, args[2], lam_) ** 2),
        argnums=(0, 1),
    )(args[3], args[1])
    for a, b in zip(g_got, g_exp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-10)


def test_pallas_fixed_point_batched_values_and_grads():
    import jax

    from multihop_offload_tpu.ops import fixed_point_pallas

    rng = np.random.default_rng(23)
    cases = [_random_conflict_case(rng, 40, 0.2) for _ in range(3)]
    batched = tuple(
        jnp.asarray(np.stack([c[k] for c in cases])) for k in range(4)
    )
    got = fixed_point_pallas(*batched, 10, True)
    for i in range(3):
        expect = np.asarray(_fp_xla(*map(jnp.asarray, cases[i])))
        np.testing.assert_allclose(np.asarray(got[i]), expect, rtol=1e-12)

    # batched gradient path goes through the custom VJP's XLA recompute
    g_got = jax.grad(
        lambda lam: jnp.sum(
            fixed_point_pallas(batched[0], batched[1], batched[2], lam, 10, True)
            ** 2
        )
    )(batched[3])
    g_exp = jax.grad(
        lambda lam: jnp.sum(_fp_xla(batched[0], batched[1], batched[2], lam) ** 2)
    )(batched[3])
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_exp), rtol=1e-10)


def test_coo_propagation_matches_dense_chebnet():
    """Same params, sparse COO propagation == dense propagation."""
    import jax

    from multihop_offload_tpu.models import ChebNet
    from multihop_offload_tpu.models.chebconv import chebyshev_support
    from multihop_offload_tpu.ops import coo_propagate, dense_to_coo

    rng = np.random.default_rng(31)
    e = 48
    adj = (rng.uniform(size=(e, e)) < 0.15).astype(np.float64)
    adj = np.triu(adj, 1)
    adj = adj + adj.T
    feats = jnp.asarray(rng.normal(size=(e, 4)))
    support = chebyshev_support(jnp.asarray(adj), jnp.ones((e,), bool))
    dense_model = ChebNet(num_layer=3, hidden=8, k=3, param_dtype=jnp.float64)
    variables = dense_model.init(jax.random.PRNGKey(0), feats, support)
    expect = dense_model.apply(variables, feats, support)

    coo = dense_to_coo(np.asarray(support))
    sparse_model = ChebNet(num_layer=3, hidden=8, k=3,
                           param_dtype=jnp.float64, propagate=coo_propagate)
    got = jax.jit(lambda v, x, s: sparse_model.apply(v, x, s))(
        variables, feats, coo
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-12, atol=1e-12)


def test_coo_matmul_matches_dense():
    from multihop_offload_tpu.ops import coo_matmul, dense_to_coo

    rng = np.random.default_rng(7)
    m = rng.normal(size=(20, 20)) * (rng.uniform(size=(20, 20)) < 0.3)
    x = rng.normal(size=(20, 5))
    got = np.asarray(coo_matmul(dense_to_coo(m), jnp.asarray(x)))
    np.testing.assert_allclose(got, m @ x, rtol=1e-12, atol=1e-12)


def test_blocked_fw_matches_xla_beyond_squaring_cap():
    """Padded N > 256 must run the blocked Floyd-Warshall, not silently
    delegate to XLA (round-1 gap: `_MAX_KERNEL_N` silently fell back)."""
    from multihop_offload_tpu.ops.minplus import blocked_fw_call, pallas_apsp_path

    assert pallas_apsp_path(150, interpret=True) == "squaring"
    assert pallas_apsp_path(300, interpret=True) == "blocked-fw"
    assert pallas_apsp_path(1000, interpret=True) == "blocked-fw"
    assert pallas_apsp_path(3000, interpret=True) == "xla-fallback"
    # off-TPU without interpret the dispatcher must delegate to XLA
    assert pallas_apsp_path(150) == "xla-fallback"

    rng = np.random.default_rng(7)
    n = 300  # pads to 384 = 3 tiles
    w = _random_symmetric_weights(rng, n, p=4.0 / n)
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(w, jnp.float32), interpret=True)
    )
    expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert (np.isinf(got) == np.isinf(expect)).all()
    assert (np.diag(got) == 0).all()


def test_auto_apsp_follows_measured_crossover():
    """`apsp_impl='auto'` must pick the fastest MEASURED implementation per
    shape (benchmarks/pallas_tpu.json round-5 re-ladder: XLA wins below
    padded N=256, chunked squaring at 256, blocked FW from 384) — not
    'pallas whenever on TPU' (the pre-crossover policy)."""
    from multihop_offload_tpu.ops.minplus import (
        apsp_minplus_auto, auto_apsp_path, resolve_apsp,
    )

    # below the crossover auto = XLA regardless of backend
    assert auto_apsp_path(110, interpret=True) == "xla"
    assert auto_apsp_path(256, interpret=True) == "squaring"
    assert auto_apsp_path(384, interpret=True) == "blocked-fw"
    assert auto_apsp_path(512, interpret=True) == "blocked-fw"
    assert auto_apsp_path(1000, interpret=True) == "blocked-fw"
    assert auto_apsp_path(3000, interpret=True) == "xla-fallback"

    # resolve_apsp('auto') returns the None sentinel (plain XLA APSP, no
    # wrapper overhead) below the crossover, the dispatching wrapper above
    fn, path = resolve_apsp("auto", 110)
    assert fn is None and path == "xla"
    fn, path = resolve_apsp("auto", 512, interpret=True)
    assert fn is not None and path == "blocked-fw"
    # 'pallas' still forces the kernel at small sizes (proof runs)
    _, path = resolve_apsp("pallas", 110, interpret=True)
    assert path == "squaring"

    # numerics through the auto wrapper: below the crossover (xla), at the
    # round-5 squaring boundary (256), the blocked-FW onset (384 — routed
    # to blocked-fw since the re-ladder), and well above (512)
    rng = np.random.default_rng(11)
    for n in (60, 256, 384, 512):
        w = _random_symmetric_weights(rng, n, p=4.0 / n)
        got = np.asarray(
            apsp_minplus_auto(jnp.asarray(w, jnp.float32), interpret=True)
        )
        expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)


def test_blocked_fw_asymmetric_and_batched():
    """blocked_fw_call is exact FW — no symmetry assumption; batched."""
    from multihop_offload_tpu.ops.minplus import blocked_fw_call

    rng = np.random.default_rng(3)
    t = 8  # small tile keeps interpret-mode runtime down
    n = 4 * t
    d = rng.uniform(0.1, 5.0, (2, n, n)).astype(np.float32)
    mask = rng.uniform(size=(2, n, n)) < 0.4
    d = np.where(mask, d, np.inf).astype(np.float32)
    for b in range(2):
        np.fill_diagonal(d[b], 0.0)
    got = np.asarray(blocked_fw_call(jnp.asarray(d), tile=t, interpret=True))
    for b in range(2):
        e = d[b].copy()
        for k in range(n):
            e = np.minimum(e, e[:, k : k + 1] + e[k : k + 1, :])
        finite = np.isfinite(e)
        np.testing.assert_allclose(got[b][finite], e[finite], rtol=1e-6)
        assert (np.isinf(got[b]) == np.isinf(e)).all()


def test_fixed_point_off_tpu_fallback_matches_reference(small_cases, rng):
    """interpret=False off-TPU must delegate to the XLA reference (the
    dispatch contract shared with apsp_minplus_pallas) — values identical,
    and fixed_point_path reports the fallback honestly."""
    import numpy as np

    from multihop_offload_tpu.ops.fixed_point import (
        _xla_reference, fixed_point_pallas, fixed_point_path,
    )

    assert fixed_point_path() == "xla-fallback"  # suite runs on CPU
    l, b = 64, 3
    adj = (rng.random((b, l, l)) < 0.1).astype(np.float32)
    for i in range(b):
        adj[i] = np.maximum(adj[i], adj[i].T)
        np.fill_diagonal(adj[i], 0.0)
    rates = rng.uniform(30, 70, (b, l)).astype(np.float32)
    cf = adj.sum(-1).astype(np.float32)
    lam = rng.uniform(0, 5, (b, l)).astype(np.float32)
    out = fixed_point_pallas(adj, rates, cf, lam, 10, False)
    ref = _xla_reference(adj, rates, cf, lam, 10)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_resolve_fixed_point_paths():
    """`fp_impl` knob resolution mirrors `resolve_apsp`: None is the sentinel
    for direct XLA execution (incl. off-TPU fallback and beyond the measured
    crossover); interpret mode yields a real Pallas callable."""
    from multihop_offload_tpu.ops.fixed_point import resolve_fixed_point

    fn, path = resolve_fixed_point("xla", 256)
    assert fn is None and path == "xla"
    # beyond the in-step-measured win (L=256): direct XLA — L=384/512 have
    # no in-step A/B and the 384 microbench rung loses, so 'auto' stops at
    # the evidence; fp_impl='pallas' is the explicit override there
    fn, path = resolve_fixed_point("auto", 640)
    assert fn is None and path == "xla"
    fn, path = resolve_fixed_point("auto", 512)
    assert fn is None and path == "xla"
    # L=256 is the measured 1.16x in-step win; off-TPU it still resolves
    # to the honest fallback path
    fn, path = resolve_fixed_point("auto", 256)
    assert fn is None and path == "xla-fallback"
    # inside the measured win but suite runs on CPU: direct XLA, honest path
    fn, path = resolve_fixed_point("auto", 200)
    assert fn is None and path == "xla-fallback"
    fn, path = resolve_fixed_point("auto", 200, interpret=True)
    assert fn is not None and path == "pallas"
    import pytest

    with pytest.raises(ValueError):
        resolve_fixed_point("bogus", 128)


def test_forward_backward_invariant_to_fp_impl():
    """Training math must be invariant to the fixed-point kernel choice:
    `fp_fn` (interpret-mode Pallas, custom_vjp) == default XLA scan for
    values AND parameter gradients."""
    import jax

    from multihop_offload_tpu.agent.train_step import forward_backward
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.graphs import generators
    from multihop_offload_tpu.graphs.instance import (
        PadSpec, build_instance, build_jobset,
    )
    from multihop_offload_tpu.graphs.topology import (
        build_topology, sample_link_rates,
    )
    from multihop_offload_tpu.models import make_model
    from multihop_offload_tpu.ops.fixed_point import resolve_fixed_point

    rng = np.random.default_rng(7)
    adj, _ = generators.generate("er", 24, seed=8)
    topo = build_topology(adj)
    roles = np.zeros(24, dtype=np.int32)
    roles[[2, 9]] = 1
    bws = np.where(roles == 1, 80.0, 4.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=np.float64)
    mobile = np.flatnonzero(roles == 0)
    jobs = build_jobset(mobile[:6], 0.15 * rng.uniform(0.1, 0.5, 6), pad_jobs=8,
                        dtype=np.float64)

    cfg = Config(dtype="float64")
    model = make_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), inst.adj_ext
    )
    key = jax.random.PRNGKey(4)
    fp_fn, path = resolve_fixed_point("pallas", pad.l, interpret=True)
    assert path == "pallas"
    out_xla = forward_backward(model, variables, inst, jobs, key)
    out_pl = forward_backward(model, variables, inst, jobs, key, fp_fn=fp_fn)
    np.testing.assert_array_equal(np.asarray(out_xla.dst), np.asarray(out_pl.dst))
    np.testing.assert_allclose(
        np.asarray(out_xla.delays.job_total), np.asarray(out_pl.delays.job_total),
        rtol=1e-9,
    )
    flat_x = jax.tree_util.tree_leaves(out_xla.grads)
    flat_p = jax.tree_util.tree_leaves(out_pl.grads)
    for gx, gp in zip(flat_x, flat_p):
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gp),
                                   rtol=1e-7, atol=1e-10)


def test_fixed_point_pallas_under_vmap():
    """The bench/driver A/B vmaps forward_backward over episodes with
    `fp_fn` bound — i.e. jax.vmap over the custom_vjp-wrapped pallas_call.
    Exercise exactly that composition (values + grads) in interpret mode."""
    import jax

    from multihop_offload_tpu.ops import fixed_point_pallas

    rng = np.random.default_rng(5)
    l, b = 32, 4
    adj = (rng.random((b, l, l)) < 0.2).astype(np.float32)
    for i in range(b):
        adj[i] = np.maximum(adj[i], adj[i].T)
        np.fill_diagonal(adj[i], 0.0)
    rates = rng.uniform(30, 70, (b, l)).astype(np.float32)
    cf = adj.sum(-1).astype(np.float32)
    lam = rng.uniform(0, 5, (b, l)).astype(np.float32)

    one = lambda a, r, c, m: fixed_point_pallas(a, r, c, m, 10, True)
    got = jax.vmap(one)(*map(jnp.asarray, (adj, rates, cf, lam)))
    want = _fp_xla(adj, rates, cf, lam)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    def loss(ms):
        return jnp.sum(jax.vmap(one)(jnp.asarray(adj), jnp.asarray(rates),
                                     jnp.asarray(cf), ms) ** 2)

    g = jax.grad(loss)(jnp.asarray(lam))
    g_ref = jax.grad(
        lambda ms: jnp.sum(_fp_xla(adj, rates, cf, ms) ** 2)
    )(jnp.asarray(lam))
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-8)


# ---- fused ChebConv tile + COO-fed APSP (ops.chebconv / ops.minplus) -------

# the fused tile reassociates the fp32 edge reduction (one-hot matmuls vs
# ordered segment-sum), so values/grads are compared SCALED: abs error over
# max(1, max|ref|).  The bwd rule itself recomputes through the XLA
# reference and is asserted bitwise cotangent-for-cotangent.
_SCALED_TOL = 4.5e-7


def _scaled_err(got, want):
    got, want = np.asarray(got), np.asarray(want)
    return float(np.abs(got - want).max()) / max(1.0, float(np.abs(want).max()))


def _sparse_support_case(rng, e=48, f=8, pad_extra=5):
    """A real Chebyshev support in edge-list form + features, with a few
    inert padded edges (rows=cols=0, vals=0) as real instances carry."""
    from multihop_offload_tpu.layouts.sparse import (
        _coo_from_dense_np, sparse_chebyshev_support,
    )
    from multihop_offload_tpu.ops import COO

    adj = np.triu(rng.uniform(size=(e, e)) < 0.15, 1)
    adj = (adj + adj.T).astype(np.float32)
    nnz = int(np.count_nonzero(adj))
    coo_np = _coo_from_dense_np(adj, nnz + pad_extra, np.float32)
    edges = COO(rows=jnp.asarray(coo_np.rows), cols=jnp.asarray(coo_np.cols),
                vals=jnp.asarray(coo_np.vals), shape=coo_np.shape)
    support = sparse_chebyshev_support(edges)
    x = jnp.asarray((10.0 * rng.normal(size=(e, f))).astype(np.float32))
    return support, x


def test_fused_chebconv_matches_segment_sum():
    """`make_fused_propagate` (interpret-mode Pallas) == the sparse layout's
    gather+segment-sum: values at the scaled 4.5e-7 bar, bwd BITWISE for
    identical cotangents, end-to-end grads back at the scaled bar (the
    cotangent then flows through the fused forward)."""
    import jax

    from multihop_offload_tpu.layouts.sparse import (
        SparseSupport, make_sparse_propagate,
    )
    from multihop_offload_tpu.ops import COO
    from multihop_offload_tpu.ops.chebconv import make_fused_propagate

    rng = np.random.default_rng(19)
    support, x = _sparse_support_case(rng)
    ref = make_sparse_propagate()
    fused = make_fused_propagate(interpret=True)
    want = ref(support, x)
    got = jax.jit(fused)(support, x)
    assert got.shape == want.shape and got.dtype == want.dtype
    assert _scaled_err(got, want) <= _SCALED_TOL

    e = support.edges

    def run(prop, vals, diag, xx):
        sup = SparseSupport(
            edges=COO(rows=e.rows, cols=e.cols, vals=vals, shape=e.shape),
            diag=diag,
        )
        return prop(sup, xx)

    g = jnp.asarray(rng.normal(size=np.asarray(want).shape).astype(np.float32))
    _, vjp_ref = jax.vjp(lambda v, d, xx: run(ref, v, d, xx),
                         e.vals, support.diag, x)
    _, vjp_fus = jax.vjp(lambda v, d, xx: run(fused, v, d, xx),
                         e.vals, support.diag, x)
    for a, b in zip(vjp_fus(g), vjp_ref(g)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def loss(prop):
        return lambda v, d, xx: jnp.sum(run(prop, v, d, xx) ** 2)

    gr = jax.grad(loss(ref), argnums=(0, 1, 2))(e.vals, support.diag, x)
    gf = jax.grad(loss(fused), argnums=(0, 1, 2))(e.vals, support.diag, x)
    for a, b in zip(gf, gr):
        assert _scaled_err(a, b) <= _SCALED_TOL


def test_fused_chebconv_under_vmap():
    """The bench vmaps the step over episodes with the propagate bound —
    vmap over the custom_vjp-wrapped pallas_call (values + grads)."""
    import jax

    from multihop_offload_tpu.layouts.sparse import (
        SparseSupport, make_sparse_propagate,
    )
    from multihop_offload_tpu.ops import COO
    from multihop_offload_tpu.ops.chebconv import make_fused_propagate

    rng = np.random.default_rng(29)
    support, x = _sparse_support_case(rng, e=32, f=4)
    e = support.edges
    b = 3
    vals = jnp.stack([e.vals * (1.0 + 0.1 * i) for i in range(b)])
    xs = jnp.stack([x * (1.0 - 0.2 * i) for i in range(b)])
    ref = make_sparse_propagate()
    fused = make_fused_propagate(interpret=True)

    def run(prop, v, xx):
        sup = SparseSupport(
            edges=COO(rows=e.rows, cols=e.cols, vals=v, shape=e.shape),
            diag=support.diag,
        )
        return prop(sup, xx)

    want = jax.vmap(lambda v, xx: run(ref, v, xx))(vals, xs)
    got = jax.vmap(lambda v, xx: run(fused, v, xx))(vals, xs)
    assert _scaled_err(got, want) <= _SCALED_TOL

    g_ref = jax.grad(lambda v: jnp.sum(
        jax.vmap(lambda vv, xx: run(ref, vv, xx))(v, xs) ** 2))(vals)
    g_fus = jax.grad(lambda v: jnp.sum(
        jax.vmap(lambda vv, xx: run(fused, vv, xx))(v, xs) ** 2))(vals)
    assert _scaled_err(g_fus, g_ref) <= _SCALED_TOL


def test_ragged_chebconv_skip_is_bitwise_and_fallback_exact():
    """The ragged tile's contract: (1) any live count is BIT-IDENTICAL to
    the same kernel walking the full capacity (skipped inert blocks are
    exact +0.0); (2) a traced live count serves every occupancy from ONE
    program; (3) off-TPU non-interpret delegates to the XLA reference
    bitwise; (4) the bwd recomputes through the reference bitwise."""
    import jax

    from multihop_offload_tpu.ops.chebconv import (
        _xla_propagate, chebconv_propagate_ragged, chebconv_ragged_path,
    )

    rng = np.random.default_rng(37)
    n, f, live, cap = 12, 6, 17, 300     # cap spans >2 edge blocks at eb=128
    rows = np.zeros(cap, np.int32)
    cols = np.zeros(cap, np.int32)
    vals = np.zeros(cap, np.float32)
    rows[:live] = rng.integers(0, n, live)
    cols[:live] = rng.integers(0, n, live)
    vals[:live] = rng.normal(size=live).astype(np.float32)
    diag = rng.normal(size=n).astype(np.float32)
    x = rng.normal(size=(n, f)).astype(np.float32)
    args = tuple(map(jnp.asarray, (rows, cols, vals, diag, x)))

    ragged = jax.jit(lambda lv: chebconv_propagate_ragged(
        *args, lv, "float32", True, 128))
    walked = np.asarray(ragged(jnp.int32(cap)))     # every block runs
    skipped = np.asarray(ragged(jnp.int32(live)))   # dead blocks skipped
    np.testing.assert_array_equal(skipped, walked)
    # a live count of zero leaves exactly the diagonal seed
    np.testing.assert_allclose(
        np.asarray(ragged(jnp.int32(0))), diag[:, None] * x, rtol=0, atol=0)

    ref = np.asarray(_xla_propagate(*args, acc=jnp.float32))
    assert _scaled_err(skipped, ref) <= _SCALED_TOL
    # off-TPU non-interpret: the masked XLA reference, bitwise
    fb = chebconv_propagate_ragged(*args, jnp.int32(live), "float32", False)
    np.testing.assert_array_equal(np.asarray(fb), ref)
    assert chebconv_ragged_path() == "xla-fallback"
    assert chebconv_ragged_path(interpret=True) == "pallas"

    g = jnp.asarray(rng.normal(size=ref.shape).astype(np.float32))
    _, vjp_rag = jax.vjp(
        lambda v, d, xx: chebconv_propagate_ragged(
            args[0], args[1], v, d, xx, jnp.int32(live), "float32", True, 128),
        args[2], args[3], args[4])
    _, vjp_ref = jax.vjp(
        lambda v, d, xx: _xla_propagate(
            args[0], args[1], v, d, xx, jnp.float32),
        args[2], args[3], args[4])
    for a, b in zip(vjp_rag(g), vjp_ref(g)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ragged_chebconv_factory_and_cost_facts():
    """`make_fused_propagate_ragged` mirrors the dense factory's support
    signature plus the live count; the analytic executed-cost facts scale
    with occupancy (the bench matrix's CPU-proxy reduction signal) and the
    kernel registers under its own prof program name."""
    from multihop_offload_tpu.obs.prof import prof_registry
    from multihop_offload_tpu.ops.chebconv import (
        chebconv_cost_facts, chebconv_ragged_cost_facts,
        make_fused_propagate_ragged,
    )

    rng = np.random.default_rng(41)
    support, x = _sparse_support_case(rng, e=32, f=4)
    nnz = int(support.edges.rows.shape[0])
    prop = make_fused_propagate_ragged(interpret=True)
    full = np.asarray(prop(support, x, jnp.int32(nnz)))
    rag = np.asarray(prop(support, x, jnp.int32(nnz - 2)))  # pad tail inert
    np.testing.assert_array_equal(rag, full)
    rec = prof_registry().get("ops/chebconv_ragged")
    assert rec is not None and rec.flops > 0

    # edge-dominated shape: executed flops AND bytes scale with occupancy
    dense = chebconv_cost_facts(64, 8192, 16)
    low = chebconv_ragged_cost_facts(64, 8192 // 8, 8192, 16)
    assert dense["flops"] / low["flops"] >= 2.0
    assert dense["bytes_accessed"] / low["bytes_accessed"] >= 2.0
    # executed work never exceeds capacity work
    cap = chebconv_ragged_cost_facts(64, 8192, 8192, 16)
    assert cap["flops"] == dense["flops"]


def test_resolve_chebconv_paths_and_fallback():
    """Executed-path honesty (`pallas_apsp_path` contract) + the knob: the
    off-TPU non-interpret wrapper must EXECUTE (XLA delegate, bitwise the
    reference) while reporting 'xla-fallback'; 'auto' stays XLA until
    bench_matrix.json records an on-chip chebconv_perf win."""
    from multihop_offload_tpu.layouts.sparse import make_sparse_propagate
    from multihop_offload_tpu.ops.chebconv import (
        chebconv_path, resolve_chebconv,
    )

    assert chebconv_path(interpret=True) == "pallas"
    assert chebconv_path() == "xla-fallback"  # CPU test environment

    fn, path = resolve_chebconv("xla")
    assert fn is None and path == "xla"
    fn, path = resolve_chebconv("auto")
    assert fn is None and path == "xla"  # auto stops at measured evidence
    factory, path = resolve_chebconv("pallas", interpret=True)
    assert callable(factory) and path == "pallas"
    with pytest.raises(ValueError):
        resolve_chebconv("bogus")

    factory, path = resolve_chebconv("pallas")  # off-TPU, no interpret
    assert path == "xla-fallback"
    rng = np.random.default_rng(23)
    support, x = _sparse_support_case(rng)
    got = factory()(support, x)
    want = make_sparse_propagate()(support, x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_coo_apsp_bit_identical_to_scatter_chain():
    """`apsp_minplus_coo` == scatter-build + `apsp_minplus_blocked`,
    BITWISE: single, int16 link ends (sparse storage), vmap, and the
    off-TPU fallback path."""
    import jax

    from multihop_offload_tpu.env.apsp import apsp_minplus_blocked
    from multihop_offload_tpu.layouts import weight_matrix_from_edges
    from multihop_offload_tpu.ops.minplus import apsp_minplus_coo

    rng = np.random.default_rng(13)
    n, l_pad = 40, 128
    adj = np.triu(rng.uniform(size=(n, n)) < 0.12, 1)
    us, vs = np.nonzero(adj)
    l = us.size
    assert 0 < l <= l_pad
    ends = np.zeros((l_pad, 2), np.int32)
    ends[:l, 0], ends[:l, 1] = us, vs
    mask = jnp.asarray(np.arange(l_pad) < l)
    ends = jnp.asarray(ends)
    delays = jnp.asarray(rng.uniform(0.1, 3.0, l_pad).astype(np.float32))

    want = np.asarray(apsp_minplus_blocked(
        weight_matrix_from_edges(ends, mask, delays, n)))
    got = np.asarray(apsp_minplus_coo(ends, mask, delays, n, interpret=True))
    np.testing.assert_array_equal(got, want)

    got16 = np.asarray(apsp_minplus_coo(
        ends.astype(jnp.int16), mask, delays, n, interpret=True))
    np.testing.assert_array_equal(got16, want)

    b = 3
    bd = jnp.stack([delays * (1.0 + 0.3 * i) for i in range(b)])
    want_b = np.asarray(jax.vmap(
        lambda d: apsp_minplus_blocked(
            weight_matrix_from_edges(ends, mask, d, n)))(bd))
    got_b = np.asarray(jax.vmap(
        lambda d: apsp_minplus_coo(ends, mask, d, n, interpret=True))(bd))
    np.testing.assert_array_equal(got_b, want_b)

    # off-TPU without interpret: executes the scatter+XLA chain, bitwise
    got_fb = np.asarray(apsp_minplus_coo(ends, mask, delays, n))
    np.testing.assert_array_equal(got_fb, want)


def test_coo_apsp_resolve_and_paths():
    from multihop_offload_tpu.ops.minplus import (
        coo_apsp_path, resolve_coo_apsp,
    )

    assert coo_apsp_path(150, interpret=True) == "coo-squaring"
    assert coo_apsp_path(300, interpret=True) == "blocked-fw"
    assert coo_apsp_path(3000, interpret=True) == "xla-fallback"
    assert coo_apsp_path(150) == "xla-fallback"  # off-TPU dispatch honesty

    fn, path = resolve_coo_apsp("xla", 150)
    assert fn is None and path == "xla"
    # 'auto' follows the same measured crossover as resolve_apsp
    fn, path = resolve_coo_apsp("auto", 110, interpret=True)
    assert fn is None and path == "xla"
    fn, path = resolve_coo_apsp("auto", 256, interpret=True)
    assert fn is not None and path == "coo-squaring"
    fn, path = resolve_coo_apsp("pallas", 64, interpret=True)
    assert fn is not None and path == "coo-squaring"
    with pytest.raises(ValueError):
        resolve_coo_apsp("bogus", 64)


def test_pallas_kernels_register_with_prof():
    """Both hand-written kernels must self-register analytic cost facts
    with the prof layer (they never pass through XLA cost analysis)."""
    import jax

    from multihop_offload_tpu.obs.prof import prof_registry
    from multihop_offload_tpu.ops.chebconv import make_fused_propagate
    from multihop_offload_tpu.ops.minplus import apsp_minplus_coo

    rng = np.random.default_rng(3)
    support, x = _sparse_support_case(rng, e=16, f=4)
    jax.block_until_ready(make_fused_propagate(interpret=True)(support, x))
    ends = jnp.asarray([[0, 1], [1, 2], [2, 3]], jnp.int32)
    mask = jnp.ones((3,), bool)
    delays = jnp.ones((3,), jnp.float32)
    jax.block_until_ready(apsp_minplus_coo(ends, mask, delays, 4,
                                           interpret=True))

    snap = prof_registry().snapshot()
    for name in ("ops/chebconv", "ops/coo_apsp"):
        assert name in snap, f"{name} not registered with obs/prof"
        rec = snap[name]
        for k in ("flops", "bytes_accessed", "arithmetic_intensity"):
            assert rec.get(k), f"{name} missing {k}"


def test_forward_backward_with_fused_chebconv():
    """The step-form critic chain (forward_backward) under the sparse
    layout with the fused propagate: decisions bit-identical, values and
    parameter grads at the scaled 4.5e-7 bar."""
    import jax

    from multihop_offload_tpu.agent.train_step import forward_backward
    from multihop_offload_tpu.graphs import generators
    from multihop_offload_tpu.graphs.instance import (
        PadSpec, build_instance, build_jobset,
    )
    from multihop_offload_tpu.graphs.topology import (
        build_topology, sample_link_rates,
    )
    from multihop_offload_tpu.layouts import (
        make_sparse_propagate, resolve_layout, zeros_support,
    )
    from multihop_offload_tpu.models import ChebNet
    from multihop_offload_tpu.ops.chebconv import make_fused_propagate

    lay = resolve_layout("sparse")
    rng = np.random.default_rng(7)
    # BA (the workload family the sparse nnz-pad heuristics are sized for)
    adj, _ = generators.generate("ba", 24, seed=8)
    topo = build_topology(adj)
    roles = np.zeros(24, dtype=np.int32)
    roles[[2, 9]] = 1
    bws = np.where(roles == 1, 80.0, 4.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad,
                          dtype=np.float32, layout=lay)
    mobile = np.flatnonzero(roles == 0)
    jobs = build_jobset(mobile[:6], 0.15 * rng.uniform(0.1, 0.5, 6),
                        pad_jobs=8, dtype=np.float32,
                        index_dtype=lay.index_dtype)

    model_ref = ChebNet(propagate=make_sparse_propagate())
    model_fus = ChebNet(propagate=make_fused_propagate(interpret=True))
    variables = model_ref.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float32),
        zeros_support(pad, jnp.float32, lay),
    )
    key = jax.random.PRNGKey(4)
    out_ref = forward_backward(model_ref, variables, inst, jobs, key,
                               layout=lay)
    out_fus = forward_backward(model_fus, variables, inst, jobs, key,
                               layout=lay)
    np.testing.assert_array_equal(np.asarray(out_ref.dst),
                                  np.asarray(out_fus.dst))
    assert _scaled_err(out_fus.delays.job_total,
                       out_ref.delays.job_total) <= _SCALED_TOL
    for gr, gf in zip(jax.tree_util.tree_leaves(out_ref.grads),
                      jax.tree_util.tree_leaves(out_fus.grads)):
        assert _scaled_err(gf, gr) <= _SCALED_TOL

"""Pallas min-plus APSP kernel (interpret mode on CPU) vs the XLA version."""

import numpy as np
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.env.apsp import apsp_minplus
from multihop_offload_tpu.ops.minplus import apsp_minplus_pallas


def _random_symmetric_weights(rng, n, p=0.1):
    w = np.full((n, n), np.inf)
    iu, ju = np.where(np.triu(rng.uniform(size=(n, n)) < p, 1))
    vals = rng.uniform(0.1, 5.0, iu.size)
    w[iu, ju] = w[ju, iu] = vals
    return w


@pytest.mark.parametrize("n", [40, 128, 150])
def test_pallas_apsp_matches_xla(n):
    rng = np.random.default_rng(n)
    w = _random_symmetric_weights(rng, n, p=4.0 / n)
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(w, jnp.float32), interpret=True)
    )
    expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert (np.isinf(got) == np.isinf(expect)).all()
    assert (np.diag(got) == 0).all()


def test_pallas_apsp_batched():
    rng = np.random.default_rng(0)
    ws = np.stack([_random_symmetric_weights(rng, 64, 0.1) for _ in range(3)])
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(ws, jnp.float32), interpret=True)
    )
    for b in range(3):
        expect = np.asarray(apsp_minplus(jnp.asarray(ws[b], jnp.float32)))
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[b][finite], expect[finite], rtol=1e-6)


def test_forward_env_accepts_pallas_apsp():
    """The large-scale path (scripts/large_scale_demo.py) swaps the APSP
    kernel via `apsp_fn`; decisions and delays must be invariant to it."""
    import functools

    import jax

    from multihop_offload_tpu.agent import forward_env
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.graphs import generators
    from multihop_offload_tpu.graphs.instance import (
        PadSpec, build_instance, build_jobset,
    )
    from multihop_offload_tpu.graphs.topology import build_topology, sample_link_rates
    from multihop_offload_tpu.models import make_model

    rng = np.random.default_rng(3)
    adj, _ = generators.generate("er", 24, seed=5)
    topo = build_topology(adj)
    roles = np.zeros(24, dtype=np.int32)
    roles[[3, 11]] = 1
    bws = np.where(roles == 1, 80.0, 4.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=np.float64)
    mobile = np.flatnonzero(roles == 0)
    jobs = build_jobset(mobile[:6], 0.15 * rng.uniform(0.1, 0.5, 6), pad_jobs=8,
                        dtype=np.float64)

    cfg = Config(dtype="float64")
    model = make_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), inst.adj_ext
    )
    key = jax.random.PRNGKey(9)
    out_xla, _ = forward_env(model, variables, inst, jobs, key)
    out_pl, _ = forward_env(
        model, variables, inst, jobs, key,
        apsp_fn=functools.partial(apsp_minplus_pallas, interpret=True),
    )
    np.testing.assert_array_equal(
        np.asarray(out_xla.decision.dst), np.asarray(out_pl.decision.dst)
    )
    np.testing.assert_allclose(
        np.asarray(out_xla.job_total), np.asarray(out_pl.job_total),
        rtol=1e-9, equal_nan=True,
    )


def _fp_xla(adj, rates, cf, lam):
    """The framework's own fixed-point core (env.queueing) is the reference
    for every Pallas fixed-point test — one definition, no drift."""
    from multihop_offload_tpu.env.queueing import interference_fixed_point_raw

    return interference_fixed_point_raw(adj, rates, cf, lam, 10)


def _random_conflict_case(rng, l, p=0.15):
    a = (rng.uniform(size=(l, l)) < p).astype(np.float64)
    a = np.triu(a, 1)
    a = a + a.T
    return a, rng.uniform(30, 70, l), a.sum(0), rng.uniform(0, 50, l)


def test_pallas_fixed_point_matches_xla_and_grads():
    """Fused VMEM fixed point == `env.queueing.interference_fixed_point`,
    values and gradients (custom VJP recomputes through the XLA scan)."""
    import jax

    from multihop_offload_tpu.ops import fixed_point_pallas

    rng = np.random.default_rng(17)
    args = tuple(map(jnp.asarray, _random_conflict_case(rng, 72)))
    got = fixed_point_pallas(*args, 10, True)
    expect = _fp_xla(*args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), rtol=1e-12)

    # gradient of a scalar loss w.r.t. lambda and rates
    g_got = jax.grad(
        lambda lam_, r_: jnp.sum(fixed_point_pallas(args[0], r_, args[2], lam_,
                                                    10, True) ** 2),
        argnums=(0, 1),
    )(args[3], args[1])
    g_exp = jax.grad(
        lambda lam_, r_: jnp.sum(_fp_xla(args[0], r_, args[2], lam_) ** 2),
        argnums=(0, 1),
    )(args[3], args[1])
    for a, b in zip(g_got, g_exp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-10)


def test_pallas_fixed_point_batched_values_and_grads():
    import jax

    from multihop_offload_tpu.ops import fixed_point_pallas

    rng = np.random.default_rng(23)
    cases = [_random_conflict_case(rng, 40, 0.2) for _ in range(3)]
    batched = tuple(
        jnp.asarray(np.stack([c[k] for c in cases])) for k in range(4)
    )
    got = fixed_point_pallas(*batched, 10, True)
    for i in range(3):
        expect = np.asarray(_fp_xla(*map(jnp.asarray, cases[i])))
        np.testing.assert_allclose(np.asarray(got[i]), expect, rtol=1e-12)

    # batched gradient path goes through the custom VJP's XLA recompute
    g_got = jax.grad(
        lambda lam: jnp.sum(
            fixed_point_pallas(batched[0], batched[1], batched[2], lam, 10, True)
            ** 2
        )
    )(batched[3])
    g_exp = jax.grad(
        lambda lam: jnp.sum(_fp_xla(batched[0], batched[1], batched[2], lam) ** 2)
    )(batched[3])
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_exp), rtol=1e-10)


def test_coo_propagation_matches_dense_chebnet():
    """Same params, sparse COO propagation == dense propagation."""
    import jax

    from multihop_offload_tpu.models import ChebNet
    from multihop_offload_tpu.models.chebconv import chebyshev_support
    from multihop_offload_tpu.ops import coo_propagate, dense_to_coo

    rng = np.random.default_rng(31)
    e = 48
    adj = (rng.uniform(size=(e, e)) < 0.15).astype(np.float64)
    adj = np.triu(adj, 1)
    adj = adj + adj.T
    feats = jnp.asarray(rng.normal(size=(e, 4)))
    support = chebyshev_support(jnp.asarray(adj), jnp.ones((e,), bool))
    dense_model = ChebNet(num_layer=3, hidden=8, k=3, param_dtype=jnp.float64)
    variables = dense_model.init(jax.random.PRNGKey(0), feats, support)
    expect = dense_model.apply(variables, feats, support)

    coo = dense_to_coo(np.asarray(support))
    sparse_model = ChebNet(num_layer=3, hidden=8, k=3,
                           param_dtype=jnp.float64, propagate=coo_propagate)
    got = jax.jit(lambda v, x, s: sparse_model.apply(v, x, s))(
        variables, feats, coo
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-12, atol=1e-12)


def test_coo_matmul_matches_dense():
    from multihop_offload_tpu.ops import coo_matmul, dense_to_coo

    rng = np.random.default_rng(7)
    m = rng.normal(size=(20, 20)) * (rng.uniform(size=(20, 20)) < 0.3)
    x = rng.normal(size=(20, 5))
    got = np.asarray(coo_matmul(dense_to_coo(m), jnp.asarray(x)))
    np.testing.assert_allclose(got, m @ x, rtol=1e-12, atol=1e-12)


def test_blocked_fw_matches_xla_beyond_squaring_cap():
    """Padded N > 256 must run the blocked Floyd-Warshall, not silently
    delegate to XLA (round-1 gap: `_MAX_KERNEL_N` silently fell back)."""
    from multihop_offload_tpu.ops.minplus import blocked_fw_call, pallas_apsp_path

    assert pallas_apsp_path(150, interpret=True) == "squaring"
    assert pallas_apsp_path(300, interpret=True) == "blocked-fw"
    assert pallas_apsp_path(1000, interpret=True) == "blocked-fw"
    assert pallas_apsp_path(3000, interpret=True) == "xla-fallback"
    # off-TPU without interpret the dispatcher must delegate to XLA
    assert pallas_apsp_path(150) == "xla-fallback"

    rng = np.random.default_rng(7)
    n = 300  # pads to 384 = 3 tiles
    w = _random_symmetric_weights(rng, n, p=4.0 / n)
    got = np.asarray(
        apsp_minplus_pallas(jnp.asarray(w, jnp.float32), interpret=True)
    )
    expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert (np.isinf(got) == np.isinf(expect)).all()
    assert (np.diag(got) == 0).all()


def test_auto_apsp_follows_measured_crossover():
    """`apsp_impl='auto'` must pick the fastest MEASURED implementation per
    shape (benchmarks/pallas_tpu.json round-5 re-ladder: XLA wins below
    padded N=256, chunked squaring at 256, blocked FW from 384) — not
    'pallas whenever on TPU' (the pre-crossover policy)."""
    from multihop_offload_tpu.ops.minplus import (
        apsp_minplus_auto, auto_apsp_path, resolve_apsp,
    )

    # below the crossover auto = XLA regardless of backend
    assert auto_apsp_path(110, interpret=True) == "xla"
    assert auto_apsp_path(256, interpret=True) == "squaring"
    assert auto_apsp_path(384, interpret=True) == "blocked-fw"
    assert auto_apsp_path(512, interpret=True) == "blocked-fw"
    assert auto_apsp_path(1000, interpret=True) == "blocked-fw"
    assert auto_apsp_path(3000, interpret=True) == "xla-fallback"

    # resolve_apsp('auto') returns the None sentinel (plain XLA APSP, no
    # wrapper overhead) below the crossover, the dispatching wrapper above
    fn, path = resolve_apsp("auto", 110)
    assert fn is None and path == "xla"
    fn, path = resolve_apsp("auto", 512, interpret=True)
    assert fn is not None and path == "blocked-fw"
    # 'pallas' still forces the kernel at small sizes (proof runs)
    _, path = resolve_apsp("pallas", 110, interpret=True)
    assert path == "squaring"

    # numerics through the auto wrapper: below the crossover (xla), at the
    # round-5 squaring boundary (256), the blocked-FW onset (384 — routed
    # to blocked-fw since the re-ladder), and well above (512)
    rng = np.random.default_rng(11)
    for n in (60, 256, 384, 512):
        w = _random_symmetric_weights(rng, n, p=4.0 / n)
        got = np.asarray(
            apsp_minplus_auto(jnp.asarray(w, jnp.float32), interpret=True)
        )
        expect = np.asarray(apsp_minplus(jnp.asarray(w, jnp.float32)))
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)


def test_blocked_fw_asymmetric_and_batched():
    """blocked_fw_call is exact FW — no symmetry assumption; batched."""
    from multihop_offload_tpu.ops.minplus import blocked_fw_call

    rng = np.random.default_rng(3)
    t = 8  # small tile keeps interpret-mode runtime down
    n = 4 * t
    d = rng.uniform(0.1, 5.0, (2, n, n)).astype(np.float32)
    mask = rng.uniform(size=(2, n, n)) < 0.4
    d = np.where(mask, d, np.inf).astype(np.float32)
    for b in range(2):
        np.fill_diagonal(d[b], 0.0)
    got = np.asarray(blocked_fw_call(jnp.asarray(d), tile=t, interpret=True))
    for b in range(2):
        e = d[b].copy()
        for k in range(n):
            e = np.minimum(e, e[:, k : k + 1] + e[k : k + 1, :])
        finite = np.isfinite(e)
        np.testing.assert_allclose(got[b][finite], e[finite], rtol=1e-6)
        assert (np.isinf(got[b]) == np.isinf(e)).all()


def test_fixed_point_off_tpu_fallback_matches_reference(small_cases, rng):
    """interpret=False off-TPU must delegate to the XLA reference (the
    dispatch contract shared with apsp_minplus_pallas) — values identical,
    and fixed_point_path reports the fallback honestly."""
    import numpy as np

    from multihop_offload_tpu.ops.fixed_point import (
        _xla_reference, fixed_point_pallas, fixed_point_path,
    )

    assert fixed_point_path() == "xla-fallback"  # suite runs on CPU
    l, b = 64, 3
    adj = (rng.random((b, l, l)) < 0.1).astype(np.float32)
    for i in range(b):
        adj[i] = np.maximum(adj[i], adj[i].T)
        np.fill_diagonal(adj[i], 0.0)
    rates = rng.uniform(30, 70, (b, l)).astype(np.float32)
    cf = adj.sum(-1).astype(np.float32)
    lam = rng.uniform(0, 5, (b, l)).astype(np.float32)
    out = fixed_point_pallas(adj, rates, cf, lam, 10, False)
    ref = _xla_reference(adj, rates, cf, lam, 10)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_resolve_fixed_point_paths():
    """`fp_impl` knob resolution mirrors `resolve_apsp`: None is the sentinel
    for direct XLA execution (incl. off-TPU fallback and beyond the measured
    crossover); interpret mode yields a real Pallas callable."""
    from multihop_offload_tpu.ops.fixed_point import resolve_fixed_point

    fn, path = resolve_fixed_point("xla", 256)
    assert fn is None and path == "xla"
    # beyond the in-step-measured win (L=256): direct XLA — L=384/512 have
    # no in-step A/B and the 384 microbench rung loses, so 'auto' stops at
    # the evidence; fp_impl='pallas' is the explicit override there
    fn, path = resolve_fixed_point("auto", 640)
    assert fn is None and path == "xla"
    fn, path = resolve_fixed_point("auto", 512)
    assert fn is None and path == "xla"
    # L=256 is the measured 1.16x in-step win; off-TPU it still resolves
    # to the honest fallback path
    fn, path = resolve_fixed_point("auto", 256)
    assert fn is None and path == "xla-fallback"
    # inside the measured win but suite runs on CPU: direct XLA, honest path
    fn, path = resolve_fixed_point("auto", 200)
    assert fn is None and path == "xla-fallback"
    fn, path = resolve_fixed_point("auto", 200, interpret=True)
    assert fn is not None and path == "pallas"
    import pytest

    with pytest.raises(ValueError):
        resolve_fixed_point("bogus", 128)


def test_forward_backward_invariant_to_fp_impl():
    """Training math must be invariant to the fixed-point kernel choice:
    `fp_fn` (interpret-mode Pallas, custom_vjp) == default XLA scan for
    values AND parameter gradients."""
    import jax

    from multihop_offload_tpu.agent.train_step import forward_backward
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.graphs import generators
    from multihop_offload_tpu.graphs.instance import (
        PadSpec, build_instance, build_jobset,
    )
    from multihop_offload_tpu.graphs.topology import (
        build_topology, sample_link_rates,
    )
    from multihop_offload_tpu.models import make_model
    from multihop_offload_tpu.ops.fixed_point import resolve_fixed_point

    rng = np.random.default_rng(7)
    adj, _ = generators.generate("er", 24, seed=8)
    topo = build_topology(adj)
    roles = np.zeros(24, dtype=np.int32)
    roles[[2, 9]] = 1
    bws = np.where(roles == 1, 80.0, 4.0)
    rates = sample_link_rates(topo, 50.0, rng=rng)
    pad = PadSpec(n=24, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(topo, roles, bws, rates, 1000.0, pad, dtype=np.float64)
    mobile = np.flatnonzero(roles == 0)
    jobs = build_jobset(mobile[:6], 0.15 * rng.uniform(0.1, 0.5, 6), pad_jobs=8,
                        dtype=np.float64)

    cfg = Config(dtype="float64")
    model = make_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((pad.e, 4), jnp.float64), inst.adj_ext
    )
    key = jax.random.PRNGKey(4)
    fp_fn, path = resolve_fixed_point("pallas", pad.l, interpret=True)
    assert path == "pallas"
    out_xla = forward_backward(model, variables, inst, jobs, key)
    out_pl = forward_backward(model, variables, inst, jobs, key, fp_fn=fp_fn)
    np.testing.assert_array_equal(np.asarray(out_xla.dst), np.asarray(out_pl.dst))
    np.testing.assert_allclose(
        np.asarray(out_xla.delays.job_total), np.asarray(out_pl.delays.job_total),
        rtol=1e-9,
    )
    flat_x = jax.tree_util.tree_leaves(out_xla.grads)
    flat_p = jax.tree_util.tree_leaves(out_pl.grads)
    for gx, gp in zip(flat_x, flat_p):
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gp),
                                   rtol=1e-7, atol=1e-10)


def test_fixed_point_pallas_under_vmap():
    """The bench/driver A/B vmaps forward_backward over episodes with
    `fp_fn` bound — i.e. jax.vmap over the custom_vjp-wrapped pallas_call.
    Exercise exactly that composition (values + grads) in interpret mode."""
    import jax

    from multihop_offload_tpu.ops import fixed_point_pallas

    rng = np.random.default_rng(5)
    l, b = 32, 4
    adj = (rng.random((b, l, l)) < 0.2).astype(np.float32)
    for i in range(b):
        adj[i] = np.maximum(adj[i], adj[i].T)
        np.fill_diagonal(adj[i], 0.0)
    rates = rng.uniform(30, 70, (b, l)).astype(np.float32)
    cf = adj.sum(-1).astype(np.float32)
    lam = rng.uniform(0, 5, (b, l)).astype(np.float32)

    one = lambda a, r, c, m: fixed_point_pallas(a, r, c, m, 10, True)
    got = jax.vmap(one)(*map(jnp.asarray, (adj, rates, cf, lam)))
    want = _fp_xla(adj, rates, cf, lam)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    def loss(ms):
        return jnp.sum(jax.vmap(one)(jnp.asarray(adj), jnp.asarray(rates),
                                     jnp.asarray(cf), ms) ** 2)

    g = jax.grad(loss)(jnp.asarray(lam))
    g_ref = jax.grad(
        lambda ms: jnp.sum(_fp_xla(adj, rates, cf, ms) ** 2)
    )(jnp.asarray(lam))
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-8)

"""ChebConv/ChebNet numerics, support construction, TF checkpoint interop."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.config import Config
from multihop_offload_tpu.models import (
    ChebConv,
    ChebNet,
    chebyshev_support,
    load_reference_checkpoint,
    make_model,
)
from multihop_offload_tpu.models.tf_import import save_reference_checkpoint

from tests.conftest import REFERENCE_CKPT

_JAX_VERSION = tuple(int(x) for x in jax.__version__.split(".")[:2])


def _needs_ckpt(path):
    """Checkpoint-interop tests require the shipped TF checkpoints, which
    only exist on hosts with the reference tree mounted."""
    return pytest.mark.skipif(
        not os.path.isdir(path),
        reason=f"reference TF checkpoint not present: {path}",
    )


# The 8-seed dead-init probe is calibrated against the init PRNG stream of
# jax >= 0.5 (>= 2 of 8 fresh inits emit all-zero lambda); older jax draws a
# different stream where the pathology appears in only 1 of the 8 seeds, so
# the `revived >= 2` floor cannot be met even though the revival mechanism
# itself is exercised (the single dead seed IS revived).
_needs_calibrated_init_prng = pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason="dead-init frequency calibrated for jax>=0.5 init PRNG stream; "
    f"jax {jax.__version__} yields <2 dead seeds in the 8-seed probe",
)


def _leaky(x, a=0.2):
    return np.where(x > 0, x, a * x)


def test_chebconv_k1_is_pointwise_mlp(rng):
    x = rng.normal(size=(10, 4))
    a = rng.normal(size=(10, 10))
    layer = ChebConv(channels=3, k=1, param_dtype=jnp.float64)
    params = layer.init(jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(a))
    out = layer.apply(params, jnp.asarray(x), jnp.asarray(a))
    w = np.asarray(params["params"]["kernel"])[0]
    b = np.asarray(params["params"]["bias"])
    np.testing.assert_allclose(np.asarray(out), x @ w + b, rtol=1e-12)
    # adjacency is provably unused at K=1
    out2 = layer.apply(params, jnp.asarray(x), jnp.zeros((10, 10)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2))


def test_chebconv_k3_matches_numpy_recursion(rng):
    x = rng.normal(size=(8, 5))
    a = rng.normal(size=(8, 8))
    a = (a + a.T) / 2
    layer = ChebConv(channels=2, k=3, param_dtype=jnp.float64)
    params = layer.init(jax.random.PRNGKey(1), jnp.asarray(x), jnp.asarray(a))
    out = np.asarray(layer.apply(params, jnp.asarray(x), jnp.asarray(a)))
    w = np.asarray(params["params"]["kernel"])
    b = np.asarray(params["params"]["bias"])
    t0, t1 = x, a @ x
    t2 = 2 * a @ t1 - t0
    expect = t0 @ w[0] + t1 @ w[1] + t2 @ w[2] + b
    np.testing.assert_allclose(out, expect, rtol=1e-10)


def test_chebyshev_support_properties(rng):
    adj = (rng.uniform(size=(12, 12)) < 0.3).astype(np.float64)
    adj = np.maximum(adj, adj.T)
    np.fill_diagonal(adj, 0)
    adj[-3:, :] = adj[:, -3:] = 0  # padded region
    mask = np.ones(12, bool)
    mask[-3:] = False
    s = np.asarray(chebyshev_support(jnp.asarray(adj), jnp.asarray(mask), lmax=2.0))
    # padded rows/cols stay zero
    assert np.abs(s[-3:, :]).sum() == 0 and np.abs(s[:, -3:]).sum() == 0
    assert np.allclose(s, s.T)
    # compat mode is the identity on the input
    raw = chebyshev_support(jnp.asarray(adj), compat_raw=True)
    np.testing.assert_array_equal(np.asarray(raw), adj)
    # power-iteration lmax runs and gives a finite support
    s2 = np.asarray(chebyshev_support(jnp.asarray(adj), jnp.asarray(mask), lmax=None))
    assert np.isfinite(s2).all()


def test_chebnet_forward_matches_manual_stack(rng):
    cfg = Config(dtype="float64", cheb_k=1)
    model = make_model(cfg)
    x = rng.normal(size=(20, 4))
    a = np.zeros((20, 20))
    params = model.init(jax.random.PRNGKey(2), jnp.asarray(x), jnp.asarray(a))
    out = np.asarray(model.apply(params, jnp.asarray(x), jnp.asarray(a)))
    h = x
    for i in range(5):
        w = np.asarray(params["params"][f"cheb_{i}"]["kernel"])[0]
        b = np.asarray(params["params"][f"cheb_{i}"]["bias"])
        h = h @ w + b
        h = np.maximum(h, 0) if i == 4 else _leaky(h)
    np.testing.assert_allclose(out, h, rtol=1e-10)
    assert out.shape == (20, 1)


@pytest.mark.parametrize("ckpt", [
    pytest.param(c, marks=_needs_ckpt(c)) for c in (
        REFERENCE_CKPT,                                       # BAT800 (T=800)
        REFERENCE_CKPT.replace("BAT800", "BAT950"),           # BAT950 (T=950)
    )
])
def test_import_reference_checkpoint(ckpt):
    """BOTH shipped reference checkpoints import (`/root/reference/model/`,
    SURVEY.md §2 #10)."""
    variables = load_reference_checkpoint(ckpt, dtype=np.float64)
    p = variables["params"]
    assert sorted(p.keys()) == [f"cheb_{i}" for i in range(5)]
    assert p["cheb_0"]["kernel"].shape == (1, 4, 32)
    assert p["cheb_4"]["kernel"].shape == (1, 32, 1)
    n_params = sum(np.prod(v.shape) for lay in p.values() for v in lay.values())
    assert n_params == 3361  # BASELINE.md model of record
    # the imported tree drives our model directly
    model = ChebNet(param_dtype=jnp.float64)
    out = model.apply(variables, jnp.ones((7, 4)), jnp.zeros((7, 7)))
    assert out.shape == (7, 1) and np.isfinite(np.asarray(out)).all()
    # K=1: every row of identical features maps to the same lambda
    assert np.allclose(np.asarray(out), np.asarray(out)[0])


@_needs_ckpt(REFERENCE_CKPT)
def test_checkpoint_export_roundtrip(tmp_path):
    variables = load_reference_checkpoint(REFERENCE_CKPT, dtype=np.float64)
    path = str(tmp_path / "export.ckpt")
    save_reference_checkpoint(path, variables)
    back = load_reference_checkpoint(path, dtype=np.float64)
    for i in range(5):
        np.testing.assert_array_equal(
            back["params"][f"cheb_{i}"]["kernel"],
            variables["params"][f"cheb_{i}"]["kernel"],
        )


@_needs_calibrated_init_prng
def test_ensure_alive_output_revives_dead_init():
    """~Half of fresh inits emit lambda == 0 everywhere (dead final relu,
    zero grads forever); the data-dependent sign flip must revive them
    without changing the init distribution's support."""
    import jax
    import jax.numpy as jnp
    from multihop_offload_tpu.models import ChebNet
    from multihop_offload_tpu.models.chebconv import ensure_alive_output

    rng = np.random.default_rng(0)
    feats = np.zeros((64, 4), np.float32)
    feats[:, 0] = rng.integers(0, 2, 64)
    feats[:, 1] = rng.uniform(20, 100, 64)
    feats[:, 2] = rng.uniform(0, 8, 64)
    feats[:, 3] = rng.integers(0, 2, 64)
    feats = jnp.asarray(feats)
    sup = jnp.zeros((64, 64), jnp.float32)
    model = ChebNet(param_dtype=jnp.float32)
    revived = 0
    for seed in range(8):
        vs = model.init(jax.random.PRNGKey(seed), feats, sup)
        dead = not bool((model.apply(vs, feats, sup) > 0).any())
        fixed = ensure_alive_output(model, vs, feats, sup)
        lam = model.apply(fixed, feats, sup)
        assert bool((lam > 0).any()), f"seed {seed} still dead"
        if dead:
            revived += 1
            # untouched layers identical; final layer exactly negated
            for i in range(4):
                np.testing.assert_array_equal(
                    np.asarray(vs["params"][f"cheb_{i}"]["kernel"]),
                    np.asarray(fixed["params"][f"cheb_{i}"]["kernel"]),
                )
            np.testing.assert_array_equal(
                -np.asarray(vs["params"]["cheb_4"]["kernel"]),
                np.asarray(fixed["params"]["cheb_4"]["kernel"]),
            )
    assert revived >= 2  # the pathology is common enough to matter


@_needs_calibrated_init_prng
def test_ensure_alive_output_not_fooled_by_padded_slots():
    """Padded slots have all-zero features so their output is
    relu(out_bias) > 0; the probe must ignore them or a dead init slips
    through (observed: 2000 file-steps of training with all-zero grads)."""
    import jax
    import jax.numpy as jnp
    from multihop_offload_tpu.models import ChebNet
    from multihop_offload_tpu.models.chebconv import ensure_alive_output

    rng = np.random.default_rng(0)
    e, real = 64, 40
    feats = np.zeros((e, 4), np.float32)
    feats[:real, 0] = rng.integers(0, 2, real)
    feats[:real, 1] = rng.uniform(20, 100, real)
    feats[:real, 2] = rng.uniform(0, 8, real)
    feats[:real, 3] = rng.integers(0, 2, real)
    feats = jnp.asarray(feats)
    sup = jnp.zeros((e, e), jnp.float32)
    mask = jnp.arange(e) < real
    model = ChebNet(param_dtype=jnp.float32)
    flipped = 0
    for seed in range(8):
        vs = model.init(jax.random.PRNGKey(seed), feats, sup)
        lam = model.apply(vs, feats, sup)[:, 0]
        dead_real = not bool(((lam > 0) & mask).any())
        fixed = ensure_alive_output(model, vs, feats, sup, mask=mask)
        lam2 = model.apply(fixed, feats, sup)[:, 0]
        assert bool(((lam2 > 0) & mask).any()), f"seed {seed} still dead"
        if dead_real:
            # unmasked probe would NOT have flipped (padded slots alive)
            assert bool((lam > 0).any())
            flipped += 1
    assert flipped >= 2

"""Env kernels vs the NumPy oracle on real reference cases."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from multihop_offload_tpu.env import (
    apsp_minplus,
    baseline_policy,
    baseline_unit_delays,
    evaluate_spmatrix_policy,
    hop_matrix,
    interference_fixed_point,
    local_policy,
    next_hop_table,
    trace_routes,
    weight_matrix_from_link_delays,
)
from multihop_offload_tpu.graphs.instance import PadSpec, build_instance, build_jobset
from multihop_offload_tpu.graphs.topology import sample_link_rates

from oracle import refenv


def _prep(rec, rng, t_max=1000.0):
    rates = sample_link_rates(rec.topo, rec.link_rates, rng=rng)
    pad = PadSpec.for_cases([rec.sizes], round_to=8)
    inst = build_instance(
        rec.topo, rec.roles, rec.proc_bws, rates, t_max, pad, dtype=np.float64
    )
    ca = refenv.case_arrays(rec, rates)
    return inst, ca, pad


def _sample_jobs(rec, rng, pad, scale=0.15):
    mobile = rng.permutation(rec.mobile_nodes)
    nj = rng.integers(max(int(0.3 * mobile.size), 1), mobile.size)
    srcs = mobile[:nj]
    rates = scale * rng.uniform(0.1, 0.5, nj)
    jobs_list = [
        {"src": int(s), "rate": float(r), "ul": 100.0, "dl": 1.0}
        for s, r in zip(srcs, rates)
    ]
    js = build_jobset(srcs, rates, pad_jobs=pad.j, dtype=np.float64)
    return jobs_list, js


def test_apsp_matches_dijkstra(small_cases, rng):
    rec = small_cases[0]
    inst, ca, _ = _prep(rec, rng)
    w_or, dlist, dproc = refenv.baseline_oracle(ca, 1000.0)
    n = rec.topo.n
    link_d, _ = baseline_unit_delays(inst)
    w = weight_matrix_from_link_delays(inst.adj, inst.link_index, link_d)
    sp = np.asarray(apsp_minplus(jnp.asarray(w)))
    sp_or = refenv.apsp_oracle(w_or)
    np.testing.assert_allclose(sp[:n, :n], sp_or, rtol=1e-12)
    # padded nodes unreachable
    assert np.isinf(sp[n:, :n]).all() if sp.shape[0] > n else True

    hop = np.asarray(hop_matrix(inst.adj))
    np.testing.assert_allclose(hop[:n, :n], refenv.hop_oracle(ca["adj"]), rtol=0)
    # the precomputed (host BFS) hop field must equal the device APSP result
    np.testing.assert_allclose(np.asarray(inst.hop), hop, rtol=0)


def test_next_hop_and_routes_match_oracle(small_cases, rng):
    rec = small_cases[0]
    inst, ca, pad = _prep(rec, rng)
    link_d, node_d = baseline_unit_delays(inst)
    w = weight_matrix_from_link_delays(inst.adj, inst.link_index, link_d)
    sp = apsp_minplus(w)
    nh = np.asarray(next_hop_table(inst.adj, sp))
    sp_np = np.asarray(sp)

    jobs_list, js = _sample_jobs(rec, rng, pad)
    servers = ca["servers"]
    # route every job to its nearest server via the oracle walker
    dsts = []
    for job in jobs_list:
        s = servers[np.argmin(sp_np[job["src"], servers])]
        dsts.append(int(s))
    dst_arr = np.zeros(pad.j, dtype=np.int32)
    dst_arr[: len(dsts)] = dsts
    dst_arr[len(dsts):] = js.src[len(dsts):]
    routes = trace_routes(inst, jnp.asarray(nh), js, jnp.asarray(dst_arr))

    for j, (job, dst) in enumerate(zip(jobs_list, dsts)):
        route, hops = refenv.greedy_route(ca["adj"], sp_np, job["src"], dst)
        assert int(routes.nhop[j]) == hops
        inc = np.asarray(routes.inc_ext[:, j])
        expect = np.zeros(pad.e)
        for a, b in zip(route[:-1], route[1:]):
            expect[ca["link_index"][a, b]] += 1
        expect[pad.l + dst] += 1
        np.testing.assert_array_equal(inc, expect)
    # padded job columns empty
    assert np.asarray(routes.inc_ext[:, len(dsts):]).sum() == 0


def test_fixed_point_matches_oracle(small_cases, rng):
    rec = small_cases[0]
    inst, ca, pad = _prep(rec, rng)
    lam = np.zeros(pad.l)
    lam[: rec.topo.num_links] = rng.uniform(0, 30, rec.topo.num_links)
    mu = np.asarray(interference_fixed_point(inst, jnp.asarray(lam)))
    mu_or = refenv.fixed_point_oracle(
        ca["link_rates"], ca["cf_degs"], ca["adj_conflict"], lam[: rec.topo.num_links]
    )
    np.testing.assert_allclose(mu[: rec.topo.num_links], mu_or, rtol=1e-12)


@pytest.mark.parametrize("case_idx,scale", [(0, 0.15), (1, 0.5), (2, 0.15)])
def test_baseline_policy_end_to_end(small_cases, case_idx, scale):
    """Full baseline method vs a pure-oracle pipeline, incl. congestion."""
    rng = np.random.default_rng(100 + case_idx)
    rec = small_cases[case_idx % len(small_cases)]
    inst, ca, pad = _prep(rec, rng)
    jobs_list, js = _sample_jobs(rec, rng, pad, scale=scale)

    out = baseline_policy(inst, js, jax.random.PRNGKey(0), explore=0.0)

    # oracle pipeline
    w_or, dlist, dproc = refenv.baseline_oracle(ca, 1000.0)
    sp_or = refenv.apsp_oracle(w_or)
    hop_or = refenv.hop_oracle(ca["adj"])
    dec = refenv.offload_oracle(ca, jobs_list, dproc, sp_or, hop_or)
    res = refenv.run_oracle(ca, jobs_list, dec, 1000.0)

    nj = len(jobs_list)
    got = np.asarray(out.delays.job_total[:nj])
    np.testing.assert_allclose(got, res["total"], rtol=1e-9)
    np.testing.assert_allclose(
        np.asarray(out.decision.dst[:nj]), [d["dst"] for d in dec]
    )
    np.testing.assert_allclose(
        np.asarray(out.decision.delay_est[:nj]), [d["est"] for d in dec], rtol=1e-9
    )
    # aggregates
    L = rec.topo.num_links
    np.testing.assert_allclose(
        np.asarray(out.delays.link_lambda[:L]), res["link_lambda"], rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(out.delays.link_mu[:L]), res["link_mu"], rtol=1e-12
    )
    # unit matrix + mask vs NaN-matrix oracle
    n = rec.topo.n
    um = np.asarray(out.delays.unit_matrix)[:n, :n]
    mk = np.asarray(out.delays.unit_mask)[:n, :n]
    assert (mk == ~np.isnan(res["unit_mtx"])).all()
    np.testing.assert_allclose(um[mk], res["unit_mtx"][mk], rtol=1e-9)


def test_local_policy_matches_oracle(small_cases):
    rng = np.random.default_rng(7)
    rec = small_cases[0]
    inst, ca, pad = _prep(rec, rng)
    jobs_list, js = _sample_jobs(rec, rng, pad)
    out = local_policy(inst, js)

    with np.errstate(divide="ignore"):
        dproc = 1.0 / ca["proc_bws"]
    flows = [
        {"dst": job["src"], "route": [job["src"], job["src"]], "nhop": 0}
        for job in jobs_list
    ]
    res = refenv.run_oracle(ca, jobs_list, flows, 1000.0)
    nj = len(jobs_list)
    np.testing.assert_allclose(
        np.asarray(out.delays.job_total[:nj]), res["total"], rtol=1e-9
    )
    est = np.asarray(out.decision.delay_est[:nj])
    np.testing.assert_allclose(
        est, [max(dproc[j["src"]] * j["ul"], 1.0) for j in jobs_list], rtol=1e-12
    )


def test_explore_and_prob_paths_run(small_cases):
    rng = np.random.default_rng(3)
    rec = small_cases[0]
    inst, ca, pad = _prep(rec, rng)
    _, js = _sample_jobs(rec, rng, pad)
    link_d, node_d = baseline_unit_delays(inst)
    out_e = evaluate_spmatrix_policy(
        inst, js, link_d, node_d, jax.random.PRNGKey(1), explore=1.0
    )
    # exploration must still pick valid compute nodes (servers or the source)
    dst = np.asarray(out_e.decision.dst)[np.asarray(js.mask)]
    ok = np.isin(dst, ca["servers"]) | (dst == np.asarray(js.src)[np.asarray(js.mask)])
    assert ok.all()
    out_p = evaluate_spmatrix_policy(
        inst, js, link_d, node_d, jax.random.PRNGKey(2), prob=True
    )
    assert np.isfinite(np.asarray(out_p.delays.job_total)[np.asarray(js.mask)]).all()


def test_vmap_batch_consistency(small_cases):
    """vmap over stacked instances == per-instance evaluation."""
    rng = np.random.default_rng(11)
    recs = [small_cases[0], small_cases[1]]
    pad = PadSpec.for_cases([r.sizes for r in recs], round_to=8)
    insts, jss = [], []
    for rec in recs:
        rates = sample_link_rates(rec.topo, rec.link_rates, rng=rng)
        insts.append(
            build_instance(rec.topo, rec.roles, rec.proc_bws, rates, 1000.0, pad,
                           dtype=np.float64)
        )
        _, js = _sample_jobs(rec, rng, pad)
        jss.append(js)
    from multihop_offload_tpu.graphs.instance import stack_instances

    binst = stack_instances(insts)
    bjobs = stack_instances(jss)
    keys = jax.random.split(jax.random.PRNGKey(0), 2)
    bout = jax.vmap(lambda i, j, k: baseline_policy(i, j, k))(binst, bjobs, keys)
    for b in range(2):
        single = baseline_policy(insts[b], jss[b], keys[b])
        np.testing.assert_allclose(
            np.asarray(bout.delays.job_total[b]),
            np.asarray(single.delays.job_total),
            rtol=1e-12,
        )


def test_local_greedy_mwis_matches_reference_algorithm():
    """Set-for-set equality with a direct NumPy port of the reference's
    `local_greedy_search` (`util.py:12-51`), ties included."""
    import jax.numpy as jnp

    from multihop_offload_tpu.env import local_greedy_mwis

    def oracle(adj, wts):
        wts = np.asarray(wts, dtype=float)
        mwis, remain, nb_is = set(), set(range(wts.size)), set()
        while remain:
            for v in sorted(remain):
                nb_set = set(np.flatnonzero(adj[v])) & remain
                if not nb_set:
                    mwis.add(v)
                    continue
                nb_list = sorted(nb_set)
                wts_nb = wts[nb_list]
                w_bar = wts_nb.max()
                if wts[v] > w_bar:
                    mwis.add(v)
                    nb_is |= nb_set
                elif wts[v] == w_bar:
                    nbv = nb_list[list(wts_nb).index(wts[v])]
                    if v < nbv:
                        mwis.add(v)
                        nb_is |= nb_set
            remain = remain - mwis - nb_is
        return mwis, wts[sorted(mwis)].sum()

    rng = np.random.default_rng(11)
    for trial in range(8):
        n = int(rng.integers(5, 40))
        adj = (rng.uniform(size=(n, n)) < 0.2).astype(np.float64)
        adj = np.triu(adj, 1)
        adj = adj + adj.T
        # integer weights force ties through the tie-break branch
        wts = rng.integers(1, 6, n).astype(np.float64)
        exp_set, exp_total = oracle(adj, wts)
        got_mask, got_total = local_greedy_mwis(jnp.asarray(adj), jnp.asarray(wts))
        got_set = set(np.flatnonzero(np.asarray(got_mask)))
        assert got_set == exp_set, (trial, got_set, exp_set)
        assert float(got_total) == exp_total
        # independence
        assert not any(adj[u, v] for u in got_set for v in got_set if u != v)


def test_local_greedy_mwis_respects_mask():
    import jax.numpy as jnp

    from multihop_offload_tpu.env import local_greedy_mwis

    adj = np.zeros((4, 4))
    adj[0, 1] = adj[1, 0] = 1.0
    wts = np.array([5.0, 9.0, 3.0, 7.0])
    mask = np.array([True, True, True, False])
    got, total = local_greedy_mwis(jnp.asarray(adj), jnp.asarray(wts),
                                   jnp.asarray(mask))
    assert set(np.flatnonzero(np.asarray(got))) == {1, 2}
    assert float(total) == 12.0


def test_apsp_early_stop_equals_static_schedule(rng):
    """The while_loop early exit must be value-identical to the full
    ceil(log2(N-1)) schedule (min-plus squaring is idempotent at the fixed
    point), including +inf disconnected entries, scalar and vmapped."""
    import functools

    import jax

    n, b = 48, 6
    w = rng.uniform(0.1, 5.0, (b, n, n)).astype(np.float32)
    w = np.minimum(w, w.transpose(0, 2, 1))
    mask = rng.uniform(size=(b, n, n)) < 0.06
    mask = mask | mask.transpose(0, 2, 1)
    w = np.where(mask, w, np.inf).astype(np.float32)
    wj = jnp.asarray(w)

    static = jax.jit(jax.vmap(functools.partial(apsp_minplus, early_stop=False)))
    early = jax.jit(jax.vmap(apsp_minplus))
    a, c = np.asarray(early(wj)), np.asarray(static(wj))
    assert (np.isinf(a) == np.isinf(c)).all()
    fin = np.isfinite(c)
    np.testing.assert_array_equal(a[fin], c[fin])
    # scalar path too
    np.testing.assert_array_equal(
        np.asarray(apsp_minplus(wj[0])), np.asarray(
            apsp_minplus(wj[0], early_stop=False))
    )

"""Sparse instance layouts: decision parity, padding inertness, compact ints.

Tier-1 (CPU) gate for the `cfg.layout` knob (ISSUE 7): the sparse layout's
decision path — scatter-built weight matrix, k-blocked min-plus APSP,
segment-min next hop — is BIT-IDENTICAL to the dense parity reference, so
offload-decision agreement is pinned at exactly 1.0 (not a floor), per-method
job totals agree to summation-order noise, the pad-to-static nnz bound is
inert, and the compact int16 storage round-trips exactly.  The committed
gate (`benchmarks/layout_ab.json`, scripts/layout_ab.py) uses the same
thresholds over more seeds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multihop_offload_tpu.env.apsp import (
    apsp_minplus,
    apsp_minplus_blocked,
    next_hop_table,
    weight_matrix_from_link_delays,
)
from multihop_offload_tpu.env.policies import baseline_policy, local_policy
from multihop_offload_tpu.env.routing import trace_routes
from multihop_offload_tpu.graphs import generators
from multihop_offload_tpu.graphs.instance import PadSpec, build_jobset
from multihop_offload_tpu.graphs.topology import build_topology
from multihop_offload_tpu.layouts import (
    LayoutPolicy,
    make_sparse_propagate,
    next_hop_from_edges,
    pack_next_hop,
    resolve_layout,
    sparse_chebyshev_support,
    unpack_next_hop,
    weight_matrix_from_edges,
)
from multihop_offload_tpu.layouts.sparse import _coo_from_dense_np
from multihop_offload_tpu.models.chebconv import chebyshev_support
from multihop_offload_tpu.sim.fidelity import make_case

TAU_RTOL = 1e-4   # dense vs sparse mean job totals (summation-order noise
#                   in the gathered delay reductions; same fp32 ops)


def _case(seed, layout, dtype=np.float32, n_nodes=16, num_jobs=8):
    topo = build_topology(generators.barabasi_albert(n_nodes, seed=seed)[0])
    pad = PadSpec(n=16, l=-(-topo.num_links // 8) * 8, s=8, j=num_jobs)
    return make_case(seed, topo, pad, num_jobs, dtype=dtype, layout=layout)


# ---- policy resolution -----------------------------------------------------


def test_resolve_identity_dense():
    lay = resolve_layout("dense")
    assert not lay.sparse
    assert np.dtype(lay.index_dtype) == np.dtype(np.int32)
    # None means dense (the default until the layout_ab on-chip gates pass)
    assert not resolve_layout(None).sparse
    # resolving an already-resolved policy is idempotent
    assert resolve_layout(lay) is lay


def test_resolve_sparse_and_auto():
    lay = resolve_layout("sparse")
    assert lay.sparse
    # compact-storage satellite: sparse packs index vectors to int16
    assert np.dtype(lay.index_dtype) == np.dtype(np.int16)
    with pytest.raises(ValueError):
        resolve_layout("banana")
    # auto resolves by backend: sparse only on TPU (tier-1 runs on CPU)
    auto = resolve_layout("auto")
    assert auto.sparse == (jax.default_backend() == "tpu")


def test_policy_is_hashable_and_closable():
    # the build-time contract: the resolved policy is baked into jitted
    # closures, so it must hash and compare by value
    assert resolve_layout("sparse") == LayoutPolicy("sparse")
    assert hash(resolve_layout("dense")) == hash(LayoutPolicy("dense"))


# ---- decision-path builders: bit parity with the dense twins ---------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_weight_matrix_and_next_hop_bit_parity(seed):
    inst, _ = _case(seed, "sparse")
    rng = np.random.default_rng(seed)
    ld = jnp.asarray(
        rng.uniform(0.05, 2.0, inst.num_pad_links).astype(np.float32)
    )
    wd = weight_matrix_from_link_delays(inst.adj, inst.link_index, ld)
    ws = weight_matrix_from_edges(
        inst.link_ends, inst.link_mask, ld, inst.num_pad_nodes
    )
    both_inf = jnp.isinf(wd) & jnp.isinf(ws)
    assert bool(jnp.all((wd == ws) | both_inf))

    sp = apsp_minplus(wd)
    nhd = next_hop_table(inst.adj, sp)
    nhs = next_hop_from_edges(inst.link_ends, inst.link_mask, sp)
    assert bool(jnp.all(nhd == nhs))


@pytest.mark.parametrize("n", [13, 16, 24])
def test_apsp_blocked_bit_identical(n):
    # fp min is exact under any reduction order and the candidate sums are
    # the same ops, so blocking changes NOTHING — including non-divisible N
    # (the k axis pads with +inf, inert for nonnegative weights)
    rng = np.random.default_rng(n)
    adj = rng.random((n, n)) < 0.2
    adj = np.triu(adj, 1)
    adj = adj | adj.T
    w = np.where(adj, rng.random((n, n)) + 0.1, np.inf).astype(np.float32)
    w = np.minimum(w, w.T)
    a = apsp_minplus(jnp.asarray(w))
    b = apsp_minplus_blocked(jnp.asarray(w), block=8)
    assert bool(jnp.all((a == b) | (jnp.isinf(a) & jnp.isinf(b))))


# ---- offload decisions: agreement pinned at exactly 1.0 --------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_decision_agreement_exact(seed):
    key = jax.random.PRNGKey(seed)
    outs = {}
    for name in ("dense", "sparse"):
        inst, jobs = _case(seed, name)
        outs[name] = (
            baseline_policy(inst, jobs, key, layout=name),
            local_policy(inst, jobs, layout=name),
            jobs,
        )
    bd, ld_, jobs = outs["dense"]
    bs, ls, _ = outs["sparse"]
    m = np.asarray(jobs.mask)
    # the acceptance gate: dense and sparse must take the SAME decisions
    assert (np.asarray(bd.decision.dst)[m] == np.asarray(bs.decision.dst)[m]).all()
    for dout, sout in ((bd, bs), (ld_, ls)):
        td = float(np.asarray(dout.job_total, np.float64)[m].mean())
        ts = float(np.asarray(sout.job_total, np.float64)[m].mean())
        assert abs(ts - td) / td <= TAU_RTOL


def test_forward_backward_parity():
    # the tentpole train path: step-form critic + gathered reductions under
    # the sparse layout vs the dense incidence reference
    from multihop_offload_tpu.agent.actor import (
        build_ext_features,
        default_support,
    )
    from multihop_offload_tpu.agent.train_step import forward_backward
    from multihop_offload_tpu.config import Config
    from multihop_offload_tpu.models.chebconv import make_model

    cfg = Config()
    key = jax.random.PRNGKey(0)
    outs = {}
    for name in ("dense", "sparse"):
        inst, jobs = _case(5, name)
        model = make_model(cfg, layout=name)
        sup = default_support(model, inst, layout=name)
        vs = model.init(
            jax.random.PRNGKey(7), build_ext_features(inst, jobs), sup
        )
        outs[name] = forward_backward(
            model, vs, inst, jobs, key, support=sup, layout=name
        )
    d, s = outs["dense"], outs["sparse"]
    assert bool(jnp.all(d.dst == s.dst))
    assert jnp.allclose(d.loss_critic, s.loss_critic, rtol=1e-5)
    assert jnp.allclose(d.loss_mse, s.loss_mse, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(d.grads), jax.tree_util.tree_leaves(s.grads)
    ):
        assert jnp.allclose(a, b, rtol=1e-4, atol=1e-6)


# ---- E_max padding: the nnz bound is inert ---------------------------------


def test_nnz_padding_inert():
    inst, _ = _case(0, "sparse")
    adj_ext = np.asarray(inst.adj_ext)
    nnz = int(np.count_nonzero(adj_ext))
    pad_a = -(-nnz // 128) * 128
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((adj_ext.shape[0], 4)).astype(np.float32)
    )
    prop = make_sparse_propagate()
    outs = []
    for nnz_pad in (pad_a, pad_a + 128):
        coo = _coo_from_dense_np(adj_ext, nnz_pad, np.float32)
        sup = sparse_chebyshev_support(coo, mask=inst.ext_mask)
        outs.append(prop(sup, x))
    # padded entries carry value 0 at slot (0, 0): they add exact zeros to
    # one segment, so a bigger bound changes no bit of the output
    assert bool(jnp.all(outs[0] == outs[1]))


def test_nnz_overflow_raises():
    inst, _ = _case(0, "sparse")
    adj_ext = np.asarray(inst.adj_ext)
    nnz = int(np.count_nonzero(adj_ext))
    with pytest.raises(ValueError, match="nnz pad"):
        _coo_from_dense_np(adj_ext, nnz - 1, np.float32)


def test_sparse_support_matches_dense():
    inst, _ = _case(1, "sparse")
    dense_sup = chebyshev_support(inst.adj_ext, mask=inst.ext_mask)
    sup = sparse_chebyshev_support(inst.sparse.ext, mask=inst.ext_mask)
    rng = np.random.default_rng(1)
    x = jnp.asarray(
        rng.standard_normal((inst.adj_ext.shape[0], 3)).astype(np.float32)
    )
    dense_out = dense_sup @ x
    sparse_out = make_sparse_propagate()(sup, x)
    assert jnp.allclose(dense_out, sparse_out, rtol=1e-5, atol=1e-6)


# ---- compact integer storage -----------------------------------------------


def test_int16_next_hop_round_trip():
    rng = np.random.default_rng(3)
    nh = jnp.asarray(rng.integers(0, 300, (300, 300)).astype(np.int32))
    packed = pack_next_hop(nh)
    assert packed.dtype == jnp.int16
    back = unpack_next_hop(packed)
    assert back.dtype == jnp.int32
    assert bool(jnp.all(back == nh))


def test_int16_jobs_trace_identically():
    inst, _ = _case(2, "sparse")
    rng = np.random.default_rng(2)
    srcs = rng.choice(np.arange(4, 14), size=6, replace=False)
    rates = rng.uniform(0.5, 1.0, 6)
    routes = {}
    for idt in (np.int32, np.int16):
        jobs = build_jobset(srcs, rates, pad_jobs=8, index_dtype=idt)
        assert np.dtype(jobs.src.dtype) == np.dtype(idt)
        w = weight_matrix_from_edges(
            inst.link_ends, inst.link_mask,
            jnp.ones((inst.num_pad_links,), jnp.float32), inst.num_pad_nodes,
        )
        nh = next_hop_from_edges(
            inst.link_ends, inst.link_mask, apsp_minplus_blocked(w)
        )
        dst = jnp.zeros((jobs.src.shape[0],), jnp.int32)  # all offload to 0
        routes[idt] = trace_routes(inst, nh, jobs, dst)
    assert bool(jnp.all(routes[np.int32].seq_slot == routes[np.int16].seq_slot))
    assert bool(
        jnp.all(routes[np.int32].seq_active == routes[np.int16].seq_active)
    )
    assert bool(jnp.all(routes[np.int32].nhop == routes[np.int16].nhop))


# ---- build-time resolution: the knob never retraces ------------------------


def test_layout_knob_no_retrace():
    lay = resolve_layout("sparse")

    @jax.jit
    def decide(inst, jobs, key):
        return baseline_policy(inst, jobs, key, layout=lay).decision.dst

    key = jax.random.PRNGKey(0)
    for seed in (0, 1, 2):
        inst, jobs = _case(seed, lay)
        decide(inst, jobs, key)
    # same shapes, different data: one trace total — the policy is baked in
    # at build time, never read inside the traced program
    assert decide._cache_size() == 1

"""Mesh sharding on the virtual 8-device CPU: ring APSP, DP steps, dryrun."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from multihop_offload_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

from multihop_offload_tpu.agent import make_optimizer, replay_init
from multihop_offload_tpu.config import Config
from multihop_offload_tpu.env.apsp import apsp_minplus
from multihop_offload_tpu.models import ChebNet
from multihop_offload_tpu.parallel import (
    make_dp_eval_step,
    make_dp_train_step,
    make_mesh,
    sharded_apsp,
)

import __graft_entry__ as graft


def test_devices_available():
    assert len(jax.devices()) == 8


def test_make_mesh_tolerates_non_factoring_device_counts():
    """A grid that does not fit the fleet degrades to a 1-D data axis over
    every device with a warning — never raises (a serving config moved
    between hosts, or a chip lost mid-run, keeps a working mesh)."""
    import warnings

    devs = jax.devices()[:3]
    with pytest.warns(RuntimeWarning, match="falling back"):
        mesh = make_mesh(data=2, graph=2, devices=devs)
    assert mesh.shape == {"data": 3, "graph": 1}
    # an oversized graph axis degrades the same way
    with pytest.warns(RuntimeWarning, match="falling back"):
        mesh = make_mesh(graph=16)
    assert mesh.shape == {"data": 8, "graph": 1}
    # fitting grids stay exact and warning-free
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        mesh = make_mesh(data=2, graph=2, devices=jax.devices()[:4])
    assert mesh.shape == {"data": 2, "graph": 2}


def test_ring_apsp_matches_dense():
    rng = np.random.default_rng(0)
    n = 64
    w = np.full((n, n), np.inf)
    iu, ju = np.where(np.triu(rng.uniform(size=(n, n)) < 0.08, 1))
    w[iu, ju] = w[ju, iu] = rng.uniform(0.5, 3.0, iu.size)
    mesh = make_mesh(data=1, graph=8)
    f = jax.jit(
        shard_map(
            lambda x: sharded_apsp(x, "graph"),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
        )
    )
    got = np.asarray(f(jnp.asarray(w)))
    expect = np.asarray(apsp_minplus(jnp.asarray(w)))
    finite = np.isfinite(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-12)
    assert (np.isinf(got) == np.isinf(expect)).all()


@pytest.fixture(scope="module")
def dp_setup():
    binst, bjobs, pad = graft._make_batch(
        num_cases=4, n_nodes=24, pad_round=16, dtype=np.float64, seed=7
    )
    model = ChebNet(num_layer=3, hidden=8, param_dtype=jnp.float64)
    feats0 = jnp.zeros((pad.e, 4), jnp.float64)
    support0 = jnp.zeros((pad.e, pad.e), jnp.float64)
    variables = model.init(jax.random.PRNGKey(0), feats0, support0)
    keys = jax.random.split(jax.random.PRNGKey(1), 4)
    return binst, bjobs, model, variables, keys


def test_dp_mean_step_matches_single_device(dp_setup):
    """4-way DP with graph=2 ring APSP == single-device reference update."""
    binst, bjobs, model, variables, keys = dp_setup
    cfg = Config(learning_rate=1e-4)
    opt = make_optimizer(cfg)
    opt_state = opt.init(variables["params"])

    mesh = make_mesh(data=4, graph=2)
    step = make_dp_train_step(model, opt, mesh, mode="mean")
    v_dp, _, metrics = step(
        variables, opt_state, binst, bjobs, keys, jnp.asarray(0.0, jnp.float64)
    )

    mesh1 = make_mesh(data=1, graph=1, devices=jax.devices()[:1])
    step1 = make_dp_train_step(model, opt, mesh1, mode="mean")
    v_1, _, metrics1 = step1(
        variables, opt_state, binst, bjobs, keys, jnp.asarray(0.0, jnp.float64)
    )

    f_dp, _ = jax.flatten_util.ravel_pytree(v_dp["params"])
    f_1, _ = jax.flatten_util.ravel_pytree(v_1["params"])
    np.testing.assert_allclose(np.asarray(f_dp), np.asarray(f_1), rtol=1e-9)
    np.testing.assert_allclose(
        float(metrics["loss_critic"]), float(metrics1["loss_critic"]), rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(metrics["job_total"]), np.asarray(metrics1["job_total"]), rtol=1e-9
    )


def test_dp_replay_step_fills_memory(dp_setup):
    binst, bjobs, model, variables, keys = dp_setup
    cfg = Config(learning_rate=1e-4)
    opt = make_optimizer(cfg)
    mem = replay_init(variables["params"], capacity=16)
    mesh = make_mesh(data=4, graph=1)
    step = make_dp_train_step(model, opt, mesh, mode="replay")
    mem, metrics = step(
        variables, mem, binst, bjobs, keys, jnp.asarray(0.1, jnp.float64)
    )
    assert int(mem.count) == 4
    g0 = jax.tree_util.tree_map(lambda x: x[0], mem.grads)
    flat, _ = jax.flatten_util.ravel_pytree(g0)
    assert np.isfinite(np.asarray(flat)).all() and np.abs(np.asarray(flat)).sum() > 0


def test_dp_eval_step(dp_setup):
    binst, bjobs, model, variables, keys = dp_setup
    mesh = make_mesh(data=2, graph=2)
    step = make_dp_eval_step(model, mesh)
    totals = step(variables, binst, bjobs, keys)
    mask = np.asarray(bjobs.mask)
    assert np.isfinite(np.asarray(totals)[mask]).all()


def test_graft_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    variables, binst, bjobs, keys = args
    assert np.isfinite(np.asarray(out)[np.asarray(bjobs.mask)]).all()


def test_graft_dryrun_multichip():
    graft.dryrun_multichip(8)


def test_sharded_fixed_point_matches_dense():
    """Halo-exchange interference fixed point == the single-device one."""
    from multihop_offload_tpu.env.queueing import interference_fixed_point
    from multihop_offload_tpu.graphs.instance import PadSpec, build_instance
    from multihop_offload_tpu.graphs.topology import build_topology
    from multihop_offload_tpu.parallel import sharded_interference_fixed_point

    rng = np.random.default_rng(21)
    from multihop_offload_tpu.graphs import generators

    adj, _ = generators.generate("er", 40, seed=3)
    topo = build_topology(adj)
    roles = np.zeros(40, dtype=np.int32)
    roles[[1, 5]] = 1
    pad = PadSpec(n=40, l=PadSpec.round_up(topo.num_links, 8), s=8, j=8)
    inst = build_instance(
        topo, roles, np.full(40, 5.0), rng.uniform(30, 70, topo.num_links),
        1000.0, pad, dtype=np.float64,
    )
    lam = jnp.asarray(rng.uniform(0.0, 40.0, pad.l))

    expect = np.asarray(interference_fixed_point(inst, lam))

    mesh = make_mesh(data=1, graph=8)
    f = jax.jit(
        shard_map(
            lambda a, r, c, l: sharded_interference_fixed_point(
                a, r, c, l, "graph"
            ),
            mesh=mesh,
            in_specs=(P("graph", None), P("graph"), P("graph"), P("graph")),
            out_specs=P("graph"),
            check_vma=False,
        )
    )
    got = np.asarray(f(inst.adj_conflict, inst.link_rates, inst.cf_degs, lam))
    np.testing.assert_allclose(got, expect, rtol=1e-12)


def test_sharded_chebnet_matches_dense():
    """Halo-exchange Chebyshev propagation == dense apply, same params."""
    from multihop_offload_tpu.models.chebconv import chebyshev_support
    from multihop_offload_tpu.parallel import sharded_spectral_forward

    rng = np.random.default_rng(5)
    e = 64
    adj = (rng.uniform(size=(e, e)) < 0.15).astype(np.float64)
    adj = np.triu(adj, 1)
    adj = adj + adj.T
    feats = jnp.asarray(rng.normal(size=(e, 4)))
    support = chebyshev_support(jnp.asarray(adj), jnp.ones((e,), bool))
    model = ChebNet(num_layer=3, hidden=8, k=3, param_dtype=jnp.float64)
    variables = model.init(jax.random.PRNGKey(2), feats, support)

    expect = np.asarray(model.apply(variables, feats, support))

    mesh = make_mesh(data=1, graph=8)
    f = jax.jit(
        shard_map(
            lambda v, x, s: sharded_spectral_forward(model, v, x, s, "graph"),
            mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(f(variables, feats, support))
    np.testing.assert_allclose(got, expect, rtol=1e-12, atol=1e-12)


# ---------------------------------------------------------------------------
# serving-fleet resolution (cli.serve.resolve_serve_devices)
# ---------------------------------------------------------------------------


def test_resolve_serve_devices_mesh_clamps_with_warning():
    """serve_mesh larger than the fleet clamps to every present device and
    says so — a degraded-capacity start must be visible, not silent."""
    from multihop_offload_tpu.cli.serve import resolve_serve_devices

    with pytest.warns(RuntimeWarning, match="clamping"):
        devs = resolve_serve_devices(Config(serve_mesh=len(jax.devices()) + 5))
    assert devs == list(jax.devices())
    # in-range mesh takes the first N, no warning
    devs = resolve_serve_devices(Config(serve_mesh=2))
    assert devs == list(jax.devices())[:2]
    # mesh <= 1 means the single-device executor
    assert resolve_serve_devices(Config()) is None


def test_resolve_serve_devices_explicit_ids_win_and_missing_raise():
    """An explicit serve_devices id list overrides serve_mesh (order
    preserved), and ids not present fail loudly with the virtual-device
    hint instead of clamping."""
    from multihop_offload_tpu.cli.serve import resolve_serve_devices

    fleet = jax.devices()
    cfg = Config(serve_devices=f"{fleet[2].id},{fleet[0].id}",
                 serve_mesh=len(fleet) + 5)   # would clamp; ids must win
    out = resolve_serve_devices(cfg)
    assert [d.id for d in out] == [fleet[2].id, fleet[0].id]
    with pytest.raises(ValueError, match="not present"):
        resolve_serve_devices(Config(serve_devices="999999"))
    with pytest.raises(ValueError, match="int ids"):
        resolve_serve_devices(Config(serve_devices="0,x"))

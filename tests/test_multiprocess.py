"""REAL multi-process distributed bring-up (not virtual devices).

Two OS processes join via `parallel.mesh.init_distributed`'s explicit
coordinator path (the framework's NCCL/MPI-equivalent entry, SURVEY.md
§5.8), see each other's devices globally, and run a cross-process `psum`
over a 2-device ('data',) mesh — the DCN collective path the multi-host
Trainer rides.  Each child also checks `jax.process_index()` (the
host-0 write gating the drivers rely on).
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

_JAX_VERSION = tuple(int(x) for x in jax.__version__.split(".")[:2])

# The psum child imports the top-level `jax.shard_map` alias (jax >= 0.6);
# older jax only ships `jax.experimental.shard_map`.
_needs_toplevel_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason=f"jax {jax.__version__} has no top-level jax.shard_map",
)

# Cross-process collectives on the CPU backend (the children force
# jax_platforms=cpu) raise `XlaRuntimeError: Multiprocess computations
# aren't implemented on the CPU backend` before jax 0.5's DCN-over-gRPC
# CPU path; the test can only exercise the real multi-host wiring there.
_needs_cpu_multiprocess = pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason=f"jax {jax.__version__} cannot run multiprocess computations "
    "on the CPU backend",
)

_CHILD = r'''
import os, sys
sys.path.insert(0, os.environ["MHO_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
from multihop_offload_tpu.parallel.mesh import init_distributed

pid = int(sys.argv[1])
idx = init_distributed(coordinator_address=os.environ["MHO_COORD"],
                       num_processes=2, process_id=pid)
assert idx == pid == jax.process_index(), (idx, pid, jax.process_index())

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

devs = jax.devices()
assert len(devs) == 2, f"expected 2 global devices, got {devs}"
mesh = Mesh(np.asarray(devs), ("data",))
f = jax.jit(shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                      in_specs=P(), out_specs=P(), check_vma=False))
out = float(f(jnp.asarray(float(pid + 1))))
assert out == 3.0, out  # 1 + 2 across processes
print(f"PROC {pid} OK psum={out}", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_children(child_src: str, xla_flags: str = "", timeout: int = 240):
    """Spawn 2 coordinator-joined children; return their outputs.

    On a hang (usually: the OTHER process died early and this one waits in
    initialize()/a collective) kill both and surface every captured output.
    """
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "MHO_REPO": repo,
           "MHO_COORD": f"127.0.0.1:{_free_port()}",
           # children must pick their own platform; scrub inherited forcing
           "JAX_PLATFORMS": "",
           "XLA_FLAGS": xla_flags}
    procs = [
        subprocess.Popen([sys.executable, "-c", child_src, str(i)], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)
    ]
    outs = ["", ""]
    try:
        for i, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=timeout)
                outs[i] = out.decode()
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                for j, q in enumerate(procs):
                    out, _ = q.communicate()
                    outs[j] = outs[j] or out.decode()
                raise AssertionError(
                    "distributed bring-up timed out; outputs:\n"
                    + "\n".join(f"--- proc {j}:\n{o[-2000:]}"
                                 for j, o in enumerate(outs))
                )
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}:\n{out[-2000:]}"
        assert f"PROC {i} OK" in out
    return outs


@_needs_toplevel_shard_map
@_needs_cpu_multiprocess
def test_two_process_distributed_psum():
    _run_children(_CHILD)


_TRAIN_CHILD = r'''
import os, sys
sys.path.insert(0, os.environ["MHO_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
from multihop_offload_tpu.parallel.mesh import (
    global_batch, init_distributed, make_mesh,
)

pid = int(sys.argv[1])
init_distributed(coordinator_address=os.environ["MHO_COORD"],
                 num_processes=2, process_id=pid)

import numpy as np
import jax.numpy as jnp
import __graft_entry__ as ge
from multihop_offload_tpu.agent import make_optimizer
from multihop_offload_tpu.config import Config
from multihop_offload_tpu.models import ChebNet
from multihop_offload_tpu.parallel.data_parallel import make_dp_train_step

devs = jax.devices()
assert len(devs) == 4, devs  # 2 processes x 2 local devices
mesh = make_mesh(data=4, graph=1, devices=devs)
# each process builds its OWN local episodes (different seeds) — true data
# parallelism across hosts, not replicated work
binst, bjobs, pad = ge._make_batch(num_cases=2, n_nodes=20, pad_round=8,
                                   dtype=np.float32, seed=100 + pid)
model = ChebNet(num_layer=3, hidden=8, param_dtype=jnp.float32)
variables = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((pad.e, 4), jnp.float32),
                       jnp.zeros((pad.e, pad.e), jnp.float32))
opt = make_optimizer(Config(learning_rate=1e-4))
opt_state = opt.init(variables["params"])
keys = jax.random.split(jax.random.PRNGKey(1 + pid), 2)
g_inst, g_jobs, g_keys = global_batch(mesh, (binst, bjobs, np.asarray(keys)))
step = make_dp_train_step(model, opt, mesh, mode="mean")
new_vars, new_opt, metrics = step(variables, opt_state, g_inst, g_jobs,
                                  g_keys, jnp.asarray(0.1, jnp.float32))
loss = float(jax.device_get(metrics["loss_critic"]))
assert np.isfinite(loss)
print(f"PROC {pid} LOSS {loss:.6f}", flush=True)
print(f"PROC {pid} OK", flush=True)
'''


@_needs_cpu_multiprocess
def test_two_process_data_parallel_training_step():
    """TRUE multi-host DP: each process contributes its OWN episodes into a
    4-device (2 processes x 2 devices) mesh via `global_batch`, one
    psum-mean update runs, and both processes agree on the cross-host
    loss — the scheme the reference's NCCL/MPI-equivalent would provide."""
    outs = _run_children(
        _TRAIN_CHILD, xla_flags="--xla_force_host_platform_device_count=2",
        timeout=400,
    )
    losses = [
        [ln for ln in out.splitlines() if "LOSS" in ln][-1].split()[-1]
        for out in outs
    ]
    # the psum-mean loss must be identical on every host (it aggregates
    # episodes only the other process holds)
    assert losses[0] == losses[1], losses

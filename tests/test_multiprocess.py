"""REAL multi-process distributed bring-up (not virtual devices).

Two OS processes join via `parallel.mesh.init_distributed`'s explicit
coordinator path (the framework's NCCL/MPI-equivalent entry, SURVEY.md
§5.8), see each other's devices globally, and run a cross-process `psum`
over a 2-device ('data',) mesh — the DCN collective path the multi-host
Trainer rides.  Each child also checks `jax.process_index()` (the
host-0 write gating the drivers rely on).
"""

import os
import socket
import subprocess
import sys

_CHILD = r'''
import os, sys
sys.path.insert(0, os.environ["MHO_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
from multihop_offload_tpu.parallel.mesh import init_distributed

pid = int(sys.argv[1])
idx = init_distributed(coordinator_address=os.environ["MHO_COORD"],
                       num_processes=2, process_id=pid)
assert idx == pid == jax.process_index(), (idx, pid, jax.process_index())

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

devs = jax.devices()
assert len(devs) == 2, f"expected 2 global devices, got {devs}"
mesh = Mesh(np.asarray(devs), ("data",))
f = jax.jit(shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                      in_specs=P(), out_specs=P(), check_vma=False))
out = float(f(jnp.asarray(float(pid + 1))))
assert out == 3.0, out  # 1 + 2 across processes
print(f"PROC {pid} OK psum={out}", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_psum():
    # bounded by the children's communicate(timeout=240) below
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "MHO_REPO": repo,
           "MHO_COORD": f"127.0.0.1:{_free_port()}",
           # children must pick their own platform; scrub inherited forcing
           "JAX_PLATFORMS": "",
           "XLA_FLAGS": ""}
    procs = [
        subprocess.Popen([sys.executable, "-c", _CHILD, str(i)], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)
    ]
    outs = ["", ""]
    try:
        for i, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=240)
                outs[i] = out.decode()
            except subprocess.TimeoutExpired:
                # a hang here usually means the OTHER process died early and
                # this one is waiting for it in initialize(); kill both and
                # surface every captured output so the root cause is visible
                for q in procs:
                    q.kill()
                for j, q in enumerate(procs):
                    out, _ = q.communicate()
                    outs[j] = outs[j] or out.decode()
                raise AssertionError(
                    "distributed bring-up timed out; outputs:\n"
                    + "\n".join(f"--- proc {j}:\n{o[-2000:]}"
                                for j, o in enumerate(outs))
                )
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}:\n{out[-2000:]}"
        assert f"PROC {i} OK" in out
